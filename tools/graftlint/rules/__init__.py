"""Rule registry: ALL_RULES is the suite ``python -m tools.graftlint``
runs. Order is the reporting order inside a line tie."""

from .gl001_donation import DonationAfterUse
from .gl002_locks import LockDiscipline
from .gl003_swallow import SilentSwallow
from .gl004_hostsync import HostSyncInHotPath
from .gl005_obsgate import ObsZeroOverhead
from .gl006_atomic import AtomicCommitDiscipline
from .gl007_faults import FaultHookPurity

ALL_RULES = (
    DonationAfterUse(),
    LockDiscipline(),
    SilentSwallow(),
    HostSyncInHotPath(),
    ObsZeroOverhead(),
    AtomicCommitDiscipline(),
    FaultHookPurity(),
)

RULE_DOCS = {r.id: r.title for r in ALL_RULES}
RULE_DOCS["GL000"] = "graftlint suppression without a reason"
