"""Rule registry: ALL_RULES is the suite ``python -m tools.graftlint``
runs. Order is the reporting order inside a line tie. GL001-GL007 are
single-file AST walks (GL001/GL003 resolve same-module helpers through
the call graph since ISSUE 10); GL008-GL011 run on the whole-repo
interprocedural engine (tools/graftlint/graph.py + flow.py)."""

from .gl001_donation import DonationAfterUse
from .gl002_locks import LockDiscipline
from .gl003_swallow import SilentSwallow
from .gl004_hostsync import HostSyncInHotPath
from .gl005_obsgate import ObsZeroOverhead
from .gl006_atomic import AtomicCommitDiscipline
from .gl007_faults import FaultHookPurity
from .gl008_deadline import DeadlineBudget
from .gl009_blocklock import BlockingUnderLock
from .gl010_lifecycle import ResourceLifecycle
from .gl011_codec import WireCodecSymmetry

ALL_RULES = (
    DonationAfterUse(),
    LockDiscipline(),
    SilentSwallow(),
    HostSyncInHotPath(),
    ObsZeroOverhead(),
    AtomicCommitDiscipline(),
    FaultHookPurity(),
    DeadlineBudget(),
    BlockingUnderLock(),
    ResourceLifecycle(),
    WireCodecSymmetry(),
)

RULE_DOCS = {r.id: r.title for r in ALL_RULES}
RULE_DOCS["GL000"] = "graftlint suppression without a reason"
