"""GL002 — lock discipline.

Two checks over classes that own a ``threading.Lock``/``RLock``/
``Condition``:

1. **Guarded-attribute inference.** Any ``self.X`` the class writes
   inside a ``with self.<lock>:`` block is lock-guarded; a write to the
   same attribute outside that lock (``__init__`` excepted — no second
   thread exists yet) is a finding. This is the discipline
   ``serving/server.py`` documents on ``_pending``/``_inflight``: the
   PR 5 failover work only stayed correct because every mutation of the
   in-flight bookkeeping happens under ``_lock``.

2. **Acquisition-order graph.** Every lexically nested
   ``with <lock A>: ... with <lock B>:`` contributes an A→B edge; a
   cycle in the per-package graph is a static deadlock candidate.
   ``FailoverServer._plock`` nests ``StreamServer._lock``
   (``serving/failover.py:promote``) — the day any code path acquires
   them in the other order, two threads deadlock. Nodes are keyed by
   attribute name within one top-level package directory (``serving/``,
   ``obs/``, ...): ``primary._lock`` IS ``StreamServer._lock``, which
   exactly the attr-name key captures.

The order graph is accumulated across modules by the runner calling
:meth:`check` per file; :meth:`finalize` reports cycles once per
package at the end (``run_lint`` drives this, and routes the findings
through the same suppression/baseline matching as per-file ones).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding, LintModule, Rule, call_name, dotted

_LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}


def _lock_expr_attr(node: ast.AST) -> Optional[str]:
    """The lock attribute name acquired by a with-item context expr:
    ``self._lock`` / ``primary._lock`` -> ``_lock``; bare module-level
    ``_lock`` -> ``_lock``. None for non-lock-shaped expressions."""
    name = dotted(node)
    if name is None:
        return None
    short = name.rsplit(".", 1)[-1]
    if "lock" in short.lower() or short in ("_mu", "_cond", "_condition"):
        return short
    return None


class LockDiscipline(Rule):
    id = "GL002"
    title = "unguarded write to a lock-guarded attribute / lock-order cycle"

    def __init__(self):
        # package -> list of (edge, module, node) accumulated across
        # check() calls; order_findings() consumes it
        self._edges: Dict[str, List[Tuple[Tuple[str, str], LintModule,
                                          ast.AST]]] = {}

    def check(self, mod: LintModule) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(mod, node)
        self._collect_edges(mod)

    # -- guarded attributes ------------------------------------------- #
    def _check_class(self, mod: LintModule, cls: ast.ClassDef
                     ) -> Iterator[Finding]:
        lock_attrs = self._own_locks(cls)
        if not lock_attrs:
            return
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        guarded: Set[str] = set()
        # pass 1: attributes written under any owned lock
        for m in methods:
            for w in self._with_lock_blocks(m, lock_attrs):
                for sub in ast.walk(w):
                    attr = self._self_attr_write(sub)
                    if attr is not None:
                        guarded.add(attr)
        guarded -= lock_attrs
        if not guarded:
            return
        # the ``_locked`` suffix is the repo's caller-holds-the-lock
        # contract (router.py documents it on ``_rebuild_merged_locked``
        # et al.): writes inside such a helper are exempt from pass 2,
        # and pass 3 makes the contract REAL by flagging any call site
        # that does not itself hold a lock (or carry the suffix)
        locked_helpers = {m.name for m in methods
                          if m.name.endswith("_locked")}
        # pass 2: writes to guarded attributes outside every owned lock
        for m in methods:
            if m.name == "__init__":
                continue  # no concurrent reader can exist yet
            if m.name in locked_helpers:
                continue  # caller holds the lock; pass 3 checks callers
            locked_nodes: Set[ast.AST] = set()
            for w in self._with_lock_blocks(m, lock_attrs):
                locked_nodes |= set(ast.walk(w))
            for sub in ast.walk(m):
                if sub in locked_nodes:
                    continue
                attr = self._self_attr_write(sub)
                if attr in guarded:
                    yield mod.finding(
                        "GL002", sub,
                        f"'{cls.name}.{attr}' is written under "
                        f"'self.{self._guard_name(cls, lock_attrs)}' "
                        f"elsewhere but written here without it "
                        f"(method '{m.name}')",
                    )
        # pass 3: every ``self.<helper>_locked(...)`` call must sit
        # inside a with-lock block or inside another ``_locked`` method
        # (the suffix composes) — otherwise the contract is a comment
        for m in methods:
            if m.name == "__init__" or m.name in locked_helpers:
                continue
            locked_nodes = set()
            for w in self._with_lock_blocks(m, lock_attrs):
                locked_nodes |= set(ast.walk(w))
            for sub in ast.walk(m):
                if not isinstance(sub, ast.Call) or sub in locked_nodes:
                    continue
                name = dotted(sub.func)
                if name is not None and name.startswith("self.") and \
                        name.split(".", 1)[1] in locked_helpers:
                    yield mod.finding(
                        "GL002", sub,
                        f"'{cls.name}.{name.split('.', 1)[1]}' is a "
                        f"'_locked'-contract helper but '{m.name}' "
                        f"calls it without holding "
                        f"'self.{self._guard_name(cls, lock_attrs)}'",
                    )

    @staticmethod
    def _guard_name(cls: ast.ClassDef, lock_attrs: Set[str]) -> str:
        return sorted(lock_attrs)[0] if len(lock_attrs) == 1 else \
            "/".join(sorted(lock_attrs))

    @staticmethod
    def _own_locks(cls: ast.ClassDef) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call) and \
                    call_name(node.value) in _LOCK_CTORS:
                for tgt in node.targets:
                    name = dotted(tgt)
                    if name is not None and name.startswith("self."):
                        out.add(name.split(".", 1)[1])
        return out

    @staticmethod
    def _with_lock_blocks(fn, lock_attrs: Set[str]) -> Iterator[ast.With]:
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    name = dotted(item.context_expr)
                    if name is not None and name.startswith("self.") and \
                            name.split(".", 1)[1] in lock_attrs:
                        yield node
                        break

    @staticmethod
    def _self_attr_write(node: ast.AST) -> Optional[str]:
        tgt = None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                name = dotted(t)
                if name is not None and name.startswith("self.") and \
                        name.count(".") == 1:
                    tgt = name.split(".", 1)[1]
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            name = dotted(node.target)
            if name is not None and name.startswith("self.") and \
                    name.count(".") == 1:
                tgt = name.split(".", 1)[1]
        return tgt

    # -- acquisition-order graph -------------------------------------- #
    def _package(self, mod: LintModule) -> str:
        parts = mod.relpath.split("/")
        return "/".join(parts[:-1]) if len(parts) > 1 else "."

    def _collect_edges(self, mod: LintModule) -> None:
        pkg = self._package(mod)
        withs = [n for n in ast.walk(mod.tree)
                 if isinstance(n, (ast.With, ast.AsyncWith))]
        for outer in withs:
            o = self._lock_of(outer)
            if o is None:
                continue
            for inner in ast.walk(outer):
                if inner is outer or not isinstance(
                        inner, (ast.With, ast.AsyncWith)):
                    continue
                i = self._lock_of(inner)
                if i is not None and i != o:
                    self._edges.setdefault(pkg, []).append(
                        ((o, i), mod, inner))

    @staticmethod
    def _lock_of(node) -> Optional[str]:
        for item in node.items:
            attr = _lock_expr_attr(item.context_expr)
            if attr is not None:
                return attr
        return None

    def finalize(self) -> Iterator[Finding]:
        return self.order_findings()

    def order_findings(self) -> Iterator[Finding]:
        """Cycle detection over the accumulated per-package graphs.
        Call after every module's :meth:`check` ran."""
        for pkg, entries in sorted(self._edges.items()):
            graph: Dict[str, Set[str]] = {}
            for (a, b), _, _ in entries:
                graph.setdefault(a, set()).add(b)
            cyc = _find_cycle(graph)
            if cyc is None:
                continue
            cyc_edges = set(zip(cyc, cyc[1:]))
            for (a, b), mod, node in entries:
                if (a, b) in cyc_edges:
                    yield mod.finding(
                        "GL002", node,
                        f"lock-order cycle in {pkg}/: "
                        + " -> ".join(cyc)
                        + " (this acquisition closes the loop; pick "
                        "ONE global order)",
                    )

    def reset(self) -> None:
        self._edges.clear()


def _find_cycle(graph: Dict[str, Set[str]]) -> Optional[List[str]]:
    """Any one cycle as [a, b, ..., a], else None."""
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph.get(n, ())):
            if color.get(m, WHITE) == GRAY:
                return stack[stack.index(m):] + [m]
            if color.get(m, WHITE) == WHITE:
                got = dfs(m)
                if got is not None:
                    return got
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            got = dfs(n)
            if got is not None:
                return got
    return None
