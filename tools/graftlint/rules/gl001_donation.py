"""GL001 — donation-after-use.

The shipped bug: ``CCServable._payload`` published an ALIAS of the
engine's carried summary while ``_superbatch_step`` donated that carry
to the next dispatch (``donate_argnums=(0,)``) — on TPU/GPU the dispatch
invalidates the donated buffer and every reader of the alias sees
garbage (fixed in the PR 3 hardening pass;
``aggregate/summary.py:_superbatch_step`` documents the discipline).

The invariant: a value passed at a donated position of a
``jax.jit(..., donate_argnums=...)`` callable is DEAD afterwards. This
rule finds, per module:

1. donating callables — ``@jax.jit``/``functools.partial(jax.jit, ...)``
   decorated defs with ``donate_argnums``, names bound to
   ``jax.jit(fn, donate_argnums=...)``, and names bound to a local
   factory whose ``return`` is such a ``jax.jit`` call (the
   ``library/pagerank.py:_build_pr_step`` shape);
2. call sites of those callables where a donated position receives a
   plain name (or tuple of names / dotted attribute);
3. any LOAD of that name after the call in the same function body with
   no intervening rebind. Rebinds on the call's own statement
   (``carry = step(carry, ...)``) are the blessed idiom and clear the
   name.

Linear-by-line within one function body: control flow is not modeled,
which is exactly the right paranoia level for buffers whose liveness
must be obvious to a reviewer anyway.

ISSUE 10 retrofit — the one-helper-call-away gap: a donated
``self.X`` read inside a helper METHOD called after the donating
dispatch (``self._publish()`` whose body loads ``self._summary``) used
to be invisible because the call site shows no load of the name. The
module-level call graph (:func:`tools.graftlint.graph.module_view`)
now resolves ``self.method`` calls and checks the callee's
``self``-attribute loads — one call level, same-module, honest
unresolved bucket beyond that.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from ..core import Finding, LintModule, Rule, call_name, dotted
from ..flow import summarize
from ..graph import module_view


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated positions from a jax.jit(...) call, None when the call
    does not donate. Non-literal donate_argnums (the conditional
    ``(0,) if donated else ()`` shape) conservatively reads as the
    positions of every integer literal found inside the expression."""
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            ints = [n.value for n in ast.walk(kw.value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int)
                    and not isinstance(n.value, bool)]
            return tuple(sorted(set(ints))) if ints else (0,)
    return None


def _is_jax_jit(call: ast.Call) -> bool:
    name = call_name(call)
    return name in ("jax.jit", "jit")


def _jit_call_in(node: ast.AST) -> Optional[ast.Call]:
    """The jax.jit(...) call expressed by ``node``: the call itself, or
    ``functools.partial(jax.jit, ...)``."""
    if not isinstance(node, ast.Call):
        return None
    if _is_jax_jit(node):
        return node
    name = call_name(node)
    if name in ("functools.partial", "partial") and node.args:
        first = node.args[0]
        if isinstance(first, (ast.Name, ast.Attribute)) and \
                dotted(first) in ("jax.jit", "jit"):
            return node
    return None


class DonationAfterUse(Rule):
    id = "GL001"
    title = "donated jit buffer read after the donating dispatch"

    def check(self, mod: LintModule) -> Iterator[Finding]:
        donating = self._collect_donating(mod)
        if not donating:
            return
        for fn in ast.walk(mod.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(mod, fn, donating)

    # -- pass 1: who donates ------------------------------------------ #
    def _collect_donating(self, mod: LintModule
                          ) -> Dict[str, Tuple[int, ...]]:
        """name -> donated positions. Keys are bare callable names; an
        attribute call ``self._step(...)`` matches on ``_step``."""
        donating: Dict[str, Tuple[int, ...]] = {}
        factories: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    jit = _jit_call_in(dec)
                    if jit is None:
                        continue
                    pos = _donate_positions(jit)
                    if pos is not None:
                        donating[node.name] = pos
                # factory shape: `return jax.jit(fn, donate_argnums=..)`
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Return) and \
                            isinstance(sub.value, ast.Call) and \
                            _is_jax_jit(sub.value):
                        pos = _donate_positions(sub.value)
                        if pos is not None:
                            factories[node.name] = pos
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                jit = _jit_call_in(node.value)
                pos = None
                if jit is not None:
                    pos = _donate_positions(jit)
                else:  # name = donating_factory(...)
                    fac = call_name(node.value)
                    if fac is not None:
                        pos = factories.get(fac.rsplit(".", 1)[-1])
                if pos is None:
                    continue
                for tgt in node.targets:
                    name = dotted(tgt)
                    if name is not None:
                        donating[name.rsplit(".", 1)[-1]] = pos
        # second sweep: assignments from factories defined later in the
        # module than the assignment (class bodies above helpers)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                fac = call_name(node.value)
                if fac is None:
                    continue
                pos = factories.get(fac.rsplit(".", 1)[-1])
                if pos is None:
                    continue
                for tgt in node.targets:
                    name = dotted(tgt)
                    if name is not None:
                        donating.setdefault(name.rsplit(".", 1)[-1], pos)
        return donating

    # -- pass 2: donated-name liveness -------------------------------- #
    def _check_function(self, mod: LintModule, fn, donating
                        ) -> Iterator[Finding]:
        own_nested = {
            n for sub in ast.walk(fn)
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
            and sub is not fn
            for n in ast.walk(sub)
        }

        calls: List[Tuple[ast.Call, str, Set[str]]] = []
        loads: List[Tuple[str, ast.AST]] = []
        stores: List[Tuple[str, int]] = []
        for node in ast.walk(fn):
            if node in own_nested:
                continue  # nested defs have their own timeline
            if isinstance(node, ast.Call):
                cname = call_name(node)
                if cname is None:
                    continue
                short = cname.rsplit(".", 1)[-1]
                pos = donating.get(short)
                if pos is None:
                    continue
                donated: Set[str] = set()
                for p in pos:
                    if p < len(node.args):
                        donated |= self._arg_names(node.args[p])
                if donated:
                    calls.append((node, short, donated))
            elif isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    loads.append((node.id, node))
                else:
                    stores.append((node.id, node.lineno))
            elif isinstance(node, ast.Attribute):
                name = dotted(node)
                if name is None:
                    continue
                if isinstance(node.ctx, ast.Load):
                    # only track full dotted loads we might have donated
                    loads.append((name, node))
                else:
                    stores.append((name, node.lineno))

        for call, cname, donated in calls:
            # the rebind window is the whole enclosing STATEMENT: a
            # multi-line tuple assign puts its targets on lines before
            # the call ((a, b) = f(a, b) spanning lines)
            stmt = call
            for anc in mod.ancestors(call):
                if isinstance(anc, ast.stmt):
                    stmt = anc
                    break
            start = stmt.lineno
            end = getattr(stmt, "end_lineno", stmt.lineno)
            # a rebind on the call's own statement (carry = f(carry))
            rebound_here = {n for n, ln in stores
                            if start <= ln <= end}
            for name in sorted(donated - rebound_here):
                hit = self._first_live_load(
                    name, end, loads, stores, call)
                if hit is not None:
                    yield mod.finding(
                        "GL001", hit,
                        f"'{name}' was donated to '{cname}' "
                        f"(donate_argnums) and read again — the "
                        f"dispatch invalidates the buffer on "
                        f"TPU/GPU; copy before donating or rebind "
                        f"from the call result",
                    )
                elif name.startswith("self.") and name.count(".") == 1:
                    yield from self._helper_reads(
                        mod, fn, name, cname, end, stores)

    def _helper_reads(self, mod: LintModule, fn, name: str,
                      cname: str, end: int, stores
                      ) -> Iterator[Finding]:
        """The retrofit: a donated ``self.X`` loaded inside a helper
        method called after the dispatch, with no intervening rebind
        of ``self.X`` before the helper call."""
        view = module_view(mod)
        attr = name.split(".", 1)[1]
        owner = view.owner_of(fn)
        if owner is None:
            return
        for call, target in view.calls_in(owner):
            line = getattr(call, "lineno", 0)
            if line <= end or target is None:
                continue
            killed = any(s == name and end < ln < line
                         for s, ln in stores)
            if killed:
                continue
            tsum = summarize(view, target)
            if attr in tsum.self_attr_loads:
                yield mod.finding(
                    "GL001", call,
                    f"'{name}' was donated to '{cname}' "
                    f"(donate_argnums) and '{target.qualname}' "
                    f"called afterwards reads it — the dispatch "
                    f"invalidates the buffer on TPU/GPU; copy "
                    f"before donating or rebind before the call",
                )
                return

    @staticmethod
    def _arg_names(arg: ast.AST) -> Set[str]:
        """Names donated by one argument expression: a bare name, a
        dotted attribute, or a tuple/list of those."""
        out: Set[str] = set()
        items = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) \
            else [arg]
        for item in items:
            name = dotted(item)
            if name is not None:
                out.add(name)
        return out

    @staticmethod
    def _first_live_load(name: str, after_line: int, loads, stores,
                         call: ast.Call) -> Optional[ast.AST]:
        """The first load of ``name`` strictly after ``after_line`` not
        preceded by an intervening store. Loads that are part of the
        donating call expression itself do not count."""
        in_call = set(ast.walk(call))
        candidates = sorted(
            (node.lineno, node) for n, node in loads
            if n == name and node.lineno > after_line
            and node not in in_call
        )
        for line, node in candidates:
            # strictly-before only: in `x = g(x)` the load on the RHS
            # executes before the store rebinds, so a same-line store
            # does not save it
            killed = any(s == name and after_line < ln < line
                         for s, ln in stores)
            if killed:
                return None
            return node
        return None
