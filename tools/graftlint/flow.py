"""Per-function dataflow facts for the interprocedural rules.

One :class:`Summary` per function, computed on demand and CACHED on the
:class:`~tools.graftlint.graph.RepoGraph` (the whole self-run builds
each summary once — the 30s CI budget is a hard constraint). A summary
is deliberately shallow: linear, statement-ordered facts about ONE
function body, the same paranoia level as GL001's liveness walk —
control flow is not modeled, and every classifier here errs toward
silence (an unknown shape is an unknown, not a finding).

What rules read out of a summary:

- **blocking ops** (GL009): direct calls that can block the calling
  thread — ``time.sleep``, socket ``send/sendall/recv/accept/connect``,
  ``open``, thread-shaped ``.join()``, and UNTIMED ``.get()``/
  ``.wait()`` (zero-argument; a timed wait is a different, bounded
  contract — and ``Condition.wait(t)`` under its own condition lock is
  the idiom, not a bug).
- **time-passing ops** (GL008): the subset of blocking ops plus
  deadline-spending sinks — after one of these fires, a function's
  original deadline budget is no longer the remaining budget.
- **evidence** (GL003 retrofit): the body counts a registry event,
  records a rejection, or re-raises.
- **self-attribute loads** (GL001 retrofit): ``self.X`` reads anywhere
  in the body — what a donated-buffer read hidden behind a helper call
  looks like from the caller.
- **lock acquisitions** (GL009's interprocedural order edges) and
  **codec facts** (GL011: constant dict keys written/read, whether the
  decoded object escapes).

Taint here is reaching-definitions at its simplest: a parameter is RAW
at a use iff the function never rebinds that name (any assignment —
including a clamp — kills the taint; the bias is silence).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .core import call_name, dotted, last_attr
from .graph import FunctionInfo, RepoGraph

#: parameter names that carry a deadline/timeout budget (GL008).
#: ``join_timeout_s`` is the ingest reader-drain vocabulary (ISSUE 18):
#: a per-shard close() that hands the same budget to every join would
#: multiply the caller's wait by the shard count.
#: ``split_boot_timeout_s`` is the elastic-resharding vocabulary
#: (ISSUE 19): the budget a split child gets to restore the parent's
#: snapshot and publish its address — a copy that never reaches the
#: store's bounded wait hangs the storm's SPLIT phase forever.
DEADLINE_PARAMS = frozenset({
    "deadline_s", "deadline", "timeout", "timeout_s", "budget_s",
    "join_timeout_s", "split_boot_timeout_s",
})

#: dict keys that carry a deadline across a wire/frame boundary
DEADLINE_KEYS = DEADLINE_PARAMS

#: attribute calls that SPEND a wall-clock budget passed as their
#: argument: thread/process joins, future results, bounded waits,
#: closes with a drain timeout
SPEND_ATTRS = frozenset({"join", "wait", "result", "close", "acquire"})

#: socket attribute calls that block the calling thread
_SOCKET_ATTRS = frozenset({
    "recv", "recv_into", "accept", "sendall", "send", "connect",
    "makefile",
})

#: registry-evidence calls (same set GL003 matches inline)
_EVIDENCE_CALLS = frozenset({
    "counter", "gauge", "histogram", "record_rejection",
})

_LOCKISH = ("lock", "_mu", "_cond", "_condition", "wlock", "plock")


def lock_attr_of(expr: ast.AST) -> Optional[str]:
    """The lock attribute acquired by a with-item context expression
    (``self._lock`` / ``primary._plock`` -> attr name), else None."""
    name = dotted(expr)
    if name is None:
        return None
    short = name.rsplit(".", 1)[-1]
    low = short.lower()
    if any(t in low for t in _LOCKISH):
        return short
    return None


def _receiver_dotted(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return dotted(call.func.value)
    return None


def _is_thread_join(call: ast.Call) -> bool:
    """``X.join(...)`` that is thread/process-shaped: zero args (string
    ``sep.join`` always takes one), or a receiver whose name says
    thread/proc, with a numeric/name timeout. ``os.path.join`` and
    string joins never match."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr == "join"):
        return False
    recv = _receiver_dotted(call)
    if recv is not None and recv.startswith("os.path"):
        return False
    if not call.args and not call.keywords:
        return True
    if len(call.args) == 1 and recv is not None:
        low = recv.lower()
        if "thread" in low or "proc" in low or low.endswith("_t"):
            return True
        if isinstance(call.args[0], (ast.Constant, ast.Name)) and not (
            isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
        ):
            # join(<number or timeout name>): str.join takes an
            # iterable, never a number/timeout — thread-shaped
            return "".join(low.split("_")) != "ospath"
    return False


def blocking_kind(call: ast.Call) -> Optional[str]:
    """Classify a call as a thread-blocking operation (GL009's sink
    set), or None. Timed ``.get(t)``/``.wait(t)`` are NOT classified —
    only the untimed forever-blocking forms are."""
    name = call_name(call)
    if name in ("time.sleep", "sleep"):
        return "time.sleep()"
    if name in ("socket.create_connection", "_socket.create_connection",
                "create_connection"):
        return "socket connect"
    if name == "open":
        return "open()"
    if isinstance(call.func, ast.Attribute):
        a = call.func.attr
        if a in _SOCKET_ATTRS:
            return f"socket .{a}()"
        if _is_thread_join(call):
            return ".join()"
        if a in ("get", "wait") and not call.args and not call.keywords:
            return f"untimed .{a}()"
    return None


def time_passing_kind(call: ast.Call) -> Optional[str]:
    """GL008's 'the budget is being spent' set: every blocking op plus
    any timed spend (``.join(t)``/``.wait(t)``/``.result(t)``). The
    first argument must be timeout-shaped (a number, name, attribute,
    or expression — never a string/iterable), so ``os.path.join`` and
    ``sep.join(parts)`` stay out."""
    kind = blocking_kind(call)
    if kind is not None:
        return kind
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in SPEND_ATTRS and call.args:
        if call.func.attr == "join" and not _is_thread_join(call):
            return None
        a0 = call.args[0]
        if isinstance(a0, ast.Constant) and not isinstance(
                a0.value, (int, float)):
            return None
        if isinstance(a0.value if isinstance(a0, ast.Constant)
                      else None, bool):
            return None
        if not isinstance(a0, (ast.Constant, ast.Name, ast.Attribute,
                               ast.BinOp, ast.Call, ast.IfExp)):
            return None
        return f".{call.func.attr}(timeout)"
    return None


@dataclass
class Summary:
    """Linear facts about one function body (nested defs excluded)."""

    info: FunctionInfo
    calls: List[Tuple[ast.Call, Optional[str]]] = field(
        default_factory=list)  # (node, dotted name)
    blocking: List[Tuple[str, ast.Call]] = field(default_factory=list)
    time_passing: List[Tuple[str, ast.Call]] = field(
        default_factory=list)
    stores: Dict[str, List[int]] = field(default_factory=dict)
    evidence: bool = False
    self_attr_loads: Set[str] = field(default_factory=set)
    self_attr_stores: Dict[str, List[int]] = field(default_factory=dict)
    lock_acquires: List[Tuple[str, ast.AST]] = field(
        default_factory=list)  # (lock attr, With node)
    # -- codec facts (GL011) ------------------------------------------- #
    #: constant keys written into locally-built dicts, key -> node
    dict_key_writes: Dict[str, ast.AST] = field(default_factory=dict)
    #: constant keys read strictly (``doc["k"]``), key -> node
    dict_key_strict_reads: Dict[str, ast.AST] = field(
        default_factory=dict)
    #: constant keys read tolerantly (``doc.get("k")`` / ``"k" in doc``)
    dict_key_tolerant_reads: Set[str] = field(default_factory=set)
    #: names of local vars holding a deserialized doc (json/pickle
    #: loads / unwrap result), and how they leave the function:
    #: returned whole (callers' reads then count, one level) vs passed
    #: on to another call (beyond one level — tolerant by construction)
    decoded_vars: Set[str] = field(default_factory=set)
    decoded_returned: bool = False
    decoded_passed: bool = False
    #: the function deserializes (loads-shaped) / serializes
    decodes: bool = False
    encodes: bool = False
    #: module-level ALL_CAPS constants referenced + module-local helper
    #: calls — GL011's pairing evidence
    const_refs: Set[str] = field(default_factory=set)

    def param_is_raw_at(self, name: str) -> bool:
        """True when parameter ``name`` is never rebound in this body —
        the conservative 'the original value is what every use sees'."""
        return name not in self.stores

    def deadline_params(self) -> Tuple[str, ...]:
        return tuple(p for p in
                     self.info.params + self.info.kwonly
                     if p in DEADLINE_PARAMS)


def _nested_nodes(fn) -> set:
    return {
        n for sub in ast.walk(fn)
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef))
        and sub is not fn
        for n in ast.walk(sub)
    }


def summarize(graph: RepoGraph, info: FunctionInfo) -> Summary:
    """Build (or fetch) the summary for one function."""
    cached = graph._summary_cache.get(info.key)
    if cached is not None:
        return cached
    s = Summary(info)
    fn = info.node
    nested = _nested_nodes(fn)
    # annotation subtrees: `-> Optional["X"]` is a Subscript with a
    # string slice — type syntax, never a dict read
    anns: set = set()
    for node in ast.walk(fn):
        for sub in getattr(node, "annotation", None), \
                getattr(node, "returns", None):
            if sub is not None:
                anns |= set(ast.walk(sub))
        if isinstance(node, ast.arg) and node.annotation is not None:
            anns |= set(ast.walk(node.annotation))
    # pre-pass: decoded-var names must exist before the main walk sees
    # any use (ast.walk is breadth-first; a shallow `return doc` visits
    # before the deeper `doc = loads(...)`)
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and node not in nested:
            _note_assign(s, node)
    for node in ast.walk(fn):
        if node in nested or node in anns:
            continue
        if isinstance(node, ast.Call):
            _note_call(s, node)
        elif isinstance(node, ast.Name):
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                s.stores.setdefault(node.id, []).append(node.lineno)
            elif node.id.isupper() and len(node.id) > 2:
                s.const_refs.add(node.id)
        elif isinstance(node, ast.Attribute):
            name = dotted(node)
            if name is not None and name.startswith("self.") and \
                    name.count(".") == 1:
                attr = name.split(".", 1)[1]
                if isinstance(node.ctx, ast.Load):
                    s.self_attr_loads.add(attr)
                else:
                    s.self_attr_stores.setdefault(attr, []).append(
                        node.lineno)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                attr = lock_attr_of(item.context_expr)
                if attr is not None:
                    s.lock_acquires.append((attr, node))
                    break
        elif isinstance(node, ast.Raise):
            s.evidence = True
        elif isinstance(node, ast.Subscript):
            _note_subscript(s, node)
        elif isinstance(node, ast.Compare):
            # "k" in doc -> tolerant read
            if len(node.ops) == 1 and isinstance(node.ops[0], ast.In) \
                    and isinstance(node.left, ast.Constant) \
                    and isinstance(node.left.value, str):
                s.dict_key_tolerant_reads.add(node.left.value)
        elif isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str):
                    s.dict_key_writes.setdefault(k.value, k)
        elif isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Name) and \
                    node.value.id in s.decoded_vars:
                s.decoded_returned = True
            elif isinstance(node.value, ast.Call):
                fname = last_attr(call_name(node.value))
                if fname in ("loads", "load"):
                    s.decoded_returned = True
    # a decoded var handed onward whole (passed as an argument to
    # something other than a read/validate helper)
    if s.decoded_vars:
        for node in ast.walk(fn):
            if node in nested or not isinstance(node, ast.Call):
                continue
            fname = last_attr(call_name(node))
            for arg in node.args:
                if isinstance(arg, ast.Name) and \
                        arg.id in s.decoded_vars and \
                        fname not in ("get", "isinstance", "len",
                                      "loads", "int", "float", "str"):
                    s.decoded_passed = True
    graph._summary_cache[info.key] = s
    return s


def _note_call(s: Summary, node: ast.Call) -> None:
    name = call_name(node)
    s.calls.append((node, name))
    kind = blocking_kind(node)
    if kind is not None:
        s.blocking.append((kind, node))
    tkind = time_passing_kind(node)
    if tkind is not None:
        s.time_passing.append((tkind, node))
    fname = node.func.attr if isinstance(node.func, ast.Attribute) \
        else last_attr(name)
    if fname in _EVIDENCE_CALLS:
        s.evidence = True
    if fname in ("dumps", "dump", "wrap_checksummed", "pack"):
        s.encodes = True
    if fname in ("loads", "load", "unwrap_checksummed", "unpack"):
        s.decodes = True
    if fname == "get" and isinstance(node.func, ast.Attribute) and \
            node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        s.dict_key_tolerant_reads.add(node.args[0].value)


def _note_subscript(s: Summary, node: ast.Subscript) -> None:
    sl = node.slice
    if not (isinstance(sl, ast.Constant) and isinstance(sl.value, str)):
        return
    if isinstance(node.ctx, ast.Store):
        s.dict_key_writes.setdefault(sl.value, node)
    else:
        s.dict_key_strict_reads.setdefault(sl.value, node)


def _note_assign(s: Summary, node: ast.Assign) -> None:
    v = node.value
    fname = None
    if isinstance(v, ast.Call):
        fname = v.func.attr if isinstance(v.func, ast.Attribute) \
            else last_attr(call_name(v))
    if fname in ("loads", "load", "from_wire", "read_dump"):
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                s.decoded_vars.add(tgt.id)


def blocking_reach(graph: RepoGraph, info: FunctionInfo
                   ) -> Optional[Tuple[str, Tuple[str, ...]]]:
    """Transitive: the first blocking op reachable from ``info``
    through RESOLVED calls — ``(op kind, call chain)`` or None."""

    def pred(fi: FunctionInfo) -> Optional[str]:
        s = summarize(graph, fi)
        return s.blocking[0][0] if s.blocking else None

    return graph.reaches(info, pred)
