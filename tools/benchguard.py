"""benchguard: an automated eye on the committed perf trajectory.

The repo commits bench artifacts (``BENCH_SERVING_RPC_CPU.json`` et
al.) but until now nothing COMPARED a fresh run against them — a
serving-latency regression only surfaced when a human re-read the
numbers. This tool is the smallest honest checker (stdlib only, like
graftlint): it takes the committed artifact and a fresh run of the same
scenario and fails when a watched latency metric regressed past a
GENEROUS ratio.

The ratio is deliberately loose (default 3.0x): CI hosts are shared and
noisy, and the committed numbers come from a different machine — this
gate exists to catch "p99 went from 100ms to a second", not to litigate
10%. It is wired as a NON-BLOCKING CI step for the same reason: a red
benchguard is a prompt to look, not a merge stopper.

Watched metrics default to the serving-RPC artifact's
(``steady.p50_ms``/``steady.p99_ms`` — the steady-state client-measured
batch latency); ``--watch`` overrides the list for other artifacts —
the CI chaos step passes ``--watch recovery_s.p50`` against
``BENCH_CHAOS_CPU.json`` (supervisor-measured recovery latency, the
resilience layer's own p50), and the ingest step passes
``--watch min:cells.c4_binary.eps`` against ``BENCH_INGEST_CPU.json``:
the ``min:`` prefix marks a THROUGHPUT metric, whose regression
direction is downward (fresh must stay >= committed / ratio). The promotion window is NOT guarded: its
latency is dominated by the configured lease timeout, which is a
correctness parameter, not a perf trajectory. ``resume_wall_s`` is not
guarded either — it is dominated by interpreter/jax boot, a hosting
property.

Usage::

    python -m tools.benchguard --committed BENCH_SERVING_RPC_CPU.json \
        --fresh /tmp/fresh.json [--ratio 3.0] \
        [--watch steady.p50_ms,steady.p99_ms]

Exit codes: 0 within bounds, 1 regression, 2 usage/unreadable input.
"""

from __future__ import annotations

import json
import sys
from typing import List, Optional, Tuple

#: dotted paths of the guarded metrics inside the artifact document
WATCHED = ("steady.p50_ms", "steady.p99_ms")

#: the chaos-sweep artifact's guarded metric (BENCH_CHAOS_CPU.json)
WATCHED_CHAOS = ("recovery_s.p50",)

#: the ingest artifact's guarded metric (BENCH_INGEST_CPU.json):
#: throughput, so HIGHER is better — the ``min:`` prefix flips the
#: bound direction (fresh must stay above committed / ratio)
WATCHED_INGEST = ("min:cells.c4_binary.eps",)

#: the latency-curve artifact's guarded cells (BENCH_LATENCY_CPU.json):
#: the fused group-fold throughput at the 1024-edge cliff window, per
#: algorithm that declares a group fold (ISSUE 14) — all throughput,
#: so ``min:`` direction (regression is downward). The per-window
#: columns are NOT guarded: they exist as the cliff baseline the fused
#: cells are measured against, not as a trajectory anyone defends.
WATCHED_LATENCY = (
    "min:points.1024.superbatch.eps",
    "min:algos.pagerank.1024.superbatch.eps",
    "min:algos.bipartiteness.1024.superbatch.eps",
)

#: the autotune artifact's guarded cells (BENCH_AUTOTUNE_CPU.json):
#: the controller's throughput on the cliff cell (``min:`` — a
#: regression means the controller started LOSING to the hand-tuned
#: constant) and its ratio against the hand cell measured in the same
#: run (also ``min:``: the ratio is the artifact's own honesty check,
#: so the watch survives the box getting faster or slower overall).
#: ...plus the negative control (ROADMAP 5b / ISSUE 16): on the
#: fixpoint-bound PageRank parity cell, auto-K must HOLD K=1 —
#: ``auto.k_final`` is watched in the latency direction (committed 1;
#: a controller that converges to the next rung, 4, breaches the 3.0x
#: bound), and the auto-vs-pinned throughput ratio in the ``min:``
#: direction (paying for fusion that buys nothing drags it down).
WATCHED_AUTOTUNE = (
    "min:cells.cc_1024.auto.eps",
    "min:cells.cc_1024.ratio_vs_hand",
    "cells.pagerank_hold.auto.k_final",
    "min:cells.pagerank_hold.ratio_vs_pinned",
)

#: the sharded-serving artifact's guarded metrics
#: (BENCH_SERVING_SHARDED_CPU.json): the cached routing tier's
#: aggregate Zipfian QPS is throughput (``min:`` — regression is
#: downward), its steady cache-on p99 is latency (regression upward).
#: The kill/promotion columns are NOT guarded: their latency is
#: dominated by the configured lease timeout, a correctness parameter.
#: The churn cell (ISSUE 17) guards the delta-pull protocol's two
#: headline ratios in the ``min:`` direction — a regression means a
#: delta refresh started costing byte- or merge-wise like a full
#: re-pull again. The absolute per-refresh columns are NOT guarded:
#: they move with geometry, the ratios are the claim.
WATCHED_SHARDED = (
    "min:headline.qps",
    "zipf.cache_on.p99_ms",
    "min:churn.bytes_x",
    "min:churn.merge_x",
)

#: the transport-fabric artifact's guarded cells
#: (BENCH_TRANSPORT_CPU.json, ISSUE 16): per-backend store round-trip
#: throughput (``min:`` — a regression means the exchange machinery
#: itself got slower) and the 2-rank allgather p50 (latency, regression
#: upward) on both locally-runnable backends. The recovery columns are
#: NOT guarded: kill/relaunch wall time is dominated by interpreter
#: boot + polling cadence, both configuration, not code.
WATCHED_TRANSPORT = (
    "min:backends.shared_dir.store.ops_per_s",
    "min:backends.socket.store.ops_per_s",
    "backends.shared_dir.exchange.p50_ms",
    "backends.socket.exchange.p50_ms",
)

#: the event-time artifact's guarded cells (BENCH_EVENTTIME_CPU.json,
#: ISSUE 18): end-to-end sliding throughput (``min:`` — watermarks,
#: pane assembly, retraction, all three summaries in the loop) and the
#: retraction cell's economic claim itself (``min:`` — repair seconds
#: saved per rebuild second; a drop below 1.0 means bounded repair
#: stopped beating the from-scratch rebuild it exists to beat). The
#: mismatch count is asserted zero INSIDE bench.py, not bounded here.
WATCHED_EVENTTIME = (
    "min:cells.sliding.eps",
    "min:cells.retract.ratio_vs_rebuild",
)

#: the failover-storm artifact's guarded cells (BENCH_STORM_CPU.json,
#: ISSUE 19): client-visible QPS through the WHOLE storm — router
#: kill, shard kill, live split, retunes — is throughput (``min:`` —
#: a regression means elasticity started costing the clients), the
#: zero-failures contract rides as a 1/0 indicator in the same
#: direction (compare() skips a committed 0, so the raw failure count
#: cannot gate; the indicator can — a fresh 0 fails the 1/3 bound),
#: and the two kill phases' client p50 are the recovery latencies
#: (regression upward). The split phase's p99 is NOT guarded: it is
#: dominated by the child's snapshot restore, which scales with
#: geometry, not code.
WATCHED_STORM = (
    "min:load_total.qps",
    "min:load_total.zero_failures",
    "load.kill_router.p50_ms",
    "load.kill_shard.p50_ms",
    # the transactional lane (ISSUE 20): the zero-consistency-
    # violations contract rides as the same 1/0 indicator shape
    # (zero repeated-read/oracle violations AND >=1 committed txn
    # spanning each chaos phase), and pinned-read throughput is
    # guarded like any other throughput cell
    "min:txn.zero_violations",
    "min:txn.qps",
)

#: a fresh value may be up to this many times the committed one
DEFAULT_RATIO = 3.0


def dig(doc: dict, dotted: str):
    """``dig({"a": {"b": 1}}, "a.b") -> 1``; None when any hop is
    missing or not a mapping."""
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict):
            return None
        cur = cur.get(part)
    return cur


def compare(
    committed: dict,
    fresh: dict,
    ratio: float = DEFAULT_RATIO,
    watched: Tuple[str, ...] = WATCHED,
) -> List[dict]:
    """Per-metric verdicts: ``{"metric", "committed", "fresh", "bound",
    "ok", "note"}``. A metric missing from either side is reported
    (``ok=None``, a skip) rather than failed — an artifact-shape change
    must read as 'benchguard needs updating', not as a perf regression.
    A committed value of 0 cannot bound anything and also skips.

    Latency-shaped metrics (the default) regress UPWARD: fresh must stay
    at or below ``committed * ratio``. A metric spelled with a ``min:``
    prefix (throughput — the ingest eps cells) regresses DOWNWARD:
    fresh must stay at or above ``committed / ratio``."""
    out = []
    for metric in watched:
        lower_bound = metric.startswith("min:")
        path = metric[4:] if lower_bound else metric
        want = dig(committed, path)
        got = dig(fresh, path)
        entry = {"metric": metric, "committed": want, "fresh": got,
                 "bound": None, "ok": None, "note": ""}
        if not isinstance(want, (int, float)) or \
                not isinstance(got, (int, float)):
            entry["note"] = "missing on one side; skipped"
        elif want <= 0:
            entry["note"] = "committed value is 0; nothing to bound"
        elif lower_bound:
            bound = want / ratio
            entry["bound"] = round(bound, 3)
            entry["ok"] = bool(got >= bound)
            if not entry["ok"]:
                entry["note"] = (
                    f"{got:.3f} < {bound:.3f} "
                    f"({got / want:.2f}x the committed {want:.3f})"
                )
        else:
            bound = want * ratio
            entry["bound"] = round(bound, 3)
            entry["ok"] = bool(got <= bound)
            if not entry["ok"]:
                entry["note"] = (
                    f"{got:.3f} > {bound:.3f} "
                    f"({got / want:.2f}x the committed {want:.3f})"
                )
        out.append(entry)
    return out


def _load(path: str) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError) as e:
        print(f"benchguard: cannot read {path}: {e}", file=sys.stderr)
        return None


def _take(argv: List[str], flag: str) -> Optional[str]:
    for i, a in enumerate(argv):
        if a == flag:
            if i + 1 >= len(argv):
                print(f"benchguard: {flag} needs a value",
                      file=sys.stderr)
                raise SystemExit(2)
            v = argv[i + 1]
            del argv[i:i + 2]
            return v
        if a.startswith(flag + "="):
            del argv[i]
            return a[len(flag) + 1:]
    return None


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    committed_path = _take(argv, "--committed")
    fresh_path = _take(argv, "--fresh")
    ratio_raw = _take(argv, "--ratio")
    watch_raw = _take(argv, "--watch")
    if committed_path is None or fresh_path is None or argv:
        print(
            "usage: python -m tools.benchguard --committed <artifact> "
            "--fresh <artifact> [--ratio 3.0] "
            "[--watch metric.a,metric.b]",
            file=sys.stderr,
        )
        return 2
    try:
        ratio = float(ratio_raw) if ratio_raw is not None \
            else DEFAULT_RATIO
    except ValueError:
        print(f"benchguard: --ratio wants a number, got {ratio_raw!r}",
              file=sys.stderr)
        return 2
    watched = WATCHED
    if watch_raw is not None:
        watched = tuple(
            m.strip() for m in watch_raw.split(",") if m.strip())
        if not watched:
            print("benchguard: --watch wants a comma-separated metric "
                  "list", file=sys.stderr)
            return 2
    committed = _load(committed_path)
    fresh = _load(fresh_path)
    if committed is None or fresh is None:
        return 2
    verdicts = compare(committed, fresh, ratio, watched)
    worst = 0
    for v in verdicts:
        state = ("SKIP" if v["ok"] is None
                 else "ok" if v["ok"] else "REGRESSED")
        line = (f"benchguard: {v['metric']}: committed={v['committed']} "
                f"fresh={v['fresh']} bound={v['bound']} [{state}]")
        if v["note"]:
            line += f" — {v['note']}"
        print(line)
        if v["ok"] is False:
            worst = 1
    print(f"benchguard: {'REGRESSION' if worst else 'within bounds'} "
          f"(ratio {ratio}x, {len(verdicts)} metrics)")
    return worst


if __name__ == "__main__":
    sys.exit(main())
