"""Periodic auto-checkpointing: the Flink-transparent restore analog.

The reference inherits fault tolerance from Flink: ``Merger implements
ListCheckpointed`` (``SummaryAggregation.java:127-135``) — the runtime
snapshots the running summary on every checkpoint barrier and, on
failover, restores it and replays the source from the checkpointed
offset. The repo's manual surface (``aggregate/checkpoint.py``) covers
the snapshot; this driver adds the BARRIER and the RESUME so a killed
process restarts and finishes with output identical to an uninterrupted
run (round-3 verdict #7 / missing-item #2):

- every ``every`` windows, :class:`AutoCheckpoint` atomically writes ONE
  file (state + vertex dictionary + windows_done) via write-temp +
  ``os.replace`` — a kill mid-snapshot leaves the previous barrier
  intact;
- on restart, the state restores and the replayed source fast-forwards
  by the recorded window count. The skipped windows still flow through
  the vertex dictionary (replay is idempotent: first-seen ordinal
  compaction assigns identical compact ids on identical prefixes), so
  ids assigned after resume continue exactly where the checkpoint left
  off.

Works for both carried-state workloads (``state_dict``/
``load_state_dict``: triangles, PageRank, spanner, samplers, SAGE,
matching, degrees) and engine aggregations (``snapshot_state``/
``restore_state``: CC, bipartiteness, ...). The driver is the analog of
Flink's checkpoint coordinator, not of its exactly-once sink protocol:
emissions between the last barrier and a kill are re-emitted after
resume, exactly like Flink's at-least-once outputs without transactional
sinks.

SUPERBATCH GRANULARITY: when the work runs with ``superbatch=K > 1``
(``SummaryAggregation``), K windows execute as one fused scan dispatch
and the carried summary is only observable on group boundaries —
between a group's yields, ``snapshot_state()`` would capture the
END-of-group summary while ``windows_done`` recorded a mid-group index,
and the resume would re-fold windows the state already contains
(harmless for idempotent semilattice summaries like CC, wrong for
counting summaries like degrees). Barriers therefore land only on
window indices that are BOTH a multiple of ``every`` and a multiple of
K (effectively ``lcm(every, K)``); pick ``every`` a multiple of K to
keep the nominal cadence. Mid-superbatch kills restore from the last
group-aligned barrier and replay, which the equivalence tests pin
(``tests/test_superbatch.py``).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Callable, Iterator, Optional

import numpy as np

from ..obs import trace as _trace


class _SkipStream:
    """View of a stream whose first ``skip`` windows are consumed (for
    vertex-dictionary replay) but not surfaced to the workload."""

    #: disable the wrapped stream's superbatch fast path: the replay
    #: skip applies to blocks(), which the generic group packer
    #: (``core.window.iter_superbatches``) consumes — forwarding the
    #: inner packer would resurface the skipped windows
    superbatches = None

    def __init__(self, stream, skip: int):
        self._stream = stream
        self._skip = skip

    def __getattr__(self, name):
        return getattr(self._stream, name)

    def blocks(self):
        it = self._stream.blocks()
        for i, block in enumerate(it):
            if i >= self._skip:
                yield block


class AutoCheckpoint:
    """Snapshot ``work`` every ``every`` windows; resume transparently.

    ``run(make_stream, work)`` yields the per-window emissions exactly as
    ``work.run(stream)`` (or ``aggregation.run(stream)``) would, starting
    from the last completed barrier when ``path`` holds one.
    ``make_stream(vdict)`` must build the stream over the SAME source,
    with ``vdict`` (restored; None on a fresh start) as its vertex
    dictionary when given.
    """

    def __init__(self, path: str, every: int = 8):
        self.path = path
        self.every = int(every)
        self._cache = None  # loaded payload (invalidated on snapshot)
        #: vertex dictionary restored by the last :meth:`run` (None on a
        #: fresh start) — the public surface for consumers that need to
        #: decode restored state when the resumed stream yields nothing
        #: (barrier already covers the whole source)
        self.restored_vdict = None

    # ------------------------------------------------------------------ #
    def windows_done(self) -> int:
        """Windows completed at the last barrier (0 if no checkpoint)."""
        payload = self._load()
        return 0 if payload is None else payload["windows_done"]

    def run(self, make_stream: Callable, work) -> Iterator[Any]:
        payload = self._load()
        done = 0
        vdict = None
        if payload is not None:
            done = payload["windows_done"]
            vdict = self._restore_vdict(payload["vdict"])
            self._restore_work(work, payload)
        self.restored_vdict = vdict
        stream = make_stream(vdict)
        src = _SkipStream(stream, done) if done else stream
        # barrier alignment (see module doc): under superbatch=K the
        # summary is only valid on group boundaries. The work reports
        # its EFFECTIVE granularity (1 when its run loop opts out of
        # superbatching — host-side aggregations, transient CC), so a
        # per-window run keeps the full `every` cadence. `done` is
        # always group-aligned, so a resumed run's groups re-tile
        # identically.
        gran = getattr(work, "checkpoint_granularity", None)
        k = int(gran()) if callable(gran) else 1
        w = done
        for batch in work.run(src):
            yield batch
            w += 1
            if w % self.every == 0 and w % k == 0:
                self._snapshot(work, stream.vertex_dict, w)

    def restored_emission(self, work):
        """For ENGINE aggregations: the emission the restored barrier's
        summary would produce — what a consumer should surface when
        :meth:`run` yields nothing because the barrier already covers the
        whole source. Returns None for workload-kind objects (their state
        surface is ``state_dict``; emissions are not reconstructible
        generically)."""
        if hasattr(work, "state_dict") or not hasattr(work, "transform"):
            return None
        return work.transform(work._summary, self.restored_vdict)

    # ------------------------------------------------------------------ #
    def _snapshot(self, work, vdict, windows_done: int) -> None:
        with _trace.span(
            "checkpoint.barrier",
            {"windows_done": windows_done} if _trace.on() else None,
        ) as sp:
            # barrier_wait: capturing the state blocks on the carried
            # summary's in-flight device work (np.asarray is the sync) —
            # the piece of barrier cost that scales with dispatch depth,
            # kept separate from host serialize time below
            with _trace.span("checkpoint.barrier_wait"):
                if hasattr(work, "state_dict"):
                    kind, state = "workload", work.state_dict()
                else:
                    import jax

                    kind = "aggregation"
                    state = {
                        "summary": jax.tree.map(
                            np.asarray, work.snapshot_state()
                        ),
                        "vcap": work._vcap,
                    }
            if sp.recording:
                sp.set(kind=kind)
            payload = {
                "windows_done": windows_done,
                "kind": kind,
                "state": state,
                "vdict": self._vdict_payload(vdict),
            }
            with _trace.span("checkpoint.serialize"):
                tmp = self.path + ".tmp"
                with open(tmp, "wb") as f:
                    pickle.dump(payload, f)
                os.replace(tmp, self.path)  # atomic barrier commit
        # invalidate, do NOT cache: payload["state"] aliases LIVE workload
        # arrays (e.g. the degree shadow mutated by later windows); only
        # the pickled file is a true point-in-time snapshot
        self._cache = None

    def _load(self) -> Optional[dict]:
        """Read (and cache) the barrier payload: the label table + vertex
        dict can be multi-MB, so repeated ``windows_done()`` calls must
        not re-unpickle the file each time."""
        if self._cache is not None:
            return self._cache
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            self._cache = pickle.load(f)
        return self._cache

    def _restore_work(self, work, payload: dict) -> None:
        if payload["kind"] == "workload":
            work.load_state_dict(payload["state"])
        else:
            work.restore_state(
                payload["state"]["summary"], vcap=payload["state"]["vcap"]
            )

    # ------------------------------------------------------------------ #
    @staticmethod
    def _vdict_payload(vdict) -> Optional[dict]:
        from ..core.vertexdict import VertexDict
        from ..datasets import IdentityDict

        if isinstance(vdict, VertexDict):
            return {"kind": "vertexdict", "raw_ids": vdict.raw_ids()}
        if isinstance(vdict, IdentityDict):
            return {
                "kind": "identity",
                "id_bound": vdict.id_bound,
                "observed": len(vdict),
            }
        return None

    @staticmethod
    def _restore_vdict(payload: Optional[dict]):
        if payload is None:
            return None
        if payload["kind"] == "vertexdict":
            from ..core.vertexdict import VertexDict

            d = VertexDict()
            if len(payload["raw_ids"]):
                d.encode(payload["raw_ids"])
            return d
        from ..datasets import IdentityDict

        d = IdentityDict(payload["id_bound"])
        d.observe(payload["observed"] - 1)
        return d
