"""Periodic auto-checkpointing: the Flink-transparent restore analog.

The reference inherits fault tolerance from Flink: ``Merger implements
ListCheckpointed`` (``SummaryAggregation.java:127-135``) — the runtime
snapshots the running summary on every checkpoint barrier and, on
failover, restores it and replays the source from the checkpointed
offset. The repo's manual surface (``aggregate/checkpoint.py``) covers
the snapshot; this driver adds the BARRIER and the RESUME so a killed
process restarts and finishes with output identical to an uninterrupted
run (round-3 verdict #7 / missing-item #2):

- every ``every`` windows, :class:`AutoCheckpoint` atomically writes ONE
  file (state + vertex dictionary + windows_done) via write-temp +
  ``os.replace`` — a kill mid-snapshot leaves the previous barrier
  intact;
- on restart, the state restores and the replayed source fast-forwards
  by the recorded window count. The skipped windows still flow through
  the vertex dictionary (replay is idempotent: first-seen ordinal
  compaction assigns identical compact ids on identical prefixes), so
  ids assigned after resume continue exactly where the checkpoint left
  off.

Works for both carried-state workloads (``state_dict``/
``load_state_dict``: triangles, PageRank, spanner, samplers, SAGE,
matching, degrees) and engine aggregations (``snapshot_state``/
``restore_state``: CC, bipartiteness, ...). The driver is the analog of
Flink's checkpoint coordinator, not of its exactly-once sink protocol:
emissions between the last barrier and a kill are re-emitted after
resume, exactly like Flink's at-least-once outputs without transactional
sinks.

SUPERBATCH GRANULARITY: when the work runs with ``superbatch=K > 1``
(``SummaryAggregation``), K windows execute as one fused scan dispatch
and the carried summary is only observable on group boundaries —
between a group's yields, ``snapshot_state()`` would capture the
END-of-group summary while ``windows_done`` recorded a mid-group index,
and the resume would re-fold windows the state already contains
(harmless for idempotent semilattice summaries like CC, wrong for
counting summaries like degrees). Barriers therefore land only on
window indices that are BOTH a multiple of ``every`` and a multiple of
K (effectively ``lcm(every, K)``); pick ``every`` a multiple of K to
keep the nominal cadence. Mid-superbatch kills restore from the last
group-aligned barrier and replay, which the equivalence tests pin
(``tests/test_superbatch.py``).
"""

from __future__ import annotations

import math
import os
import pickle
import re
import time
from typing import Any, Callable, Iterator, Optional

import numpy as np

from ..obs import trace as _trace
from ..resilience import faults as _faults
from ..resilience import integrity as _integrity


class _SkipStream:
    """View of a stream whose first ``skip`` windows are consumed (for
    vertex-dictionary replay) but not surfaced to the workload."""

    def __init__(self, stream, skip: int):
        self._stream = stream
        self._skip = skip

    def __getattr__(self, name):
        return getattr(self._stream, name)

    def blocks(self):
        it = self._stream.blocks()
        for i, block in enumerate(it):
            if i >= self._skip:
                yield block

    def superbatches(self, k: int):
        """Group-granular replay skip. ``skip`` is always group-aligned
        (barriers land on ``checkpoint_granularity`` multiples), so when
        the wrapped stream has a packer and the tiling agrees we skip
        ``skip // k`` whole groups THROUGH it: the skipped groups still
        pack (one group encode each — the vertex-dictionary replay),
        they are just never surfaced, and the resumed run keeps the
        packer's exact per-window seen-count watermark
        (``SuperbatchGroup.n_seen_per_window`` — a workload like
        IncrementalPageRank reads it for value-identical resume). A
        misaligned ``k`` (the work was reconfigured between runs) falls
        back to generic packing of the skipped block iterator."""
        inner = getattr(self._stream, "superbatches", None)
        if callable(inner) and self._skip % k == 0:
            it = inner(k)
            for _ in range(self._skip // k):
                if next(it, None) is None:
                    break
            yield from it
            return
        from ..core.pipeline import prefetch, superbatch_prefetch_depth
        from ..core.window import superbatches_from_blocks

        yield from superbatches_from_blocks(
            prefetch(self.blocks(), superbatch_prefetch_depth(k)), k
        )

    def superbatches_dynamic(self, k_fn, skip: int = 0):
        """Adaptive-K replay skip (the ``superbatch="auto"`` resume
        path): the inner dynamic packer fast-forwards ``skip`` windows
        THROUGH the group encode (the vertex-dictionary replay, tiled
        at its own replay group size) without surfacing them. No tiling
        agreement is needed — unlike the fixed-K skip, the resumed
        controller is free to re-tile from the barrier onward, because
        value identity holds for ANY tiling (the group-fold contract)
        and barriers only ever landed on group boundaries. Defined
        explicitly so ``__getattr__`` can never hand the caller the
        INNER stream's packer with the skip silently dropped."""
        inner = getattr(self._stream, "superbatches_dynamic", None)
        if callable(inner):
            yield from inner(k_fn, skip=self._skip + skip)
            return
        from ..core.pipeline import prefetch, superbatch_prefetch_depth
        from ..core.window import superbatches_from_blocks_dynamic

        # self.blocks() consumes self._skip; an ADDITIONAL skip from a
        # nested wrapper must also be honored here, not dropped
        blocks = self.blocks()
        for _ in range(skip):
            if next(blocks, None) is None:
                break
        yield from superbatches_from_blocks_dynamic(
            prefetch(blocks, superbatch_prefetch_depth(int(k_fn()))),
            k_fn,
        )


class AutoCheckpoint:
    """Snapshot ``work`` every ``every`` windows; resume transparently.

    ``run(make_stream, work)`` yields the per-window emissions exactly as
    ``work.run(stream)`` (or ``aggregation.run(stream)``) would, starting
    from the last completed barrier when ``path`` holds one.
    ``make_stream(vdict)`` must build the stream over the SAME source,
    with ``vdict`` (restored; None on a fresh start) as its vertex
    dictionary when given.

    INTEGRITY + ROTATION (resilience layer): each barrier commits as a
    checksummed container (CRC32 over the pickled payload) via temp +
    ``os.replace``, and the previous ``keep - 1`` barriers rotate to
    ``path.1``, ``path.2``, ... (renames only — a kill mid-rotation
    loses nothing). Loading scans head-first and falls back to the
    NEWEST VALID barrier when the head is torn, truncated, or corrupt;
    every rejected artifact is recorded as ``resilience.ckpt_rejected``
    in the obs registry and warned. If every barrier is invalid the run
    restarts from scratch (a full replay is still correct under the
    at-least-once emission contract above) after recording each
    rejection — recovery never silently loads damage.
    """

    #: ``every="auto"``: barrier-overhead budget as a fraction of wall
    #: time (the ISSUE 5 satellite target — at most ~5% of the run spent
    #: inside barriers), and the cadence clamp the tuner moves within
    AUTO_TARGET_OVERHEAD = 0.05
    AUTO_MIN_EVERY = 1
    AUTO_MAX_EVERY = 4096

    def __init__(self, path: str, every=8, keep: int = 2, *,
                 target_overhead: Optional[float] = None):
        self.path = path
        #: ``every="auto"`` tunes the cadence from the measured
        #: ``checkpoint.barrier_wait`` + ``checkpoint.serialize`` cost of
        #: each barrier vs the measured per-window wall time, so at most
        #: ``target_overhead`` of the run is spent inside barriers. The
        #: tuned value is re-derived after every barrier (both costs
        #: drift as the summary grows) and always lands on a
        #: superbatch-group boundary (see run()).
        self.auto = every == "auto"
        self.every = 2 if self.auto else int(every)
        self.target_overhead = float(
            self.AUTO_TARGET_OVERHEAD if target_overhead is None
            else target_overhead
        )
        #: the ONE retune-signal implementation (ISSUE 15): barrier and
        #: window costs are direct taps on the shared SignalReader —
        #: the same reader the control-plane tuners consume — instead
        #: of private fields, so every closed loop in the repo measures
        #: through one code path (and tuning keeps working with obs
        #: disabled, which the direct-tap half guarantees)
        from ..control.signals import SignalReader

        self.signals = SignalReader()
        self.keep = max(1, int(keep))
        #: artifacts already rejected, keyed by (path, mtime_ns, size):
        #: repeated _load scans (every windows_done() while all barriers
        #: are invalid) must not re-inflate resilience.ckpt_rejected for
        #: the SAME damaged bytes; an externally replaced file gets a
        #: new key and re-validates
        self._rejected_seen: set = set()
        self._cache = None  # loaded payload (invalidated on snapshot)
        # True when _cache holds a scan RESULT — including the negative
        # "no barrier found" one. The no-result case must cache too: in
        # the coordinated layout a peer can commit between two scans,
        # and an attempt whose windows_done() said "from scratch" but
        # whose run() then restored a fresh epoch would desynchronize
        # the supervisor's dedupe ordinals from the actual replay
        self._cache_valid = False
        #: vertex dictionary restored by the last :meth:`run` (None on a
        #: fresh start) — the public surface for consumers that need to
        #: decode restored state when the resumed stream yields nothing
        #: (barrier already covers the whole source)
        self.restored_vdict = None

    # ------------------------------------------------------------------ #
    @property
    def measured_barrier_s(self) -> Optional[float]:
        """Last measured barrier cost in seconds (state capture +
        serialize + commit; None before the first barrier) — the
        ``checkpoint.barrier_s`` direct tap on :attr:`signals`."""
        return self.signals.last("checkpoint.barrier_s")

    @property
    def measured_window_s(self) -> Optional[float]:
        """Last measured mean per-window wall seconds of the segment
        before a barrier (None before the first) — the
        ``checkpoint.window_s`` direct tap on :attr:`signals`."""
        return self.signals.last("checkpoint.window_s")

    # ------------------------------------------------------------------ #
    def invalidate(self) -> None:
        """Drop the cached barrier payload so the next read re-scans the
        disk. The supervisor calls this before every (re)start attempt:
        between a failure and its restart another actor may have
        committed or damaged barriers (a peer process in the coordinated
        multi-host layout, the chaos harness's corruption fault), and a
        restart must restore from what is on disk NOW, not from a
        pre-failure cache."""
        self._cache = None
        self._cache_valid = False

    def discard(self) -> None:
        """Delete every artifact of THIS checkpoint — the barrier head,
        its crash-leftover temp, and all numbered rotation slots — and
        drop the cache: the fresh-start path (the example CLIs'
        ``--fresh``). Layout knowledge lives here next to ``_commit`` /
        ``_rotate``; prefix-sharing siblings (``/d/run1`` vs
        ``/d/run10``) are never touched."""
        d, base = os.path.split(self.path)
        try:
            names = os.listdir(d or ".")
        except OSError:
            names = []
        for name in names:
            if name == base or name == base + ".tmp" or (
                name.startswith(base + ".")
                and re.fullmatch(r"\d+", name[len(base) + 1:])
            ):
                try:
                    os.remove(os.path.join(d or ".", name))
                except OSError:
                    pass
        self._cache = None
        self._cache_valid = False

    def windows_done(self) -> int:
        """Windows completed at the last barrier (0 if no checkpoint)."""
        payload = self._load()
        return 0 if payload is None else payload["windows_done"]

    def run(self, make_stream: Callable, work) -> Iterator[Any]:
        payload = self._load()
        done = 0
        vdict = None
        if payload is not None:
            done = payload["windows_done"]
            vdict = self._restore_vdict(payload["vdict"])
            self._restore_work(work, payload)
        self.restored_vdict = vdict
        stream = make_stream(vdict)
        src = _SkipStream(stream, done) if done else stream
        # barrier alignment (see module doc): under superbatch=K the
        # summary is only valid on group boundaries. The work reports
        # its EFFECTIVE granularity (1 when its run loop opts out of
        # superbatching — host-side aggregations, transient CC), so a
        # per-window run keeps the full `every` cadence. `done` is
        # always group-aligned, so a resumed run's groups re-tile
        # identically.
        gran = getattr(work, "checkpoint_granularity", None)
        k = int(gran()) if callable(gran) else 1
        if self.auto and self.every % k:
            self.every = self.every + (k - self.every % k)
        # alignment: group-folded workloads report their EXACT group
        # boundaries (checkpoint_aligned over windows-since-resume —
        # required under superbatch="auto", where the controller
        # re-tiles mid-run and no static modulo can know the
        # boundaries); everything else keeps the historical modulo rule
        aligned = getattr(work, "checkpoint_aligned", None)
        use_pred = callable(aligned)
        # a dynamically-tiled workload (superbatch="auto") has no static
        # group stride for the modulo cadence to coincide with — its
        # barriers land on the FIRST group boundary at least `every`
        # windows past the previous barrier (the same counting rule the
        # auto cadence tuner uses)
        dynamic = bool(getattr(work, "superbatch_auto", False))
        w = done
        last_barrier = done
        seg_t0 = time.perf_counter()  # start of the inter-barrier segment
        for batch in work.run(src):
            yield batch
            w += 1
            # fixed cadence keeps the historical modulo rule (barriers on
            # multiples of `every`, resume re-tiles identically); the
            # auto tuner counts windows SINCE the last barrier instead,
            # because `every` itself moves between barriers
            due = (
                w - last_barrier >= self.every if self.auto or dynamic
                else w % self.every == 0
            )
            ok = aligned(w - done) if use_pred else w % k == 0
            if due and ok:
                window_s = (time.perf_counter() - seg_t0) / max(
                    1, w - last_barrier
                )
                self._snapshot(work, stream.vertex_dict, w)
                last_barrier = w
                if self.auto:
                    self._retune(window_s, k)
                seg_t0 = time.perf_counter()

    def _retune(self, window_s: float, k: int) -> None:
        """Re-derive the auto cadence from the just-measured barrier cost
        (the ``checkpoint.barrier_wait`` + ``checkpoint.serialize`` spans
        of :meth:`_snapshot`) and the measured per-window wall time:
        ``every >= barrier_s / (target_overhead * window_s)`` keeps the
        fraction of wall time spent in barriers at or under the target,
        rounded UP to a superbatch-group multiple and clamped to
        [AUTO_MIN_EVERY, AUTO_MAX_EVERY]."""
        self.signals.observe("checkpoint.window_s", window_s)
        barrier_s = self.measured_barrier_s
        if not barrier_s or window_s <= 0:
            return
        want = math.ceil(barrier_s / (self.target_overhead * window_s))
        want = max(self.AUTO_MIN_EVERY, want, k)
        if want % k:
            want = want + (k - want % k)
        # clamp AFTER rounding, to the largest superbatch multiple under
        # the ceiling (never below k itself: barriers must stay aligned)
        cap = max(self.AUTO_MAX_EVERY - self.AUTO_MAX_EVERY % k, k)
        self.every = min(want, cap)

    def restored_emission(self, work):
        """For ENGINE aggregations: the emission the restored barrier's
        summary would produce — what a consumer should surface when
        :meth:`run` yields nothing because the barrier already covers the
        whole source. Returns None for workload-kind objects (their state
        surface is ``state_dict``; emissions are not reconstructible
        generically)."""
        if hasattr(work, "state_dict") or not hasattr(work, "transform"):
            return None
        return work.transform(work._summary, self.restored_vdict)

    # ------------------------------------------------------------------ #
    def _snapshot(self, work, vdict, windows_done: int) -> None:
        t0 = time.perf_counter()
        with _trace.span(
            "checkpoint.barrier",
            {"windows_done": windows_done} if _trace.on() else None,
        ) as sp:
            # barrier_wait: capturing the state blocks on the carried
            # summary's in-flight device work (np.asarray is the sync) —
            # the piece of barrier cost that scales with dispatch depth,
            # kept separate from host serialize time below
            with _trace.span("checkpoint.barrier_wait"):
                if hasattr(work, "state_dict"):
                    kind, state = "workload", work.state_dict()
                else:
                    import jax

                    kind = "aggregation"
                    state = {
                        "summary": jax.tree.map(
                            np.asarray, work.snapshot_state()
                        ),
                        "vcap": work._vcap,
                    }
            if sp.recording:
                sp.set(kind=kind)
            payload = {
                "windows_done": windows_done,
                "kind": kind,
                "state": state,
                "vdict": self._vdict_payload(vdict),
            }
            with _trace.span("checkpoint.serialize"):
                committed = self._commit(payload)
        # invalidate, do NOT cache: payload["state"] aliases LIVE workload
        # arrays (e.g. the degree shadow mutated by later windows); only
        # the pickled file is a true point-in-time snapshot
        self._cache = None
        self._cache_valid = False
        # the measured barrier cost feeds the auto cadence tuner — the
        # same barrier_wait + serialize regions the obs spans time, but
        # tapped DIRECTLY on the shared SignalReader so tuning works
        # with obs disabled
        barrier_s = time.perf_counter() - t0
        self.signals.observe("checkpoint.barrier_s", barrier_s)
        # and credited as FOREIGN time to this thread's throughput
        # taps: a barrier lands between two of a group's yields, so
        # without the credit the group controller (auto-K) would read
        # it as a throughput collapse at the current K
        from ..control.signals import add_excluded_s

        add_excluded_s(barrier_s)
        if _faults.active():  # chaos hook: corrupt-the-barrier-just-written
            _faults.fire(
                "checkpoint.committed", index=windows_done, path=committed
            )

    def _commit(self, payload: dict) -> str:
        """Serialize + atomically commit one barrier; returns the
        committed path (the chaos corruption hook's target). The
        single-process layout writes ``self.path`` with keep-last-N
        rotation; the coordinated multi-host subclass overrides this to
        write per-shard epoch files plus a rendezvous record."""
        data = _integrity.wrap_checksummed(pickle.dumps(payload))
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        self._rotate()
        os.replace(tmp, self.path)  # atomic barrier commit
        return self.path

    def _rotate(self) -> None:
        """Shift committed barriers one slot down (``path`` -> ``path.1``
        -> ... -> dropped past ``keep - 1``) ahead of a new head commit.
        Renames only: a kill between any two steps leaves every barrier
        intact under some scanned name. A head this instance already
        REJECTED is unlinked instead of rotated — shifting corrupt
        bytes over ``path.1`` would overwrite the good fallback those
        bytes forced us onto (fatal at ``keep=2`` if the process then
        dies before the new head commits)."""
        if self.keep <= 1:
            return
        try:
            st = os.stat(self.path)
            if (self.path, st.st_mtime_ns, st.st_size) in self._rejected_seen:
                os.remove(self.path)
        except OSError:
            pass
        for i in range(self.keep - 1, 0, -1):
            src = self.path if i == 1 else f"{self.path}.{i - 1}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i}")

    #: keys a valid barrier payload must carry (anything less is a torn
    #: or foreign file, not a barrier)
    _PAYLOAD_KEYS = frozenset(("windows_done", "kind", "state", "vdict"))

    def _load(self) -> Optional[dict]:
        """Read (and cache) the NEWEST VALID barrier payload: the label
        table + vertex dict can be multi-MB, so repeated
        ``windows_done()`` calls must not re-unpickle the file each
        time. Scans head-first, then the rotation slots; invalid
        artifacts are rejected (recorded + warned) and the scan falls
        through to the previous barrier. The NEGATIVE result caches
        too: one attempt's reads must all agree (see ``_cache_valid``);
        :meth:`invalidate` is the explicit re-scan."""
        if self._cache_valid:
            return self._cache
        payload = None
        for cand in self._candidates():
            payload = self._read_barrier(cand)
            if payload is not None:
                break
        self._cache = payload
        self._cache_valid = True
        return payload

    def _candidates(self) -> list:
        """Barrier files newest-first: the head plus every rotation
        slot on disk. The scan TOLERATES GAPS (a kill between two
        rotation renames leaves e.g. ``path`` and ``path.2`` with no
        ``path.1``) and runs past ``self.keep`` with slack, so a
        reader configured with a smaller ``keep`` than the writer's
        still sees the deeper history."""
        out = [self.path]
        for i in range(1, max(self.keep + 1, 9)):
            p = f"{self.path}.{i}"
            if os.path.exists(p):
                out.append(p)
        return out

    def _read_barrier(self, path: str) -> Optional[dict]:
        """One candidate: unwrap + checksum + unpickle + shape-check.
        Returns None (after recording the rejection ONCE per damaged
        file version) on any damage — the caller falls back to the
        next-newest barrier."""
        try:
            st = os.stat(path)
        except FileNotFoundError:
            return None
        except OSError as e:
            # EACCES/EIO is damage the operator must see, not a gap in
            # the rotation — record it (once per error shape) before
            # falling back
            key = (path, "stat", type(e).__name__)
            if key not in self._rejected_seen:
                self._rejected_seen.add(key)
                _integrity.record_rejection(path, f"unstatable: {e!r}")
            return None
        key = (path, st.st_mtime_ns, st.st_size)
        if key in self._rejected_seen:
            return None
        try:
            with open(path, "rb") as f:
                data = f.read()
        except Exception as e:
            self._rejected_seen.add(key)
            _integrity.record_rejection(path, repr(e))
            return None
        return self._barrier_payload(data, path, key)

    def _barrier_payload(self, data: bytes, origin: str,
                         key) -> Optional[dict]:
        """Validate one barrier's BYTES — unwrap + checksum + unpickle
        + shape-check — independent of where they were read from; the
        coordinated layer reuses this for shards read through a
        cluster :class:`~gelly_streaming_tpu.fabric.Transport`.
        Returns None (after recording the rejection once per ``key``)
        on any damage."""
        if key in self._rejected_seen:
            return None
        try:
            payload = pickle.loads(
                _integrity.unwrap_checksummed(data, origin=origin)
            )
            if (
                not isinstance(payload, dict)
                or not self._PAYLOAD_KEYS <= payload.keys()
            ):
                raise ValueError("barrier payload missing required keys")
            return payload
        except Exception as e:
            self._rejected_seen.add(key)
            _integrity.record_rejection(origin, repr(e))
            return None

    def _restore_work(self, work, payload: dict) -> None:
        if payload["kind"] == "workload":
            work.load_state_dict(payload["state"])
        else:
            work.restore_state(
                payload["state"]["summary"], vcap=payload["state"]["vcap"]
            )

    # ------------------------------------------------------------------ #
    @staticmethod
    def _vdict_payload(vdict) -> Optional[dict]:
        from ..core.vertexdict import VertexDict
        from ..datasets import IdentityDict

        if isinstance(vdict, VertexDict):
            return {"kind": "vertexdict", "raw_ids": vdict.raw_ids()}
        if isinstance(vdict, IdentityDict):
            return {
                "kind": "identity",
                "id_bound": vdict.id_bound,
                "observed": len(vdict),
            }
        return None

    @staticmethod
    def _restore_vdict(payload: Optional[dict]):
        if payload is None:
            return None
        if payload["kind"] == "vertexdict":
            from ..core.vertexdict import VertexDict

            d = VertexDict()
            if len(payload["raw_ids"]):
                d.encode(payload["raw_ids"])
            return d
        from ..datasets import IdentityDict

        d = IdentityDict(payload["id_bound"])
        d.observe(payload["observed"] - 1)
        return d
