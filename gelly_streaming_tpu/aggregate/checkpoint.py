"""Checkpoint / resume for carried aggregation state.

The reference's only fault-tolerance hook is ``Merger implements
ListCheckpointed<S>`` — the running global summary is snapshotted/restored by
Flink checkpointing (``SummaryAggregation.java:93,127-135``); window-fold
partials ride on Flink managed state implicitly. SURVEY.md §5 notes the TPU
surface is equally small: (summary pytree + vertex dictionary + window
position) per stream.

This module serializes that surface with numpy only (no orbax dependency for
a kilobyte-scale state): a pytree of arrays goes to ``.npz`` plus a JSON
treedef; the vertex dictionary saves its raw-id table (compact ids are
first-seen ordinal, so the table alone reconstructs it).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

from ..core.vertexdict import VertexDict
from ..obs import trace as _trace
from ..resilience import integrity as _integrity
from ..resilience.errors import CheckpointCorrupt


def _keypaths(tree: Any) -> list:
    """Version-stable structural encoding: one path string per leaf.

    ``jax.tree_util.keystr`` output (dict keys, attribute names, indices) is
    part of the public API and stable across JAX versions, unlike
    ``str(treedef)`` whose repr has changed between releases."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [jax.tree_util.keystr(path) for path, _ in flat]


def _generation_files(path: str) -> list:
    """Every on-disk generation-named array file for ``path``."""
    import glob as _glob

    return sorted(_glob.glob(_glob.escape(path) + ".g*.npz"))


def _next_generation(path: str) -> int:
    """One past the highest array-file generation on disk for ``path``
    (crash leftovers included, so a new save never overwrites a file
    any sidecar — committed or torn — might reference)."""
    import re

    best = -1
    for p in _generation_files(path):
        m = re.search(r"\.g(\d+)\.npz$", p)
        if m:
            best = max(best, int(m.group(1)))
    return best + 1


def _npz_path(path: str, info: dict) -> str:
    """The array file a sidecar references: generation-named for
    post-resilience checkpoints, the legacy fixed ``path.npz`` before."""
    name = info.get("npz")
    if name is None:
        return path + ".npz"
    return os.path.join(os.path.dirname(path) or ".", name)


def save_pytree(path: str, tree: Any, meta: Optional[dict] = None) -> None:
    """Write a pytree of arrays to ``path.g<N>.npz`` + ``path.json``.

    ATOMIC COMMIT: the arrays land under a GENERATION-UNIQUE name (never
    overwriting the file the committed sidecar references), then the
    JSON sidecar — naming that file and carrying a CRC32 over the leaf
    content — commits via temp + ``os.replace``. A kill at any byte
    leaves the previous pair fully intact (at worst plus one orphaned
    new-generation array file, swept by the next successful save);
    :func:`load_pytree` validates the named file against the sidecar's
    leaf count and checksum, so a torn or bit-rotted checkpoint never
    loads.
    """
    leaves, treedef = jax.tree.flatten(tree)
    # barrier_wait: np.asarray blocks on any in-flight device work that
    # produces these leaves — the snapshot's implicit device barrier
    with _trace.span(
        "checkpoint.barrier_wait",
        {"leaves": len(leaves)} if _trace.on() else None,
    ):
        arrays = {f"leaf_{i}": np.asarray(leaf) for i, leaf in enumerate(leaves)}
    with _trace.span(
        "checkpoint.serialize",
        {"leaves": len(leaves)} if _trace.on() else None,
    ):
        gen = _next_generation(path)
        npz = f"{path}.g{gen}.npz"
        npz_tmp = npz + ".tmp"
        # savez appends .npz to names without it; write with the real
        # suffix inside the temp name, then rename
        with open(npz_tmp, "wb") as f:
            np.savez(f, **arrays)
        os.replace(npz_tmp, npz)
        # content checksum over the in-memory leaves (leaf order), NOT a
        # re-read of the file just written — the barrier must not pay a
        # second pass over a potentially multi-GB .npz
        crc = _integrity.arrays_crc32(
            arrays[f"leaf_{i}"] for i in range(len(leaves))
        )
        doc = {"treedef": str(treedef), "keypaths": _keypaths(tree),
               "n_leaves": len(leaves), "meta": meta or {},
               "npz": os.path.basename(npz), "leaves_crc32": crc}
        json_tmp = path + ".json.tmp"
        with open(json_tmp, "w") as f:
            json.dump(doc, f)
        _integrity.replace_atomic(json_tmp, path + ".json")  # commit
        # sweep superseded generations (and the legacy fixed name) only
        # AFTER the new sidecar committed; best-effort — leftovers are
        # orphans, never referenced
        for stale in _generation_files(path) + [path + ".npz"]:
            if stale != npz and os.path.exists(stale):
                try:
                    os.remove(stale)
                except OSError:
                    pass


def load_pytree(path: str, like: Any) -> Tuple[Any, dict]:
    """Read arrays back into the structure of ``like`` (same treedef).

    Returns (tree, meta). Rejects a checkpoint whose stored structure (leaf
    key paths), leaf count, leaf shapes, or leaf dtype kinds disagree with
    ``like`` — restoring one summary kind into another must fail at load
    time, not corrupt state silently. Structure is compared via leaf key
    paths (stable across JAX versions), not ``str(treedef)`` (which is not);
    for pre-keypath checkpoints the treedef string downgrades to a warning.

    INTEGRITY: before any structural comparison the ``.npz`` is checked
    against its sidecar — stored leaf count vs. the arrays actually
    present (a torn or swapped ``.npz`` fails HERE with a clear
    :class:`~gelly_streaming_tpu.resilience.errors.CheckpointCorrupt`,
    not an opaque numpy KeyError), and content checksum when the
    sidecar carries one (post-resilience checkpoints always do). Every
    rejection is recorded as ``resilience.ckpt_rejected``.
    """
    with open(path + ".json") as f:
        info = json.load(f)
    npz = _npz_path(path, info)
    try:
        data = np.load(npz)
        stored = {k for k in data.files if k.startswith("leaf_")}
    except Exception as e:
        _integrity.record_rejection(npz, f"unreadable: {e!r}")
        raise CheckpointCorrupt(
            f"checkpoint array file {npz} is unreadable ({e!r}); the "
            "sidecar committed but the array file is torn, corrupt, or "
            "missing"
        ) from e
    if len(stored) != info["n_leaves"]:
        _integrity.record_rejection(
            npz,
            f"{len(stored)} leaf arrays vs sidecar n_leaves="
            f"{info['n_leaves']}",
        )
        raise CheckpointCorrupt(
            f"checkpoint array file {npz} holds {len(stored)} leaf "
            f"arrays but its sidecar committed n_leaves="
            f"{info['n_leaves']}; the pair is torn (mismatched save "
            "generations)"
        )
    try:
        leaves = [data[f"leaf_{i}"] for i in range(info["n_leaves"])]
    except Exception as e:
        _integrity.record_rejection(npz, f"torn archive: {e!r}")
        raise CheckpointCorrupt(
            f"checkpoint array file {npz} failed to decompress its "
            f"leaf arrays ({e!r}); the file is torn or corrupt"
        ) from e
    want_crc = info.get("leaves_crc32")
    if want_crc is not None:
        got_crc = _integrity.arrays_crc32(leaves)
        if got_crc != want_crc:
            _integrity.record_rejection(
                npz,
                f"content crc32 {got_crc:#x} != sidecar {want_crc:#x}",
            )
            raise CheckpointCorrupt(
                f"checkpoint array file {npz} leaf content checksum "
                f"{got_crc:#x} does not match its sidecar's "
                f"{want_crc:#x} (torn pair or bit rot)"
            )
    like_leaves, treedef = jax.tree.flatten(like)
    if treedef.num_leaves != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves but template has "
            f"{treedef.num_leaves}"
        )
    if info.get("keypaths") is not None:
        want_paths = _keypaths(like)
        if info["keypaths"] != want_paths:
            raise ValueError(
                f"checkpoint structure {info['keypaths']} does not match "
                f"template structure {want_paths}"
            )
    elif info.get("treedef") and info["treedef"] != str(treedef):
        # Old checkpoint without keypaths: the treedef repr is not stable
        # across JAX versions, so only warn; leaf count/shape/dtype checks
        # below remain the load-bearing validation.
        import warnings

        warnings.warn(
            f"checkpoint treedef string {info['treedef']!r} differs from "
            f"template {str(treedef)!r}; proceeding on matching leaf "
            "count/shapes (repr may differ across JAX versions)"
        )
    for i, (stored, want) in enumerate(zip(leaves, like_leaves)):
        if np.shape(want) != stored.shape:
            raise ValueError(
                f"checkpoint leaf {i} has shape {stored.shape} but template "
                f"expects {np.shape(want)}"
            )
        want_kind = np.asarray(want).dtype.kind
        if stored.dtype.kind != want_kind:
            raise ValueError(
                f"checkpoint leaf {i} has dtype {stored.dtype} but template "
                f"expects kind {want_kind!r}"
            )
    return jax.tree.unflatten(treedef, leaves), info.get("meta", {})


def load_meta(path: str) -> dict:
    """Read just the sidecar metadata (e.g. ``vcap``) without the arrays."""
    with open(path + ".json") as f:
        return json.load(f).get("meta", {})


def save_vertex_dict(path: str, vdict: VertexDict) -> None:
    np.save(path + ".vdict.npy", vdict.raw_ids())


def load_vertex_dict(path: str) -> VertexDict:
    raw = np.load(path + ".vdict.npy")
    d = VertexDict()
    d.encode(raw)
    return d


def _commit_pickle_bytes(path: str, payload: bytes) -> None:
    """Atomically commit pickled state: CRC-framed container written to
    a tmp sibling, then ``os.replace``d into place — the same
    torn-file guarantee the pytree/barrier paths already have. A kill
    at any byte leaves the previous committed file (or nothing), never
    a half-written pickle under the live name."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(_integrity.wrap_checksummed(payload))
    _integrity.replace_atomic(tmp, path)


def _load_pickle_bytes(path: str) -> bytes:
    """Read back a :func:`_commit_pickle_bytes` artifact. Legacy
    un-framed pickles pass through unchanged (rename-atomicity was
    their only guarantee, as before); a torn/corrupt frame raises
    :class:`CheckpointCorrupt` and is recorded."""
    with open(path, "rb") as f:
        data = f.read()
    try:
        return _integrity.unwrap_checksummed(data, origin=path)
    except CheckpointCorrupt as e:
        _integrity.record_rejection(path, str(e))
        raise


def save_aggregation(path: str, aggregation, vdict: Optional[VertexDict] = None) -> None:
    """Checkpoint an aggregation's running summary (+ optional dict).

    Device aggregations serialize as array pytrees; host-state aggregations
    (``device=False``, e.g. the spanner's adjacency map) pickle their summary
    object instead — np.asarray would wrap it in an object array that
    ``np.load`` refuses to read back.
    """
    if aggregation.device:
        save_pytree(path, aggregation.snapshot_state(), meta={"vcap": aggregation._vcap})
    else:
        import pickle

        _commit_pickle_bytes(
            path + ".pkl", pickle.dumps(aggregation._summary)
        )
    if vdict is not None:
        save_vertex_dict(path, vdict)


def restore_aggregation(path: str, aggregation, template: Any = None) -> Optional[VertexDict]:
    """Restore a checkpointed summary into ``aggregation``.

    For device aggregations the template defaults to
    ``aggregation.initial_state(vcap)`` with ``vcap`` read from the sidecar
    metadata — a resume site needs only the path and a fresh aggregation
    object. Pass ``template`` explicitly only for states whose structure
    ``initial_state`` does not produce. Host aggregations unpickle and ignore
    it. Returns the restored VertexDict if one was saved alongside, else None.
    """
    if aggregation.device:
        if template is None:
            vcap = load_meta(path).get("vcap")
            if vcap is None:
                raise ValueError(
                    f"checkpoint {path} has no vcap metadata; pass template="
                )
            template = aggregation.initial_state(vcap)
        state, meta = load_pytree(path, template)
        aggregation.restore_state(state, vcap=meta.get("vcap"))
    else:
        import pickle

        aggregation._summary = pickle.loads(
            _load_pickle_bytes(path + ".pkl")
        )
    vd_path = path + ".vdict.npy"
    return load_vertex_dict(path) if os.path.exists(vd_path) else None


def save_workload(path: str, workload, vdict: Optional[VertexDict] = None) -> None:
    """Checkpoint any carried-state workload exposing ``state_dict()``
    (triangles, PageRank, degree distribution, spanner, samplers, SAGE,
    matching). The state is a plain dict of numpy arrays / scalars and is
    pickled — same trust model as the host-aggregation path above."""
    import pickle

    _commit_pickle_bytes(
        path + ".workload.pkl", pickle.dumps(workload.state_dict())
    )
    if vdict is not None:
        save_vertex_dict(path, vdict)


def restore_workload(path: str, workload) -> Optional[VertexDict]:
    """Restore a :func:`save_workload` checkpoint into ``workload``.
    Returns the restored VertexDict when one was saved alongside."""
    import pickle

    workload.load_state_dict(
        pickle.loads(_load_pickle_bytes(path + ".workload.pkl"))
    )
    vd_path = path + ".vdict.npy"
    return load_vertex_dict(path) if os.path.exists(vd_path) else None


def restore_server(
    path: str,
    workload,
    source,
    *,
    template: Any = None,
    start: bool = True,
    **server_kwargs,
):
    """Boot a live query server from a checkpoint: restore ``workload``'s
    carried state (aggregation or ``state_dict`` workload checkpoints are
    both recognized by their sidecar files), publish the restored summary
    as the server's BOOT snapshot (window ``-1``), then serve while the
    ``source`` stream catches up — queries answer from the restored state
    immediately, before the first live window folds.

    ``source`` must be built against the same compact-id space as the
    checkpoint (pass the restored VertexDict into the stream, the
    existing resume contract); the boot payload resolves raw ids through
    the restored dict when one was saved alongside, else the source's.
    Returns the (started, unless ``start=False``) ``StreamServer``.
    """
    from ..serving import StreamServer

    if os.path.exists(path + ".workload.pkl"):
        vdict = restore_workload(path, workload)
    else:
        vdict = restore_aggregation(path, workload, template)
    if vdict is None:
        vdict = getattr(source, "vertex_dict", None)
    servable = workload.servable(vdict=vdict)
    server = StreamServer(servable, source, **server_kwargs)
    boot = servable.boot_payload()
    if boot is not None:
        payload, watermark = boot
        server.publish_boot(payload, watermark)
    return server.start() if start else server
