"""Checkpoint / resume for carried aggregation state.

The reference's only fault-tolerance hook is ``Merger implements
ListCheckpointed<S>`` — the running global summary is snapshotted/restored by
Flink checkpointing (``SummaryAggregation.java:93,127-135``); window-fold
partials ride on Flink managed state implicitly. SURVEY.md §5 notes the TPU
surface is equally small: (summary pytree + vertex dictionary + window
position) per stream.

This module serializes that surface with numpy only (no orbax dependency for
a kilobyte-scale state): a pytree of arrays goes to ``.npz`` plus a JSON
treedef; the vertex dictionary saves its raw-id table (compact ids are
first-seen ordinal, so the table alone reconstructs it).
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

from ..core.vertexdict import VertexDict
from ..obs import trace as _trace


def _keypaths(tree: Any) -> list:
    """Version-stable structural encoding: one path string per leaf.

    ``jax.tree_util.keystr`` output (dict keys, attribute names, indices) is
    part of the public API and stable across JAX versions, unlike
    ``str(treedef)`` whose repr has changed between releases."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    return [jax.tree_util.keystr(path) for path, _ in flat]


def save_pytree(path: str, tree: Any, meta: Optional[dict] = None) -> None:
    """Write a pytree of arrays to ``path.npz`` + ``path.json``."""
    leaves, treedef = jax.tree.flatten(tree)
    # barrier_wait: np.asarray blocks on any in-flight device work that
    # produces these leaves — the snapshot's implicit device barrier
    with _trace.span(
        "checkpoint.barrier_wait",
        {"leaves": len(leaves)} if _trace.on() else None,
    ):
        arrays = {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}
    with _trace.span(
        "checkpoint.serialize",
        {"leaves": len(leaves)} if _trace.on() else None,
    ):
        np.savez(path + ".npz", **arrays)
        with open(path + ".json", "w") as f:
            json.dump({"treedef": str(treedef), "keypaths": _keypaths(tree),
                       "n_leaves": len(leaves), "meta": meta or {}}, f)


def load_pytree(path: str, like: Any) -> Tuple[Any, dict]:
    """Read arrays back into the structure of ``like`` (same treedef).

    Returns (tree, meta). Rejects a checkpoint whose stored structure (leaf
    key paths), leaf count, leaf shapes, or leaf dtype kinds disagree with
    ``like`` — restoring one summary kind into another must fail at load
    time, not corrupt state silently. Structure is compared via leaf key
    paths (stable across JAX versions), not ``str(treedef)`` (which is not);
    for pre-keypath checkpoints the treedef string downgrades to a warning.
    """
    with open(path + ".json") as f:
        info = json.load(f)
    data = np.load(path + ".npz")
    leaves = [data[f"leaf_{i}"] for i in range(info["n_leaves"])]
    like_leaves, treedef = jax.tree.flatten(like)
    if treedef.num_leaves != len(leaves):
        raise ValueError(
            f"checkpoint has {len(leaves)} leaves but template has "
            f"{treedef.num_leaves}"
        )
    if info.get("keypaths") is not None:
        want_paths = _keypaths(like)
        if info["keypaths"] != want_paths:
            raise ValueError(
                f"checkpoint structure {info['keypaths']} does not match "
                f"template structure {want_paths}"
            )
    elif info.get("treedef") and info["treedef"] != str(treedef):
        # Old checkpoint without keypaths: the treedef repr is not stable
        # across JAX versions, so only warn; leaf count/shape/dtype checks
        # below remain the load-bearing validation.
        import warnings

        warnings.warn(
            f"checkpoint treedef string {info['treedef']!r} differs from "
            f"template {str(treedef)!r}; proceeding on matching leaf "
            "count/shapes (repr may differ across JAX versions)"
        )
    for i, (stored, want) in enumerate(zip(leaves, like_leaves)):
        if np.shape(want) != stored.shape:
            raise ValueError(
                f"checkpoint leaf {i} has shape {stored.shape} but template "
                f"expects {np.shape(want)}"
            )
        want_kind = np.asarray(want).dtype.kind
        if stored.dtype.kind != want_kind:
            raise ValueError(
                f"checkpoint leaf {i} has dtype {stored.dtype} but template "
                f"expects kind {want_kind!r}"
            )
    return jax.tree.unflatten(treedef, leaves), info.get("meta", {})


def load_meta(path: str) -> dict:
    """Read just the sidecar metadata (e.g. ``vcap``) without the arrays."""
    with open(path + ".json") as f:
        return json.load(f).get("meta", {})


def save_vertex_dict(path: str, vdict: VertexDict) -> None:
    np.save(path + ".vdict.npy", vdict.raw_ids())


def load_vertex_dict(path: str) -> VertexDict:
    raw = np.load(path + ".vdict.npy")
    d = VertexDict()
    d.encode(raw)
    return d


def save_aggregation(path: str, aggregation, vdict: Optional[VertexDict] = None) -> None:
    """Checkpoint an aggregation's running summary (+ optional dict).

    Device aggregations serialize as array pytrees; host-state aggregations
    (``device=False``, e.g. the spanner's adjacency map) pickle their summary
    object instead — np.asarray would wrap it in an object array that
    ``np.load`` refuses to read back.
    """
    if aggregation.device:
        save_pytree(path, aggregation.snapshot_state(), meta={"vcap": aggregation._vcap})
    else:
        import pickle

        with open(path + ".pkl", "wb") as f:
            pickle.dump(aggregation._summary, f)
    if vdict is not None:
        save_vertex_dict(path, vdict)


def restore_aggregation(path: str, aggregation, template: Any = None) -> Optional[VertexDict]:
    """Restore a checkpointed summary into ``aggregation``.

    For device aggregations the template defaults to
    ``aggregation.initial_state(vcap)`` with ``vcap`` read from the sidecar
    metadata — a resume site needs only the path and a fresh aggregation
    object. Pass ``template`` explicitly only for states whose structure
    ``initial_state`` does not produce. Host aggregations unpickle and ignore
    it. Returns the restored VertexDict if one was saved alongside, else None.
    """
    if aggregation.device:
        if template is None:
            vcap = load_meta(path).get("vcap")
            if vcap is None:
                raise ValueError(
                    f"checkpoint {path} has no vcap metadata; pass template="
                )
            template = aggregation.initial_state(vcap)
        state, meta = load_pytree(path, template)
        aggregation.restore_state(state, vcap=meta.get("vcap"))
    else:
        import pickle

        with open(path + ".pkl", "rb") as f:
            aggregation._summary = pickle.load(f)
    vd_path = path + ".vdict.npy"
    return load_vertex_dict(path) if os.path.exists(vd_path) else None


def save_workload(path: str, workload, vdict: Optional[VertexDict] = None) -> None:
    """Checkpoint any carried-state workload exposing ``state_dict()``
    (triangles, PageRank, degree distribution, spanner, samplers, SAGE,
    matching). The state is a plain dict of numpy arrays / scalars and is
    pickled — same trust model as the host-aggregation path above."""
    import pickle

    with open(path + ".workload.pkl", "wb") as f:
        pickle.dump(workload.state_dict(), f)
    if vdict is not None:
        save_vertex_dict(path, vdict)


def restore_workload(path: str, workload) -> Optional[VertexDict]:
    """Restore a :func:`save_workload` checkpoint into ``workload``.
    Returns the restored VertexDict when one was saved alongside."""
    import pickle

    with open(path + ".workload.pkl", "rb") as f:
        workload.load_state_dict(pickle.load(f))
    vd_path = path + ".vdict.npy"
    return load_vertex_dict(path) if os.path.exists(vd_path) else None


def restore_server(
    path: str,
    workload,
    source,
    *,
    template: Any = None,
    start: bool = True,
    **server_kwargs,
):
    """Boot a live query server from a checkpoint: restore ``workload``'s
    carried state (aggregation or ``state_dict`` workload checkpoints are
    both recognized by their sidecar files), publish the restored summary
    as the server's BOOT snapshot (window ``-1``), then serve while the
    ``source`` stream catches up — queries answer from the restored state
    immediately, before the first live window folds.

    ``source`` must be built against the same compact-id space as the
    checkpoint (pass the restored VertexDict into the stream, the
    existing resume contract); the boot payload resolves raw ids through
    the restored dict when one was saved alongside, else the source's.
    Returns the (started, unless ``start=False``) ``StreamServer``.
    """
    from ..serving import StreamServer

    if os.path.exists(path + ".workload.pkl"):
        vdict = restore_workload(path, workload)
    else:
        vdict = restore_aggregation(path, workload, template)
    if vdict is None:
        vdict = getattr(source, "vertex_dict", None)
    servable = workload.servable(vdict=vdict)
    server = StreamServer(servable, source, **server_kwargs)
    boot = servable.boot_payload()
    if boot is not None:
        payload, watermark = boot
        server.publish_boot(payload, watermark)
    return server.start() if start else server
