from .summary import SummaryAggregation, SummaryBulkAggregation, SummaryTreeReduce
from . import checkpoint
