"""The summary-aggregation engine: per-window fold + cross-shard combine +
carried global summary.

TPU-native re-design of the reference's L3 engine (``SummaryAggregation.java``,
``SummaryBulkAggregation.java``, ``SummaryTreeReduce.java``). The reference's
dataflow per window:

    stamp partition -> keyBy -> per-partition window fold(updateFun)
    -> timeWindowAll -> reduce(combineFun) -> Merger (parallelism 1,
    running summary, ListCheckpointed) -> optional transform

Here the same roles map to:

    shard the window's EdgeBlock over the mesh edge axis
    -> per-shard ``update`` from ``initial_state`` (the window fold)
    -> cross-shard ``combine`` via collectives (flat stack-and-fold for the
       bulk engine; log2(p) ppermute butterfly for the tree engine)
    -> host-carried running summary combined per window (the Merger)
    -> ``transform`` for emission.

Differences, by design (SURVEY.md §7 "semantic deltas"): the Merger emits
per *window*, not per incoming partial; every shard holds the global result
after the collective (the reference funnels to one subtask).

Subclasses supply the five state hooks (initial/update/combine/grow/
transform); ``device=False`` marks host-state aggregations (spanner,
matching) whose update/combine run on host records instead of device arrays.

Checkpoint surface (the reference's only fault-tolerance hook — ``Merger
implements ListCheckpointed``, ``SummaryAggregation.java:127-135``):
``snapshot_state()`` / ``restore_state()`` capture and restore the running
summary; see ``aggregate/checkpoint.py`` for (de)serialization.
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.edgeblock import EdgeBlock, StackedEdgeBlock
from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..parallel import comm
from ..parallel.mesh import EDGE_AXIS
from ..summaries.groupfold import GroupFoldable, drive_group_folded
from jax.sharding import PartitionSpec as P


#: Compiled window-step executables shared across aggregation instances,
#: keyed by (step_cache_key(), vcap, mesh, tree-ness). Compiling the fused
#: window program costs seconds on a remote TPU; a fresh aggregation object
#: per stream must not pay it again. Bounded FIFO: each cached closure
#: pins the aggregation instance it was built from (and thereby one
#: summary pytree), so unbounded growth would leak device arrays across
#: vcap buckets.
_STEP_CACHE: dict = {}
_STEP_CACHE_MAX = 16


def _step_cache_put(key, fn) -> None:
    if len(_STEP_CACHE) >= _STEP_CACHE_MAX:
        _STEP_CACHE.pop(next(iter(_STEP_CACHE)))
    _STEP_CACHE[key] = fn


class SummaryAggregation(GroupFoldable, abc.ABC):
    """Abstract engine config (``SummaryAggregation.java:22-137``).

    Parameters
    ----------
    transient_state:
        When True the running summary resets after each emission
        (``SummaryAggregation.java:113-115``).
    mesh:
        Optional ``jax.sharding.Mesh`` with an ``"edges"`` axis; falls back
        to the stream context's mesh, else single-device execution.
    superbatch:
        Fuse this many consecutive windows into ONE jitted dispatch — a
        ``lax.scan`` over a ``[K, cap]``
        :class:`~gelly_streaming_tpu.core.edgeblock.StackedEdgeBlock` —
        instead of K separate window steps. Amortizes the per-window
        fixed cost (host block assembly + dispatch) that dominates below
        ~64k-edge windows (the BENCH_CPU latency cliff: 714k eps at
        1024-edge windows vs 15.5M at 1M). Emission SEQUENCE is
        unchanged (one record per window, same values); emission TIMING
        batches — the K records of a superbatch surface together after
        its single dispatch, and the stacked per-window summaries cost
        K x summary bytes of device memory while their lazy emissions
        are live. ``1`` (default) keeps the per-window path.

    Contract for the state hooks (initial/update/combine): they must be
    pure functions of their arguments for a given constructor
    configuration. Subclasses whose constructor parameters change hook
    behavior declare them in ``config_fields`` — the default
    :meth:`step_cache_key` hashes those attribute values, so two
    differently-configured instances of one class can never silently
    share a compiled step (round-2 verdict #9 / advisor finding).
    """

    #: False for host-state aggregations (update/combine get host edge arrays)
    device: bool = True

    #: names of instance attributes whose values change the behavior of
    #: initial_state/update/combine/transform; hashed into the step-cache
    #: key. Values must be hashable.
    config_fields: tuple = ()

    def __init__(self, transient_state: bool = False, mesh=None,
                 superbatch=1):
        self.transient_state = transient_state
        self.mesh = mesh
        #: ``superbatch="auto"``: the run loop drives the fused-group
        #: path under an :class:`~gelly_streaming_tpu.control.AutoK`
        #: controller — K starts at 1 and is re-tuned at group
        #: boundaries from measured group throughput (+ span ratios
        #: when obs is on), with hysteresis and bounded steps;
        #: ``self.superbatch`` then tracks the LIVE operating point.
        self.superbatch_auto = superbatch == "auto"
        if self.superbatch_auto:
            superbatch = 1
        elif isinstance(superbatch, str):
            # a mistyped mode must fail with the accepted values, not
            # with an unrelated str-vs-int comparison TypeError below
            raise ValueError(
                f'superbatch must be an int >= 1 or "auto", '
                f"got {superbatch!r}"
            )
        elif superbatch < 1:
            raise ValueError(f"superbatch must be >= 1, got {superbatch}")
        self.superbatch = int(superbatch)
        #: the live ControlPlane of an auto run (None otherwise); tests
        #: and the bench read its AutoK history as retune evidence
        self.control = None
        self._summary = None
        self._vcap = 0
        self._sync_ref = None  # last dispatched window state (sync target)
        # run-loop context for the declared group fold (set by the
        # superbatched drive loops before drive_group_folded delegates
        # back into fold_group)
        self._gf_mesh = None
        self._gf_vdict = None
        #: whether the last superbatch dispatch DONATED the carried
        #: summary (in-place HBM update). Consumers that publish live
        #: carry buffers (``CCServable._payload``) read this to know
        #: they must copy — a published alias would be invalidated by
        #: the next group's dispatch.
        self._donated_carry = False

    def step_cache_key(self):
        """Hashable identity of the compiled window step (see class doc)."""
        return (type(self),) + tuple(
            getattr(self, f) for f in self.config_fields
        )

    # ------------------------------------------------------------------ #
    # State protocol (the updateFun / combineFun / transform slots)
    # ------------------------------------------------------------------ #
    @abc.abstractmethod
    def initial_state(self, vcap: int) -> Any:
        """Fresh per-window fold state (the ``initialValue`` analog)."""

    def grow_state(self, state: Any, old_vcap: int, new_vcap: int) -> Any:
        """Re-size carried state when the vertex capacity bucket grows."""
        raise NotImplementedError(
            f"{type(self).__name__} must implement grow_state to stream "
            "beyond its initial vertex capacity"
        )

    @abc.abstractmethod
    def update(self, state: Any, src, dst, val, mask) -> Any:
        """Fold one (shard of a) window into the state (``EdgesFold`` role).

        Device aggregations receive device arrays; host aggregations receive
        numpy arrays with padding already stripped.
        """

    @abc.abstractmethod
    def combine(self, a: Any, b: Any) -> Any:
        """Associative merge of two states (``combineFun`` role)."""

    def transform(self, state: Any, vdict) -> Any:
        """Map the running summary to the emitted record (optional)."""
        return state

    # ------------------------------------------------------------------ #
    # Engine
    # ------------------------------------------------------------------ #
    def _resolve_mesh(self, stream):
        mesh = self.mesh if self.mesh is not None else stream.get_context().mesh
        if mesh is None:
            return None
        if EDGE_AXIS not in mesh.shape or mesh.shape[EDGE_AXIS] == 1:
            return None
        return mesh

    def _make_partial_fn(self, vcap: int, mesh) -> Callable:
        """Build the traced one-window fold: per-shard ``update`` from
        ``initial_state`` + cross-shard combine. Shared by the per-window
        step and the superbatch scan body so the two paths cannot drift."""
        p = mesh.shape[EDGE_AXIS] if mesh is not None else 1
        tree = self._is_tree()
        # a fan-in the mesh cannot honor degrades to 2 with a warning
        # (reference posture; see SummaryTreeReduce docstring). Only
        # the tree engine runs the butterfly — resolving for bulk
        # aggregations would warn about a collective they never run.
        degree = (
            comm.resolve_tree_degree(p, getattr(self, "degree", 2))
            if tree and mesh is not None else 2
        )

        def partial_fn(src, dst, val, mask):
            init = self.initial_state(vcap)
            if mesh is None:
                return self.update(init, src, dst, val, mask)

            def shard_fn(src, dst, val, mask):
                part = self.update(init, src, dst, val, mask)
                if tree:
                    return comm.tree_all_reduce(
                        part, EDGE_AXIS, self.combine, p, degree=degree,
                    )
                return jax.tree.map(lambda x: x[None], part)

            in_specs = (
                P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)
            )
            out_specs = jax.tree.map(
                lambda _: P() if tree else P(EDGE_AXIS), init
            )
            out = comm.shard_map(shard_fn, mesh, in_specs, out_specs)(
                src, dst, val, mask
            )
            # bulk: stacked shard partials -> log-depth reduction
            # (the timeWindowAll gather analog)
            return out if tree else comm.stacked_reduce(out, p, self.combine)

        return partial_fn

    def _window_step(self, summary: Any, block: EdgeBlock, vcap: int, mesh) -> Any:
        """One window's full pipeline — per-shard fold, cross-shard combine,
        Merger merge — as ONE jitted dispatch (the keyBy->fold->reduce->
        Merger chain). Single-dispatch matters twice: host round trips
        never interleave the device pipeline, and successive windows
        overlap via async dispatch."""
        cache_key = (self.step_cache_key(), vcap, mesh, self._is_tree())
        step_fn = _STEP_CACHE.get(cache_key)
        if step_fn is None:
            partial_fn = self._make_partial_fn(vcap, mesh)

            def step(summary, src, dst, val, mask):
                return self.combine(summary, partial_fn(src, dst, val, mask))

            step_fn = jax.jit(step)
            _step_cache_put(cache_key, step_fn)
        # span measures DISPATCH (enqueue) time, not device compute —
        # the async-dispatch contract sync() documents; compile time
        # shows up as a fat first span, which is itself worth seeing
        with _trace.span(
            "engine.dispatch",
            {"vcap": vcap, "edges_capacity": int(block.capacity)}
            if _trace.on() else None,
        ):
            return step_fn(
                summary, block.src, block.dst, block.val, block.mask
            )

    def _superbatch_step(
        self, summary: Any, sblock: StackedEdgeBlock, vcap: int, mesh
    ) -> tuple:
        """K window steps as ONE jitted ``lax.scan`` over the stacked
        axis. Returns ``(carry, ys)``: the carried summary after all K
        windows, and the stacked per-window summaries ``[K, ...]`` that
        back the group's lazy emissions. ``transient_state`` resets the
        carry to a fresh ``initial_state`` INSIDE the scan (the per-yield
        reset of the per-window path, fused).

        The carried summary is DONATED to the dispatch when the backend
        supports donation and no mesh is involved: successive superbatches
        then update HBM state in place instead of allocating a fresh
        buffer per dispatch. Safe because the group's emissions reference
        ``ys`` (fresh buffers), never the donated carry, and the engine
        re-aims ``_summary``/``_sync_ref`` at the new carry immediately.
        """
        # ONE donation decision feeds the compiled donate_argnums, the
        # instance flag consumers read (see __init__), and the obs
        # evidence — computed once so they can never disagree
        donated = mesh is None and jax.default_backend() != "cpu"
        cache_key = ("superbatch", self.step_cache_key(), vcap,
                     sblock.capacity, sblock.k, mesh, self._is_tree(),
                     self.transient_state)
        step_fn = _STEP_CACHE.get(cache_key)
        if step_fn is None:
            partial_fn = self._make_partial_fn(vcap, mesh)
            transient = self.transient_state

            def superstep(summary, src, dst, val, mask):
                def body(carry, xs):
                    s, d, v, m = xs
                    new = self.combine(carry, partial_fn(s, d, v, m))
                    nxt = self.initial_state(vcap) if transient else new
                    return nxt, new

                return lax.scan(body, summary, (src, dst, val, mask))

            step_fn = jax.jit(
                superstep, donate_argnums=(0,) if donated else ()
            )
            _step_cache_put(cache_key, step_fn)
        self._donated_carry = donated
        if _trace.on():
            if donated:
                get_registry().counter("engine.donated_dispatches").inc()
            sp = _trace.span(
                "engine.superbatch_dispatch",
                {"k": int(sblock.k), "capacity": int(sblock.capacity),
                 "vcap": vcap, "donated": donated},
            )
        else:
            sp = _trace.NOOP_SPAN
        with sp:
            return step_fn(
                summary, sblock.src, sblock.dst, sblock.val, sblock.mask
            )

    def _is_tree(self) -> bool:
        return False

    def checkpoint_granularity(self) -> int:
        """Window stride at which the carried summary is observable — 1
        on the per-window path, ``superbatch`` when :meth:`run` will
        actually take the fused-group path. Checkpoint drivers
        (``aggregate/autockpt.py``) align barriers to this so a
        mid-group snapshot can never pair an end-of-group summary with
        a mid-group window count; subclasses whose run loop opts out of
        superbatching under extra conditions override it (the CC mixin
        does for ``transient_state``). Under ``superbatch="auto"`` this
        reports the LIVE operating K — barrier drivers align exactly
        through :meth:`~gelly_streaming_tpu.summaries.groupfold.GroupFoldable.checkpoint_aligned`,
        which tracks the variable group boundaries themselves."""
        if self.device and (self.superbatch > 1 or self.superbatch_auto):
            return max(1, self.superbatch)
        return 1

    def _device_block(self, block: EdgeBlock, mesh) -> None:
        """Grow + fold one block into the carried summary (the device
        branch of :meth:`run`, extracted so subclasses with a custom run
        loop — e.g. the forest-carry CC — can fall back to it)."""
        vcap = block.n_vertices
        if self._summary is None:
            self._vcap = vcap
            self._summary = self.initial_state(vcap)
        elif vcap > self._vcap:
            self._summary = self.grow_state(self._summary, self._vcap, vcap)
            self._vcap = vcap
        self._summary = self._window_step(self._summary, block, vcap, mesh)

    def run(self, stream) -> Iterator[Any]:
        """Drive the aggregation over the stream's windows
        (``SummaryAggregation.run`` / ``SummaryBulkAggregation.java:68-90``).

        With ``superbatch=K > 1`` (device aggregations only), K
        consecutive windows run as one fused ``lax.scan`` dispatch and
        still yield one record per window with identical values — only
        the records of a group surface together, after its dispatch.
        CHECKPOINT GRANULARITY under superbatching: the carried summary
        is only observable on superbatch boundaries (mid-group states
        exist solely as stacked emission rows), so checkpoint barriers
        must land on multiples of K —
        :class:`~gelly_streaming_tpu.aggregate.autockpt.AutoCheckpoint`
        aligns its ``every`` to the work's
        :meth:`checkpoint_granularity` automatically; manual
        ``snapshot_state()`` calls between a group's yields capture the
        END-of-group summary, not the mid-group window's. Vertex
        capacity growth likewise quantizes to group boundaries (see
        :meth:`_fold_group_states`). Feed the loop
        with a prefetched stream whose depth covers a full group
        (:func:`~gelly_streaming_tpu.core.pipeline.superbatch_prefetch_depth`)
        so the host assembles superbatch N+1 while the device scans N.
        """
        mesh = self._resolve_mesh(stream) if self.device else None
        vdict = stream.vertex_dict
        if self.device and (self.superbatch > 1 or self.superbatch_auto):
            yield from self._run_superbatched(stream, mesh, vdict)
            return
        for block in stream.blocks():
            if self.device:
                self._device_block(block, mesh)
            else:
                src, dst, val = block.to_host()
                raw_s = vdict.decode(src)
                raw_d = vdict.decode(dst)
                if self._summary is None:
                    self._summary = self.initial_state(0)
                partial = self.update(
                    self.initial_state(0), raw_s, raw_d, val, None
                )
                self._summary = self.combine(self._summary, partial)
            self._sync_ref = self._summary
            yield self.transform(self._summary, vdict)
            if self.transient_state:
                self._summary = (
                    self.initial_state(self._vcap) if self.device else self.initial_state(0)
                )

    def _run_superbatched(self, stream, mesh, vdict) -> Iterator[Any]:
        """The fused-group drive loop — the engine's
        :class:`~gelly_streaming_tpu.summaries.groupfold.GroupFoldable`
        declaration driven by the shared
        :func:`~gelly_streaming_tpu.summaries.groupfold.drive_group_folded`
        loop (groups from the stream's packer, prefetched one ahead so
        the host assembles superbatch N+1 while the device scans N).
        ``superbatch="auto"`` attaches a fresh
        :class:`~gelly_streaming_tpu.control.ControlPlane` (AutoK +
        adaptive group prefetch over one SignalReader) and lets the
        drive loop re-tile at group boundaries."""
        self._gf_mesh = mesh
        self._gf_vdict = vdict
        yield from drive_group_folded(
            self, stream, self.superbatch,
            controller=self._attach_control(self.superbatch),
        )

    def _attach_control(self, k: int):
        """The ONE ``superbatch="auto"`` controller-attach rule for
        every group-folded run loop (engine, CC, bipartiteness): None
        unless auto; a pre-set plane is honored (the injection seam —
        pin the knob via ``AutoK(k0=K, k_max=K)``, or share one
        SignalReader across loops); otherwise the stock
        :func:`~gelly_streaming_tpu.control.default_plane` is built
        and kept on ``self.control``."""
        if not self.superbatch_auto:
            return None
        if self.control is None:
            from ..control import default_plane

            self.control = default_plane(k)
        return self.control

    def fold_group(self, group) -> Iterator[Any]:
        """The engine's declared group fold (see
        :class:`~gelly_streaming_tpu.summaries.groupfold.GroupFoldable`):
        one fused scan over the group's stacked block, per-window
        summaries unstacked lazily. Supports EVERY group — device-
        transformed members dispatch on the device stack."""
        for state in self._fold_group_states(group, self._gf_mesh):
            yield self.transform(state, self._gf_vdict)

    def _fold_group_states(self, group, mesh) -> Iterator[Any]:
        """Grow + fold one :class:`SuperbatchGroup` through the fused
        scan, yielding the K per-window summary states (shared by the
        engine loop and the CC mixin's dense group path).

        Capacity growth quantizes to GROUP boundaries here: a group
        whose windows grow the vertex table folds (and emits) every
        window at the group's FINAL capacity — scatter-style summaries
        are value-identical on the shared prefix with initial-state
        tails, but an aggregation whose update/transform depends on the
        table SIZE itself observes the quantized capacity one group
        early. Per-window growth semantics need the per-window path."""
        from ..core.emission import iter_unstacked

        vmax = max(1, group.n_vertices)
        if self._summary is None:
            self._vcap = vmax
            self._summary = self.initial_state(self._vcap)
        elif vmax > self._vcap:
            self._summary = self.grow_state(self._summary, self._vcap, vmax)
            self._vcap = vmax
        carry, ys = self._superbatch_step(
            self._summary, group.stacked(), self._vcap, mesh
        )
        # the carry IS the post-reset summary under transient_state
        # (the scan body resets it), so one assignment serves both
        self._summary = carry
        self._sync_ref = carry
        yield from iter_unstacked(ys, len(group))

    def sync(self) -> None:
        """Block until the carried summary's device work completes — the
        end-of-stream barrier. The aggregate loop only DISPATCHES async
        device steps; anyone timing throughput (bench.py does) must call
        this inside the timed region, or they measure an enqueue rate.
        Per-window emissions stay async/lazy either way. Also blocks the
        last DISPATCHED window state: with ``transient_state`` the run
        loop resets ``_summary`` to a fresh initial state after each
        yield, which would otherwise make this a silent no-op barrier."""
        jax.block_until_ready((self._summary, self._sync_ref))

    # ------------------------------------------------------------------ #
    # Checkpoint surface (ListCheckpointed analog)
    # ------------------------------------------------------------------ #
    def snapshot_state(self) -> Any:
        """The running summary, as a host pytree
        (``SummaryAggregation.java:127-130`` snapshotState)."""
        return jax.tree.map(np.asarray, self._summary)

    def infer_vcap(self, state: Any) -> int:
        """Vertex capacity implied by a state pytree (override when the
        leading dim is not the base vertex capacity, e.g. double covers)."""
        leaves = jax.tree.leaves(state)
        return int(leaves[0].shape[0]) if leaves else 0

    def restore_state(self, state: Any, vcap: Optional[int] = None) -> None:
        """Restore a summary captured by :meth:`snapshot_state`
        (``SummaryAggregation.java:132-135`` restoreState)."""
        self._summary = jax.tree.map(jnp.asarray, state) if self.device else state
        if vcap is not None:
            self._vcap = vcap
        elif self.device:
            self._vcap = self.infer_vcap(self._summary)


class SummaryBulkAggregation(SummaryAggregation):
    """Flat-combine engine (``SummaryBulkAggregation.java:51-131``):
    per-shard fold, then a stack-and-fold global combine — the analog of the
    ``timeWindowAll`` gather + reduce + Merger tail."""

    def _is_tree(self) -> bool:
        return False


class SummaryTreeReduce(SummaryAggregation):
    """Tree-combine engine (``SummaryTreeReduce.java:47-160``): the shard
    partials merge through a ``log_degree(p)``-round ppermute butterfly
    (:func:`gelly_streaming_tpu.parallel.comm.tree_all_reduce`), the ICI
    equivalent of ``enhance()``'s recursive parallelism reduction
    (``SummaryTreeReduce.java:95-123``).

    ``degree`` here GENERALIZES the reference rather than mirroring it:
    the reference's ``degree`` sets the partial-aggregation parallelism
    (``setParallelism(degree)``) while ``enhance()``'s fan-in is fixed
    at 2 (``key = f0/2``, ``nextParal = p/2``); the butterfly promotes
    it to a true tree fan-in — higher degrees run fewer collective
    rounds with more combines per round. A degree the mesh edge axis
    cannot honor (the axis size must be a power of the fan-in) degrades
    to the degree-2 butterfly with a warning, matching the reference's
    warn-and-run posture for non-conforming degrees
    (:func:`~gelly_streaming_tpu.parallel.comm.resolve_tree_degree`).
    The combine must be commutative as well as associative — all engine
    workloads' join-semilattice merges are."""

    #: degree changes the compiled collective program
    config_fields: tuple = ("degree",)

    def __init__(self, transient_state: bool = False, mesh=None,
                 degree: int = 2, superbatch: int = 1):
        super().__init__(transient_state=transient_state, mesh=mesh,
                         superbatch=superbatch)
        if degree < 2:
            raise ValueError(f"degree must be >= 2, got {degree}")
        self.degree = degree

    def _is_tree(self) -> bool:
        return True
