"""Wire-level query serving: the RPC front end on ``StreamServer.submit``.

Until now the query path ended at the process boundary: PR 7 put the
TELEMETRY half of the serving tier on the wire (``obs/endpoint.py``'s
scrape surface), but no client could reach ``submit`` from another
process. This module is the query half, kept on the same stdlib-only
stance:

- **Length-prefixed binary frames** (:data:`MAGIC` + version + type +
  payload length, then a compact JSON body). Framing is the contract a
  TCP stream needs: a reader always knows where one message ends, a
  torn read is DETECTABLE (``rpc.malformed{kind=truncated}``) instead
  of a parser wedged mid-garbage, and oversized/garbage input is
  rejected per-connection without touching the handler thread's life.
- **Batched at the socket boundary**: one REQ frame carries a whole
  query batch under ONE idempotent batch id — the wire analog of the
  worker's drain-and-coalesce discipline, so a chatty client cannot
  force per-query dispatches.
- **Async answer delivery**: the handler thread only parses and admits;
  answers ride the queries' future callbacks (the server worker's
  thread) back onto the connection, so a slow sweep never blocks the
  read loop and responses may complete out of submission order
  (clients match on the batch id).
- **The existing semantics travel**: :class:`~.server.Overloaded`
  becomes the retryable wire status ``overloaded`` (the CLIENT honors
  its :class:`~gelly_streaming_tpu.resilience.RetryPolicy`; the server
  never sleeps a handler thread), :class:`~.server.Shed` is terminal
  (``shed`` — clients must not retry; shedding exists to lose exactly
  that traffic), and a per-query ``deadline_s`` rides the frame and
  expires SERVER-SIDE through ``StreamServer``'s own deadline sweep.

Cross-process failover (:class:`ReplicaServer`) extends the in-process
:class:`~.failover.FailoverServer` story to a standby serving BINARY:
the primary mirrors every published snapshot into a shared directory
(:class:`~.snapshot_store.SnapshotMirror` — CRC-framed, atomic-commit)
and maintains a heartbeat lease there (:class:`HeartbeatLease`, same
commit discipline); the standby process follows the directory
(:func:`~.snapshot_store.follow_snapshots`), answers ``not_primary``
to keep clients pointed at the primary, and PROMOTES itself when the
lease lapses — counting ``serving.lease_lapse`` +
``serving.failover{reason=lease_lapse}`` and observing
``serving.promotion_seconds``, so a cross-process takeover renders in
the same timeline vocabulary as the in-process one. Ingest is not
failed over (the primary owned it); the standby keeps serving the
newest mirrored snapshot — the keep-serving-from-final-state contract,
now across processes. Clients (:class:`~.client.RpcClient`) reconnect
and RESUBMIT in-flight batches under their original ids; the server's
dedupe cache makes double delivery harmless, so a primary kill is
client-visible only as a latency blip.

``python -m gelly_streaming_tpu.serving.rpc --smoke`` is the CI gate:
it boots a primary + standby replica pair as real subprocesses,
round-trips a query batch over real sockets, SIGKILLs the primary, and
asserts the client's retry lands on the promoted standby.
"""

from __future__ import annotations

import json
import os
import socket as _socket
import threading
import time
from collections import OrderedDict
from functools import partial
from typing import Callable, List, Optional, Tuple

from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience import faults as _faults
from .query import (
    Answer,
    BipartiteQuery,
    ComponentSizeQuery,
    ConnectedQuery,
    DegreeQuery,
    Query,
    RankQuery,
    SummaryPullQuery,
)
from .server import Overloaded, Shed, StreamServer
from .snapshot_store import (
    SnapshotMirror,
    SnapshotStore,
    follow_snapshots,
)
from .txn import TxnSnapshotExpired, active_txn_count, decode_txn, note_txn

# --------------------------------------------------------------------- #
# Wire format — the GSRP framing moved into the cluster fabric
# (fabric/wire.py, ISSUE 16) so the exchange daemon speaks the same
# frames; re-exported here because every RPC consumer (client, router,
# ingest, the fuzz tests) imports it from this module.
# --------------------------------------------------------------------- #
from ..fabric.wire import (  # noqa: E402  (re-export)
    DEFAULT_MAX_FRAME,
    HEADER,
    MAGIC,
    T_REQ,
    T_RESP,
    VERSION,
    Disconnect,
    MalformedFrame,
    pack_frame,
    read_frame,
    recv_exact,
)

# batch-level wire statuses
OK = "ok"
OVERLOADED = "overloaded"      # retryable: admission limit reached
SHED = "shed"                  # terminal: class is load-shed, never retry
NOT_PRIMARY = "not_primary"    # retryable elsewhere: replica is standby
BAD_REQUEST = "bad_request"    # terminal: the frame parsed, the request didn't
ERROR = "error"                # terminal: server-side failure

#: statuses a client may retry (everything else is terminal)
RETRYABLE = frozenset({OVERLOADED, NOT_PRIMARY})


class Wire:
    """One framed socket endpoint: serialized sends, frame-counted
    reads, both threaded through the fault plan's socket sites
    (``rpc.frame`` disconnects on the read path, one-shot frame
    truncation on the send path)."""

    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()
        self.sent = 0
        self.rcvd = 0

    def send(self, data: bytes) -> None:
        # wlock exists to SERIALIZE frame writes on one socket — a
        # frame interleaved mid-frame is wire corruption, so blocking
        # the next sender until this frame is fully out is the lock's
        # entire purpose, not contention (GL009 suppressions below)
        with self.wlock:
            idx = self.sent
            self.sent = idx + 1
            if _faults.active() and _faults.rpc_truncate(idx):
                # the torn-write shape on the wire: half a frame, then
                # the connection dies — the peer must count a clean
                # rpc.malformed{kind=truncated}, never a thread death
                try:
                    self.sock.sendall(data[: max(1, len(data) // 2)])  # graftlint: disable=GL009 (wlock is the per-socket frame-write serializer; blocking the next sender until this frame is out is its purpose)
                finally:
                    self.close()
                raise ConnectionAbortedError("injected frame truncation")
            self.sock.sendall(data)  # graftlint: disable=GL009 (wlock is the per-socket frame-write serializer; blocking the next sender until this frame is out is its purpose)

    def read(self, *, max_frame: int = DEFAULT_MAX_FRAME
             ) -> Tuple[int, bytes]:
        ftype, payload = read_frame(self.sock, max_frame=max_frame)
        if _faults.active():
            _faults.fire("rpc.frame", index=self.rcvd)
        self.rcvd += 1
        return ftype, payload

    def close(self) -> None:
        # shutdown BEFORE close: a reader blocked in recv on another
        # thread only wakes reliably on shutdown — close alone can
        # leave it hanging until its own next byte (ENOTCONN from an
        # already-reset peer is the normal case, not an event)
        try:
            self.sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            # double close / already-reset socket: nothing left to
            # release, but keep the event visible
            get_registry().counter(
                "rpc.swallowed", site="wire_close"
            ).inc()


# --------------------------------------------------------------------- #
# Query / answer codec (wire <-> serving/query.py types)
# --------------------------------------------------------------------- #
_Q_KINDS = {
    "C": (ConnectedQuery, 2),
    "D": (DegreeQuery, 1),
    "R": (RankQuery, 1),
    "S": (ComponentSizeQuery, 1),
    "P": (SummaryPullQuery, 0),
    "B": (BipartiteQuery, 0),
}
_Q_TAGS = {
    ConnectedQuery: "C",
    DegreeQuery: "D",
    RankQuery: "R",
    ComponentSizeQuery: "S",
    SummaryPullQuery: "P",
    BipartiteQuery: "B",
}


def encode_queries(queries) -> List[list]:
    out = []
    for q in queries:
        tag = _Q_TAGS.get(type(q))
        if tag is None:
            raise TypeError(
                f"{type(q).__name__} has no wire encoding"
            )
        if tag == "C":
            out.append([tag, int(q.u), int(q.v)])
        elif tag == "P":
            # protocol v2: the delta baseline rides as an OPTIONAL
            # trailing field — a v1-shaped pull (since_version < 0)
            # stays the bare ["P"] item old servers already accept
            if q.since_version >= 0:
                out.append([tag, int(q.since_version)])
            else:
                out.append([tag])
        elif tag == "B":
            out.append([tag])
        else:
            out.append([tag, int(q.v)])
    return out


def decode_queries(items) -> List[Query]:
    out: List[Query] = []
    for it in items:
        cls, arity = _Q_KINDS.get(it[0], (None, 0))
        if cls is None:
            raise ValueError(f"unknown or malformed query item {it!r}")
        if cls is SummaryPullQuery:
            # arity 0 (v1) or 1 (v2 with since_version) both decode
            if len(it) not in (1, 2):
                raise ValueError(
                    f"unknown or malformed query item {it!r}")
        elif len(it) != arity + 1:
            raise ValueError(f"unknown or malformed query item {it!r}")
        out.append(cls(*(int(x) for x in it[1:])))
    return out


def encode_answer(ans: Answer, shard: Optional[int] = None) -> list:
    v = ans.value
    if hasattr(v, "item"):
        v = v.item()
    # the trailing snapshot version is what a routing tier keys its
    # hot-key cache invalidation on; the event-time watermark stamp
    # after it says how far behind the WORLD the answer is; the shard
    # index + boot lineage after THAT complete the reply stamp a
    # snapshot-pinned transaction pins its vector from (ISSUE 20) —
    # decoders tolerate the absence of any trailing field, so v1
    # peers stay interoperable (GL011: written here, read in
    # client._settle_ok)
    s = int(ans.shard)
    if s < 0 and shard is not None:
        s = int(shard)
    return ["ok", v, ans.window, ans.watermark, ans.staleness,
            ans.version, ans.event_ts, s, ans.boot]


# --------------------------------------------------------------------- #
# Server
# --------------------------------------------------------------------- #
class _Batch:
    """One in-flight wire batch: futures + answer slots + the delivery
    connection (re-homed when the client resubmits on a new socket).
    ``ctx``/``t_recv``/``decode_s``/``admit_s`` carry the batch's trace
    context and stage timings from the handler thread to the worker
    callback that emits the server-side spans (set only when tracing
    was on at receive time)."""

    __slots__ = ("id", "conn", "futures", "slots", "remaining",
                 "ctx", "t_recv", "decode_s", "admit_s")

    def __init__(self, qid: str, conn: Wire, futures: list):
        self.id = qid
        self.conn = conn
        self.futures = futures
        self.slots: list = [None] * len(futures)
        self.remaining = len(futures)
        self.ctx = None
        self.t_recv = 0.0
        self.decode_s = 0.0
        self.admit_s = 0.0


class RpcServer:
    """Socket front end over anything with ``StreamServer.submit``'s
    contract (a ``StreamServer``, a ``FailoverServer``, a
    ``ReplicaServer``'s inner server).

    ``gate`` (optional) is consulted per batch BEFORE admission: return
    None to serve, or a wire status (``not_primary``) to refuse — the
    standby replica's refusal hook. ``port=0`` binds an ephemeral port
    (read it back from :attr:`port`).

    Answered batches are cached (``dedupe_cap`` most recent) under
    their idempotent batch id: a client that lost the response to a
    disconnect RESUBMITS the same id and gets the cached answer
    (``rpc.deduped``) instead of recomputing; a resubmit that catches
    the batch still in flight just re-homes its delivery connection.
    """

    def __init__(
        self,
        server,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        gate: Optional[Callable[[], Optional[str]]] = None,
        max_frame: int = DEFAULT_MAX_FRAME,
        dedupe_cap: int = 1024,
        epoch: Optional[Callable[[], int]] = None,
        shard: Optional[int] = None,
        txn_narrow: bool = True,
    ):
        self.server = server
        self.host = host
        self._port = int(port)
        self.gate = gate
        # ownership-epoch provider (serving.reshard): when it returns
        # > 0, reply frames carry the epoch so routers learn of live
        # splits from ordinary traffic, no control channel needed
        self.epoch = epoch
        # this replica's shard index: stamps every reply answer (the
        # pin source a TxnContext observes) and narrows an inbound txn
        # VECTOR down to the one pin this shard must honor (ISSUE 20)
        self.shard = None if shard is None else int(shard)
        # False for a ROUTER front end: a router fans a txn VECTOR out
        # across shards itself, so the decoded txn must pass through
        # un-narrowed (narrowing here would drop a multi-shard vector
        # on the floor — the front end has no single shard identity)
        self.txn_narrow = bool(txn_narrow)
        # one-time probe: does the inner server's submit path accept
        # the txn kwarg? A server without it IS a v1 txn-unaware peer
        # — the pin is dropped here and the CLIENT detects the unpinned
        # answer from the reply stamp, failing the read honestly
        import inspect

        self._txn_kwarg = False
        try:
            target = getattr(server, "submit_many", None) \
                or getattr(server, "submit", None)
            if target is not None:
                self._txn_kwarg = (
                    "txn" in inspect.signature(target).parameters
                )
        except (TypeError, ValueError):
            pass
        self.max_frame = int(max_frame)
        self.dedupe_cap = int(dedupe_cap)
        self._lock = threading.Lock()
        self._conns: set = set()
        self._done: "OrderedDict[str, bytes]" = OrderedDict()
        self._inflight: dict = {}
        self._listener = None
        self._accept_thread: Optional[threading.Thread] = None
        self._closing = threading.Event()

    # ------------------------------------------------------------------ #
    @property
    def port(self) -> int:
        return self._port

    @property
    def address(self) -> str:
        return f"{self.host}:{self._port}"

    def start(self) -> "RpcServer":
        if self._listener is not None:
            raise RuntimeError("rpc server already started")
        s = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        try:
            s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
            s.bind((self.host, self._port))
            s.listen(128)
            # a bounded accept timeout is the shutdown path: closing a
            # listener does NOT wake a thread blocked in accept on
            # Linux, so the loop polls the closing flag at this cadence
            s.settimeout(0.25)
        except OSError:
            # bind/listen failed (port taken, perms): the caller gets
            # the error, not a leaked listener fd (GL010)
            s.close()
            raise
        self._listener = s
        self._port = s.getsockname()[1]
        self._accept_thread = threading.Thread(
            target=self._accept, name="rpc-accept", daemon=True
        )
        self._accept_thread.start()
        return self

    def __enter__(self) -> "RpcServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _accept(self) -> None:
        while not self._closing.is_set():
            try:
                sock, _addr = self._listener.accept()
            except TimeoutError:
                continue  # the closing-flag poll cadence
            except OSError:
                if self._closing.is_set():
                    return
                get_registry().counter(
                    "rpc.swallowed", site="accept"
                ).inc()
                continue
            try:
                sock.settimeout(None)
                sock.setsockopt(_socket.IPPROTO_TCP,
                                _socket.TCP_NODELAY, 1)
            except OSError:
                # a peer that connected and reset immediately: config
                # on its socket can raise — that must drop THIS socket
                # (closed, counted), never kill the accept thread and
                # leave the whole server deaf (GL010)
                get_registry().counter(
                    "rpc.swallowed", site="accept_config"
                ).inc()
                sock.close()
                continue
            conn = Wire(sock)
            with self._lock:
                if self._closing.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            get_registry().counter("rpc.connects").inc()
            threading.Thread(
                target=self._handle, args=(conn,),
                name="rpc-conn", daemon=True,
            ).start()

    # ------------------------------------------------------------------ #
    def _handle(self, conn: Wire) -> None:
        """Per-connection read loop. EVERY exit path is per-connection:
        malformed bytes, injected disconnects, and peer resets end THIS
        socket (counted), never the handler pool or the server."""
        reg = get_registry()
        try:
            while not self._closing.is_set():
                try:
                    ftype, payload = conn.read(max_frame=self.max_frame)
                except Disconnect:
                    return
                except MalformedFrame as e:
                    reg.counter("rpc.malformed", kind=e.kind).inc()
                    self._respond(conn, None, ERROR,
                                  error=f"malformed frame: {e.kind}")
                    return
                except ConnectionResetError:
                    # the fault plan's injected mid-stream disconnect
                    # (rpc.frame site) or a real peer reset between
                    # frames: clean per-connection teardown
                    return
                if ftype != T_REQ:
                    reg.counter("rpc.malformed", kind="type").inc()
                    self._respond(conn, None, ERROR,
                                  error=f"unexpected frame type {ftype}")
                    return
                t_recv = time.perf_counter()
                doc = None
                try:
                    doc = json.loads(payload.decode("utf-8"))
                    qid = str(doc["id"])
                    queries = decode_queries(doc["q"])
                    deadline_s = doc.get("deadline_s")
                    # coerce HERE, not at submit: a non-numeric
                    # deadline must be a terminal bad_request, never a
                    # handler-thread death inside _admit's float()
                    if deadline_s is not None:
                        deadline_s = float(deadline_s)
                except (ValueError, KeyError, TypeError,
                        UnicodeDecodeError) as e:
                    reg.counter("rpc.malformed", kind="request").inc()
                    bad_id = doc.get("id") if isinstance(doc, dict) \
                        else None
                    self._respond(conn, bad_id, BAD_REQUEST,
                                  error=repr(e)[:200])
                    continue
                # trace extraction is GATED: the tc field is parsed and
                # a context allocated only when tracing is on (the
                # disabled wire path stays allocation-identical to
                # PR 8's); a missing/garbage tc is an untraced batch
                ctx = None
                decode_s = 0.0
                if _trace.on():
                    ctx = _trace.TraceContext.from_wire(doc.get("tc"))
                    decode_s = time.perf_counter() - t_recv
                    if ctx is not None:
                        _trace.record_span(
                            "rpc.decode", decode_s,
                            trace_id=ctx.trace_id,
                            parent=ctx.parent_sid,
                            attrs={"id": qid},
                        )
                # the txn field is OPTIONAL and tolerant: absent or
                # garbage decodes as None (unpinned request); a v1
                # client never sends it, a v1 server never reads it
                txn = decode_txn(doc.get("txn"))
                self._serve_batch(conn, qid, queries, deadline_s,
                                  ctx, t_recv, decode_s, txn=txn)
        finally:
            with self._lock:
                self._conns.discard(conn)
            conn.close()
            reg.counter("rpc.disconnects").inc()

    def _serve_batch(self, conn: Wire, qid: str, queries: list,
                     deadline_s, ctx=None, t_recv: float = 0.0,
                     decode_s: float = 0.0, txn=None) -> None:
        reg = get_registry()
        if txn is not None:
            note_txn(txn.get("id", ""))
            if self.txn_narrow:
                txn = self._narrow_txn(txn)
        with self._lock:
            cached = self._done.get(qid)
            if cached is not None:
                self._done.move_to_end(qid)
            inflight = None
            if cached is None:
                inflight = self._inflight.get(qid)
                if inflight is not None:
                    # the client resubmitted (reconnect) while the
                    # batch is still being answered: deliver to the
                    # NEW connection, don't recompute
                    inflight.conn = conn
        if cached is not None:
            reg.counter("rpc.deduped").inc()
            self._send(conn, cached)
            return
        if inflight is not None:
            reg.counter("rpc.deduped").inc()
            return
        gate = self.gate
        refusal = gate() if gate is not None else None
        if refusal is not None:
            reg.counter("rpc.not_primary").inc()
            self._respond(conn, qid, refusal)
            return
        t_admit = time.perf_counter()
        futures: list = []
        # one-lock batch admission when the server offers it (the
        # whole-frame fast path); the per-query loop stays the
        # compatibility path for bare submit-only servers
        many = getattr(self.server, "submit_many", None)
        # the txn kwarg rides only when the probe found it: a server
        # without it is a v1 peer — the pin is DROPPED here and the
        # client fails the unpinned answer honestly via the reply stamp
        kw = {}
        if txn is not None and self._txn_kwarg:
            kw["txn"] = txn
        try:
            if many is not None:
                futures = many(queries, deadline_s=deadline_s, ctx=ctx,
                               **kw)
            else:
                for q in queries:
                    futures.append(
                        self.server.submit(q, deadline_s=deadline_s,
                                           ctx=ctx, **kw)
                    )
        except Shed as e:
            self._cancel(futures)
            self._respond(conn, qid, SHED, error=str(e)[:200])
            return
        except Overloaded as e:
            # a partial batch must not half-admit: cancel what slipped
            # in and report the whole batch retryable — queries are
            # idempotent reads, so the client's full resubmit is safe
            self._cancel(futures)
            self._respond(conn, qid, OVERLOADED, error=str(e)[:200])
            return
        except TypeError as e:
            self._cancel(futures)
            self._respond(conn, qid, BAD_REQUEST, error=str(e)[:200])
            return
        except RuntimeError as e:
            self._cancel(futures)
            self._respond(conn, qid, ERROR, error=str(e)[:200])
            return
        except Exception as e:
            # the no-thread-death contract is structural, not an
            # enumeration: ANY admission-path surprise fails THIS
            # batch terminally (counted), never the handler thread
            self._cancel(futures)
            reg.counter("rpc.answer_errors").inc()
            self._respond(conn, qid, ERROR, error=repr(e)[:200])
            return
        batch = _Batch(qid, conn, futures)
        if _trace.on() and ctx is not None:
            batch.ctx = ctx
            batch.t_recv = t_recv
            batch.decode_s = decode_s
            batch.admit_s = time.perf_counter() - t_admit
            _trace.record_span(
                "rpc.admit", batch.admit_s,
                trace_id=ctx.trace_id, parent=ctx.parent_sid,
                attrs={"n": len(queries)},
            )
        with self._lock:
            self._inflight[qid] = batch
        reg.counter("rpc.batches").inc()
        reg.counter("rpc.queries").inc(len(queries))
        for i, f in enumerate(futures):
            f.add_done_callback(partial(self._one_done, batch, i))

    def _narrow_txn(self, txn: dict) -> Optional[dict]:
        """Narrow a wire txn down to THIS shard's single pin.

        A router-directed sub-request already carries ``pin``; a
        client's direct request carries the full ``vec`` — only the
        entry for this replica's shard (or the sole entry, for an
        unsharded deployment) applies here. A vector with no entry for
        this shard means the transaction has not pinned it yet: the
        request runs unpinned and the ANSWER's stamp does the pinning.
        """
        if txn.get("pin") is not None:
            return txn
        vec = txn.get("vec")
        if not vec:
            return None  # bare id: nothing pinned yet
        pin = None
        if self.shard is not None:
            pin = vec.get(self.shard)
        elif len(vec) == 1:
            pin = next(iter(vec.values()))
        if pin is None:
            return None
        return {"id": txn.get("id", ""), "pin": pin, "vec": None}

    @staticmethod
    def _cancel(futures: list) -> None:
        for f in futures:
            f.cancel()

    def _one_done(self, batch: _Batch, i: int, fut) -> None:
        """Future callback (the serving worker's thread): record one
        answer slot; the LAST slot serializes and delivers the batch."""
        batch.slots[i] = self._encode_result(fut)
        with self._lock:
            batch.remaining -= 1
            if batch.remaining:
                return
            self._inflight.pop(batch.id, None)
        t_reply = time.perf_counter()
        doc = {"id": batch.id, "status": OK, "answers": batch.slots}
        if self.epoch is not None:
            try:
                ep = int(self.epoch())
            except Exception:
                # a broken epoch provider must never cost an answer;
                # the frame just rides without the stamp, counted
                get_registry().counter(
                    "rpc.swallowed", site="epoch_probe").inc()
                ep = 0
            if ep > 0:
                doc["epoch"] = ep
        data = pack_frame(T_RESP, json.dumps(doc).encode("utf-8"))
        with self._lock:
            self._done[batch.id] = data
            while len(self._done) > self.dedupe_cap:
                self._done.popitem(last=False)
            conn = batch.conn
        self._send(conn, data)
        if _trace.on() and batch.ctx is not None:
            # wire reply (serialize + send) and the whole server-side
            # residence of the batch: recv -> last answer on the wire.
            # The residence span is what the attribution table compares
            # against the client's own end-to-end measurement.
            now = time.perf_counter()
            ctx = batch.ctx
            _trace.record_span(
                "rpc.reply", now - t_reply,
                trace_id=ctx.trace_id, parent=ctx.parent_sid,
            )
            _trace.record_span(
                "rpc.server.batch", now - batch.t_recv,
                trace_id=ctx.trace_id, parent=ctx.parent_sid,
                attrs={
                    "n": len(batch.slots),
                    "decode_s": round(batch.decode_s, 6),
                    "admit_s": round(batch.admit_s, 6),
                    "reply_s": round(now - t_reply, 6),
                },
            )

    def _encode_result(self, fut) -> list:
        from concurrent.futures import CancelledError

        from ..resilience.errors import DeadlineExceeded

        try:
            ans = fut.result(0)
        except DeadlineExceeded as e:
            return ["deadline", str(e)[:200]]
        except CancelledError:
            return ["error", "cancelled"]
        except TxnSnapshotExpired as e:
            # typed HONEST expiry on the wire: the client re-raises it
            # per answer — a pinned read whose snapshot is gone fails,
            # it is never quietly handed a fresher answer (already
            # counted txn.snapshot_expired at the raise site)
            return ["txn_expired", str(e)[:200],
                    getattr(e, "kind", "expired")]
        except BaseException as e:
            get_registry().counter("rpc.answer_errors").inc()
            return ["error", repr(e)[:200]]
        return encode_answer(ans, shard=self.shard)

    # ------------------------------------------------------------------ #
    def _respond(self, conn: Wire, qid, status: str,
                 error: Optional[str] = None) -> None:
        doc = {"id": qid, "status": status}
        if error:
            doc["error"] = error
        self._send(conn, pack_frame(
            T_RESP, json.dumps(doc).encode("utf-8")
        ))

    def _send(self, conn: Wire, data: bytes) -> None:
        try:
            conn.send(data)
        except OSError:
            # the connection died under the answer; the response stays
            # in the dedupe cache, so the client's resubmit on its next
            # connection collects it — count the undelivered send
            get_registry().counter(
                "rpc.swallowed", site="answer_send"
            ).inc()

    # ------------------------------------------------------------------ #
    def close(self) -> None:
        if self._closing.is_set():
            return
        self._closing.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                get_registry().counter(
                    "rpc.swallowed", site="listener_close"
                ).inc()
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            c.close()
        if self._accept_thread is not None:
            self._accept_thread.join(5.0)


# --------------------------------------------------------------------- #
# Heartbeat lease (the shared directory's liveness record)
# --------------------------------------------------------------------- #
HEARTBEAT_NAME = "heartbeat.bin"


class HeartbeatLease:
    """Primary liveness as an atomic CRC-framed record in the shared
    serving directory.

    The primary commits ``{role, pid, port, ts, lease_s}`` every
    ``beat_s`` with the checkpoint commit discipline (the transport's
    CRC-framed atomic put) so a reader NEVER sees a torn record — it
    sees the previous beat or the new one. The standby promotes when
    the newest record's age exceeds its own declared ``lease_s``: a
    dead primary stops beating, a live one cannot lapse (``beat_s``
    defaults to ``lease_s / 5``).

    ``dirpath`` is any store-backed cluster
    :class:`~gelly_streaming_tpu.fabric.Transport` (a bare path keeps
    the historical shared-directory record, byte-identical).
    """

    def __init__(
        self,
        dirpath,
        *,
        lease_s: float = 0.5,
        beat_s: Optional[float] = None,
        role: str = "primary",
        port: Optional[int] = None,
    ):
        from ..fabric import as_transport

        self.dirpath = dirpath
        self.transport = as_transport(dirpath)
        self.lease_s = float(lease_s)
        self.beat_s = float(beat_s) if beat_s is not None \
            else self.lease_s / 5.0
        self.role = role
        self.port = port
        self.path = self.transport.describe(HEARTBEAT_NAME)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def write(self) -> None:
        doc = {
            "role": self.role,
            "pid": os.getpid(),
            "port": self.port,
            "ts": time.time(),
            "lease_s": self.lease_s,
        }
        self.transport.put_framed(
            HEARTBEAT_NAME, json.dumps(doc).encode("utf-8"),
            overwrite=True,
        )

    def start(self) -> "HeartbeatLease":
        self.write()
        self._thread = threading.Thread(
            target=self._beat, name="rpc-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _beat(self) -> None:
        while not self._stop.wait(self.beat_s):
            try:
                self.write()
            except OSError:
                # a full/unwritable shared dir: the standby will see
                # the lease lapse and promote — which is the CORRECT
                # outcome for a primary that cannot commit state, so
                # count it and keep trying rather than crash serving
                get_registry().counter(
                    "rpc.swallowed", site="heartbeat_write"
                ).inc()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)

    # -- reader side ---------------------------------------------------- #
    @staticmethod
    def read(dirpath) -> Optional[dict]:
        """The newest committed heartbeat record, or None when absent
        or invalid (an invalid record is rejected VISIBLY and treated
        as absent — put atomicity makes it near-impossible, so it is
        evidence of external damage, not a normal state)."""
        from ..fabric import as_transport
        from ..resilience import integrity

        tr = as_transport(dirpath)
        data = tr.get_framed(HEARTBEAT_NAME)
        if data is None:
            return None
        try:
            return json.loads(data)
        except ValueError as e:
            integrity.record_rejection(
                tr.describe(HEARTBEAT_NAME), repr(e)
            )
            return None

    @staticmethod
    def age_s(dirpath) -> Optional[Tuple[float, float]]:
        """(age, declared lease) of the newest heartbeat, or None when
        no valid record exists yet."""
        doc = HeartbeatLease.read(dirpath)
        if doc is None:
            return None
        return max(0.0, time.time() - float(doc["ts"])), \
            float(doc.get("lease_s", 0.5))


# --------------------------------------------------------------------- #
# Replica runtime (the cross-process failover pair's halves)
# --------------------------------------------------------------------- #
class ReplicaServer:
    """One serving replica of a cross-process failover pair.

    ``role="primary"``: owns ingest (a servable + source, exactly like
    ``StreamServer``), mirrors every published snapshot into
    ``dirpath`` and beats the heartbeat lease there, and serves RPC
    queries on ``host:port``.

    ``role="standby"``: follows ``dirpath`` (each mirrored snapshot is
    ingested into its own local store), refuses queries with the
    retryable ``not_primary`` status, and monitors the heartbeat; when
    the lease lapses it :meth:`promote`s — opens its gate, takes over
    the heartbeat, and starts answering from the newest followed
    snapshot. Promotion is one-shot and fully observable
    (``serving.lease_lapse``, ``serving.failover{reason=lease_lapse}``,
    ``serving.promotion_seconds``, a ``serving.promotion`` span).

    A replica constructed with ``role="primary"`` whose serving
    directory already holds a FRESH lease (another replica actively
    beating — the standby a previous incarnation failed over to)
    REJOINS AS STANDBY instead of seizing serving back:
    ``self.rejoined`` is set, ``serving.rejoin_demoted`` counted, and
    the replica behaves exactly like a booted standby — following the
    directory, refusing ``not_primary``, promoting only if the current
    holder's lease lapses. A promoted replica therefore stays promoted
    until IT fails, however many times the old primary restarts.

    Ingest does NOT fail over: the dead primary's stream dies with it,
    and the promoted standby serves the last mirrored snapshot — the
    same keep-serving-from-final-state contract a closed stream has.
    Stream-processing recovery stays with the supervisor/cluster layer.

    ``role="split"`` (ISSUE 19, elastic resharding): the CHILD of a
    live shard split. Follows the PARENT's serving directory exactly
    like a standby — but its gate is OPEN (it answers immediately from
    the followed state), it never monitors or touches the parent's
    lease, and it never promotes. The parent keeps every key, so the
    child serving the full followed table is oracle-identical on the
    moved half of the keyspace — routers send it only keys whose
    ``split_side`` bit moved (``core.ingest.vertex_owner_epoch``).

    ``reshard={"store": <dir>, "shard": <int>}`` attaches a
    :class:`~gelly_streaming_tpu.serving.reshard.ReshardWatcher`: the
    replica learns the live ownership epoch and stamps it on every
    reply frame (``RpcServer(epoch=...)``), which is how routers hear
    about splits from ordinary traffic. An adopted plan whose parent
    is THIS shard is counted ``reshard.split``; any other adoption is
    ``reshard.adopt``.
    """

    def __init__(
        self,
        servable=None,
        source=None,
        *,
        dirpath: str,
        role: str = "primary",
        host: str = "127.0.0.1",
        port: int = 0,
        lease_s: float = 0.5,
        beat_s: Optional[float] = None,
        mirror_every: int = 1,
        mirror_keep: int = 2,
        poll_s: float = 0.02,
        monitor: bool = True,
        reshard: Optional[dict] = None,
        **server_kwargs,
    ):
        if role not in ("primary", "standby", "split"):
            raise ValueError(
                f"role must be primary/standby/split, got {role!r}")
        self.dirpath = dirpath
        self.rejoined = False
        if role == "primary":
            # failed-back primary REJOINS as standby: if another
            # replica HOLDS the lease in this serving directory (the
            # standby this process's predecessor failed over to),
            # seizing serving back would put two primaries on one
            # keyspace. A fresh record alone is not proof of a holder
            # — a fast supervisor restart can boot the SAME replica
            # within its own predecessor's lease window, and
            # self-demoting then would discard ingest forever. So a
            # fresh record is confirmed by watching for a BEAT: only a
            # record whose timestamp advances within the declared
            # lease window has a live writer behind it. Observed beat
            # -> demote (follow the directory, promote only if that
            # holder lapses); no beat / stale record -> a dead
            # predecessor's leftovers, normal primary boot proceeds.
            if self._lease_actively_held(dirpath):
                role = "standby"
                self.rejoined = True
                get_registry().counter("serving.rejoin_demoted").inc()
        self.role = role
        self.lease_s = float(lease_s)
        self.beat_s = beat_s
        self.promoted = False
        self.monitor = monitor and role == "standby"
        self._poll_s = float(poll_s)
        self._stop_follow = threading.Event()
        self._mon_stop = threading.Event()
        self._mon_thread: Optional[threading.Thread] = None
        self._plock = threading.Lock()
        self._closed = False
        self.lease: Optional[HeartbeatLease] = None
        self._reshard_cfg = reshard
        self._reshard = None  # ReshardWatcher, created in start()
        self._reshard_seen = 0  # adopted-plan prefix already counted
        self.shard = None if reshard is None else reshard.get("shard")
        if role == "primary":
            if servable is None:
                raise ValueError("a primary replica needs a servable")
            self.store = SnapshotStore()
            self.mirror = SnapshotMirror(
                dirpath, keep=mirror_keep, every=mirror_every
            )
            self.store.add_listener(self.mirror)
            self.server = StreamServer(
                servable, source, store=self.store, **server_kwargs
            )
        else:
            self.mirror = None
            # carry_version: the follower mirrors the PRIMARY's version
            # sequence and boot lineage into this store, so a standby's
            # ring holds the same (version, boot) addresses a client's
            # transaction pinned — promotion preserves pins (ISSUE 20)
            follower = follow_snapshots(
                dirpath, self._stop_follow, poll_s=self._poll_s,
                carry_version=True,
            )
            self.server = StreamServer(follower, None, **server_kwargs)
            self.store = self.server.store
        self.rpc = RpcServer(
            self.server, host=host, port=port, gate=self._gate,
            epoch=self._epoch, shard=self.shard,
        )

    # ------------------------------------------------------------------ #
    @staticmethod
    def _lease_actively_held(dirpath: str) -> bool:
        """True when a LIVE replica is beating the directory's lease:
        the newest record is fresh AND its timestamp advances within
        one declared lease window (beats land every ``lease_s / 5``).
        Blocks at most one lease window — paid only on the rare boot
        into a directory with a fresh record."""
        got = HeartbeatLease.age_s(dirpath)
        if got is None or got[0] > got[1]:
            return False  # no record, or already lapsed: no holder
        first = HeartbeatLease.read(dirpath)
        if first is None:
            return False
        deadline = time.monotonic() + float(got[1])
        while time.monotonic() < deadline:
            time.sleep(min(0.02, got[1] / 10))
            rec = HeartbeatLease.read(dirpath)
            if rec is not None and rec.get("ts") != first.get("ts"):
                return True  # the writer beat: genuinely held
        return False  # fresh but silent: a dead predecessor's record

    def _gate(self) -> Optional[str]:
        # a split child answers from boot — its traffic is routed by
        # ownership epoch, not by lease, so there is nothing to refuse
        return None if self.role in ("primary", "split") else NOT_PRIMARY

    def _epoch(self) -> int:
        """Current ownership epoch for reply-frame stamping (0 before
        any split is actionable, or with no reshard store attached)."""
        w = self._reshard
        return 0 if w is None else w.epoch()

    def _on_reshard(self, plans: list) -> None:
        """Watcher callback: count each NEWLY adopted plan — a split
        of this shard's own keyspace (``reshard.split``) reads
        differently in the storm timeline than a peer's split this
        replica merely adopts (``reshard.adopt``)."""
        reg = get_registry()
        for p in plans[self._reshard_seen:]:
            if self.shard is not None and p["parent"] == self.shard:
                reg.counter(
                    "reshard.split", epoch=str(p["epoch"]),
                    parent=str(p["parent"]), child=str(p["child"]),
                ).inc()
            else:
                reg.counter(
                    "reshard.adopt", epoch=str(p["epoch"]),
                    site="replica",
                ).inc()
        self._reshard_seen = len(plans)

    def start(self) -> "ReplicaServer":
        if self._reshard_cfg is not None:
            from .reshard import ReshardWatcher

            self._reshard = ReshardWatcher(
                self._reshard_cfg["store"],
                poll_s=float(self._reshard_cfg.get("poll_s", 0.1)),
                on_adopt=self._on_reshard,
            )
        self.server.start()
        self.rpc.start()
        if self.role == "primary":
            # the lease's first commit is shared-directory file I/O:
            # it happens OUTSIDE _plock (GL009) so a slow shared mount
            # never stalls close()/promote() callers queued on the lock
            self._install_lease(HeartbeatLease(
                self.dirpath, lease_s=self.lease_s,
                beat_s=self.beat_s, port=self.rpc.port,
            ).start())
            # the mirror stride may skip trailing windows; when ingest
            # ENDS the newest snapshot is the final state and must be
            # on the shared dir for any later failover to serve it
            threading.Thread(
                target=self._flush_on_ingest_end,
                name="rpc-mirror-flush", daemon=True,
            ).start()
        elif self.monitor:
            self._mon_thread = threading.Thread(
                target=self._monitor, name="rpc-lease-monitor",
                daemon=True,
            )
            self._mon_thread.start()
        return self

    def __enter__(self) -> "ReplicaServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _flush_on_ingest_end(self) -> None:
        self.server._ingest_done.wait()
        if not self._closed:
            try:
                self.mirror.flush(self.store)
            except OSError:
                # same posture as the heartbeat writer: an unwritable
                # shared dir surfaces as a lease lapse, not a crash
                get_registry().counter(
                    "rpc.swallowed", site="mirror_flush"
                ).inc()

    def _monitor(self) -> None:
        """Watch the primary's lease; a lapse promotes this standby.
        Promotion needs EVIDENCE the primary existed: before the first
        valid heartbeat there is nothing to lapse (a standby booted
        ahead of its primary waits, it does not seize)."""
        poll = min(self._poll_s, self.lease_s / 4)
        while not self._mon_stop.wait(poll):
            if self.promoted or self._closed:
                return
            got = HeartbeatLease.age_s(self.dirpath)
            if got is None:
                continue
            age, lease = got
            if age > lease:
                get_registry().counter("serving.lease_lapse").inc()
                self.promote(
                    reason="lease_lapse",
                    _t0=time.perf_counter(),
                )
                return

    # ------------------------------------------------------------------ #
    def _install_lease(self, lease: "HeartbeatLease") -> None:
        """Publish an already-started lease under the promotion lock.
        The lease's file I/O stays OUTSIDE ``_plock`` (GL009); only the
        reference swap is locked. A close() that raced the commit wins:
        the fresh lease is released instead of leaking its beat
        thread."""
        with self._plock:
            if not self._closed:
                self.lease = lease
                return
        lease.close()

    def promote(self, reason: str = "manual",
                _t0: Optional[float] = None) -> None:
        """Take over serving: open the query gate, own the heartbeat.
        One-shot; later calls are no-ops. ``serving.promotion_seconds``
        measures lapse-detection (or call) to heartbeat-takeover — the
        latency a client's retry actually waits out on top of its
        reconnect."""
        t0 = time.perf_counter() if _t0 is None else _t0
        reg = get_registry()
        with _trace.span(
            "serving.promotion",
            {"reason": reason} if _trace.on() else None,
        ):
            with self._plock:
                if self.promoted or self._closed:
                    return
                reg.counter("serving.failover", reason=reason).inc()
                self.role = "primary"  # the gate reads this: queries flow
                self.promoted = True
                # pinned reads this promoted standby cannot satisfy
                # from its mirrored ring are failover expiries from
                # here on (txn.failover_expired) — counted differently
                # because they tell the lost-trailing-state story
                self.server.txn_failover = True
            # the heartbeat takeover is shared-directory file I/O:
            # committed outside _plock (GL009) so health probes and
            # close() never queue behind a disk write
            self._install_lease(HeartbeatLease(
                self.dirpath, lease_s=self.lease_s,
                beat_s=self.beat_s, port=self.rpc.port,
            ).start())
            reg.histogram("serving.promotion_seconds").observe(
                time.perf_counter() - t0
            )

    # ------------------------------------------------------------------ #
    # Query surface (local, for tests/symmetry; the wire is the point)
    # ------------------------------------------------------------------ #
    def submit(self, query: Query, **kw):
        return self.server.submit(query, **kw)

    def ask(self, query: Query, timeout: Optional[float] = None,
            deadline_s: Optional[float] = None) -> Answer:
        return self.server.ask(query, timeout, deadline_s=deadline_s)

    def heartbeat_age_s(self) -> Optional[float]:
        """Age of the newest heartbeat record in the shared directory —
        what an external probe reads to tell a wedged primary (stale
        beat) from a healthy standby (fresh beat, standby role)."""
        got = HeartbeatLease.age_s(self.dirpath)
        return None if got is None else round(got[0], 4)

    def health(self) -> dict:
        doc = {
            "role": self.role,
            "promoted": bool(self.promoted),
            "rejoined": bool(self.rejoined),
            "worker_alive": bool(self.server.worker_alive()),
            "pending": len(self.server._pending),
            "heartbeat_age_s": self.heartbeat_age_s(),
            "rpc_port": self.rpc.port,
            "epoch": self._epoch(),
            # the transaction probe surface (ISSUE 20): how deep the
            # pinned-readable ring is, the OLDEST version a pin can
            # still be answered at, and how many transactions touched
            # this replica within the tracker TTL
            "txn": {
                "retention": self.store.retention,
                "ring_depth": self.store.ring_depth(),
                "oldest_pinned": self.store.oldest_retained(),
                "active": active_txn_count(),
            },
        }
        rec = HeartbeatLease.read(self.dirpath)
        if rec is not None:
            # who holds the lease RIGHT NOW — the record's role/pid/
            # port exist for exactly this probe surface (GL011: every
            # key the writer commits has a reader), and it is how an
            # external check tells "this standby is healthy because a
            # live primary beats" from "nobody is beating"
            doc["lease"] = {
                "role": rec.get("role"),
                "pid": rec.get("pid"),
                "port": rec.get("port"),
            }
        doc["ok"] = doc["worker_alive"]
        return doc

    def metrics_endpoint(self, **kw):
        """Scrape endpoint for this replica: ``/healthz`` reports role,
        promotion state, and heartbeat age next to worker liveness."""
        from ..obs.endpoint import MetricsEndpoint

        return MetricsEndpoint(health=self.health, **kw).start()

    # ------------------------------------------------------------------ #
    def close(self, timeout: float = 30.0) -> None:
        with self._plock:
            if self._closed:
                return
            self._closed = True
        # one budget for the whole close (GL008): the monitor join and
        # the server drain spend what REMAINS of `timeout`, not a
        # fresh copy each
        deadline = time.monotonic() + float(timeout)
        self._mon_stop.set()
        if self._reshard is not None:
            self._reshard.close(max(0.0, deadline - time.monotonic()))
        if self._mon_thread is not None:
            self._mon_thread.join(
                max(0.0, deadline - time.monotonic()))
        if self.lease is not None:
            self.lease.close()
        self.rpc.close()
        self._stop_follow.set()
        self.server.close(max(0.0, deadline - time.monotonic()))
        if self.mirror is not None:
            try:
                self.mirror.flush(self.store)
            except OSError:
                get_registry().counter(
                    "rpc.swallowed", site="mirror_flush"
                ).inc()


# --------------------------------------------------------------------- #
# The serving binary (subprocess entry) + CI smoke
# --------------------------------------------------------------------- #
#: exit code for an injected kill (matches resilience/chaos.py KILL_RC)
KILL_RC = 17

#: repo root for subprocess sys.path injection (same derivation as
#: resilience/chaos.py — replicas must import this package regardless
#: of the driver's cwd)
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def demo_payloads(windows: int = 200, vcap: int = 64,
                  pace_s: float = 0.005):
    """The replica binary's demo servable: per window, a CC label table
    whose zero-rooted chain grows by one vertex — cheap, deterministic,
    and every window's answers differ, so staleness is testable."""
    import numpy as np

    from ..datasets import IdentityDict

    vd = IdentityDict(vcap)
    vd.observe(vcap - 1)
    labels = np.arange(vcap, dtype=np.int32)
    for w in range(windows):
        labels = labels.copy()
        labels[: min(vcap, w + 2)] = 0
        yield {"labels": labels, "vdict": vd}, w + 1
        if pace_s:
            time.sleep(pace_s)


def replica_main(cfg: dict) -> None:
    """One serving replica as a real process. ``cfg`` keys: ``dir``,
    ``role``, ``portfile`` (the bound port is committed there
    atomically), optional ``events`` (streaming ShardSink path),
    ``flight`` (flight-recorder dump base), ``kill_at_sweep`` (FaultPlan
    ``serving.worker`` kill -> ``os._exit(KILL_RC)`` with the black box
    dumped first), ``windows``/``vcap``/``pace_s`` (primary demo
    stream), ``lease_s``, ``run_s`` (wall-clock cap), ``meta``.

    ISSUE 19 keys: ``autotune``/``target_wait_s`` (load-aware
    admission on the inner StreamServer), ``reshard``
    (``{"store": dir, "shard": k}`` — epoch stamping + adoption),
    ``role="split"`` + ``split_epoch`` (boot as a split child of
    ``dir``'s parent shard and publish this process's address under
    the split epoch once servable), ``pullring`` (persist the delta
    pull ring next to the snapshot mirror), ``adopt_boot`` (republish
    the newest mirrored snapshot under its ORIGINAL version before
    ingest, restoring the pull ring when present — the restarted-shard
    bridge)."""
    import signal

    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..obs import flight as obs_flight
    from ..obs import trace as obs_trace
    from ..obs.cluster import ShardSink
    from ..resilience import faults

    role = cfg["role"]
    sink = None
    if cfg.get("events"):
        sink = ShardSink(cfg["events"], shard=cfg.get("shard"))
        get_registry().add_sink(sink)
        obs_trace.add_sink(sink)
        # span events ARE the shipped evidence; the registry mirror
        # (trace.span_seconds) would double every span in the event
        # log for a surface nothing scrapes in a bench replica
        obs_trace.enable(registry_spans=False)
    if cfg.get("flight"):
        obs_flight.install(obs_flight.FlightRecorder(
            cfg["flight"], capacity=128, shard=cfg.get("shard"),
        ))
    kill_at = cfg.get("kill_at_sweep")
    if kill_at is not None:
        faults.install(faults.FaultPlan(
            seed=int(cfg.get("seed", 0)),
            kill_site="serving.worker",
            kill_at_window=int(kill_at),
            kill_exit_code=KILL_RC,
        ))
    kw = dict(
        lease_s=float(cfg.get("lease_s", 0.5)),
        max_pending=int(cfg.get("max_pending", 1 << 14)),
    )
    if cfg.get("autotune"):
        kw["autotune"] = True
        if cfg.get("target_wait_s") is not None:
            kw["target_wait_s"] = float(cfg["target_wait_s"])
    if cfg.get("reshard"):
        kw["reshard"] = cfg["reshard"]
    if role == "primary":
        if cfg.get("cc_shard"):
            # one SHARD of the partitioned serving deployment: real CC
            # forest + degree folds over the edges this shard owns
            # (serving/router.py — the sharded bench's replica shape)
            from .router import shard_demo_payloads

            servable = shard_demo_payloads(**cfg["cc_shard"])
        else:
            servable = demo_payloads(
                windows=int(cfg.get("windows", 200)),
                vcap=int(cfg.get("vcap", 64)),
                pace_s=float(cfg.get("pace_s", 0.005)),
            )
        rep = ReplicaServer(
            servable, None, dirpath=cfg["dir"], role="primary", **kw
        )
        if cfg.get("pullring"):
            from .query import PullRingMirror

            rep.store.add_listener(PullRingMirror(
                rep.server.engine, cfg["dir"],
                every=int(cfg.get("pullring_every", 1)),
            ))
        if cfg.get("adopt_boot") and not rep.rejoined:
            # restart adoption: republish the newest mirrored snapshot
            # under its ORIGINAL version so router delta baselines (and
            # the persisted pull ring) survive the restart; a missing
            # mirror just means a cold boot
            from .snapshot_store import load_newest_snapshot

            doc = load_newest_snapshot(cfg["dir"])
            if doc is not None:
                # boot lineage rides the mirror: a restart-adopted
                # snapshot keeps its ORIGINAL (version, boot) address,
                # so an exact-version pin on it stays satisfiable (the
                # content is identical); absent boot = old mirror =
                # fresh lineage, pins reset honestly
                rep.server.publish_boot(
                    doc["payload"], int(doc["watermark"]),
                    version=int(doc["version"]),
                    boot=doc.get("boot"),
                )
                if cfg.get("pullring"):
                    from .query import load_pull_ring

                    rep.server.engine.restore_chain(
                        load_pull_ring(cfg["dir"]),
                        rep.store.epoch, int(doc["version"]),
                    )
    else:
        rep = ReplicaServer(dirpath=cfg["dir"], role=role, **kw)
    rep.start()
    if role == "split" and cfg.get("reshard"):
        # the child address is published ONLY once servable (first
        # followed snapshot answered) — the actionable-prefix rule in
        # serving/reshard.py is what keeps routers from adopting an
        # epoch whose child would refuse traffic
        from .reshard import publish_addr

        rep.store.wait_for(
            min_version=1,
            timeout=float(cfg.get("split_boot_timeout_s", 60.0)),
        )
        publish_addr(
            cfg["reshard"]["store"], int(cfg["split_epoch"]),
            f"127.0.0.1:{rep.rpc.port}",
        )
    if cfg.get("portfile"):
        from ..resilience import integrity

        tmp = cfg["portfile"] + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(rep.rpc.port))
        integrity.replace_atomic(tmp, cfg["portfile"])
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    deadline = time.monotonic() + float(cfg.get("run_s", 600.0))
    while not stop.is_set() and time.monotonic() < deadline:
        stop.wait(0.05)
    meta = {
        "role": rep.role,
        "promoted": rep.promoted,
        "port": rep.rpc.port,
    }
    adm = getattr(rep.server, "admission", None)
    if cfg.get("autotune") and adm is not None:
        # the admission tuner's full trajectory: every knob move plus
        # the final watermark — the committed shed-trajectory evidence
        meta["autotune"] = {
            "knob": adm.knob,
            "ceiling": adm.ceiling,
            "max_pending": adm.max_pending,
            "shed_watermark": round(adm.shed_watermark, 4),
            "history": [list(h) for h in adm.history],
        }
    rep.close()
    if cfg.get("meta"):
        with open(cfg["meta"], "w") as f:
            json.dump(meta, f)
    if sink is not None:
        sink.close()
        get_registry().remove_sink(sink)
    faults.clear()


def _replica_code() -> str:
    return (
        "import sys, json; "
        f"sys.path.insert(0, {REPO_ROOT!r}); "
        "from gelly_streaming_tpu.serving import rpc; "
        "rpc.replica_main(json.loads(sys.argv[1]))"
    )


def spawn_replica(cfg: dict):
    """Launch one replica binary detached (stdout/stderr to a log file
    next to its portfile — a killed replica must never deadlock the
    driver on a full pipe). Returns the Popen, with ``log_path`` set."""
    import subprocess
    import sys as _sys

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    os.makedirs(cfg["dir"], exist_ok=True)
    log_path = os.path.join(
        cfg["dir"], f"replica.{cfg['role']}.log"
    )
    logf = open(log_path, "wb")
    try:
        p = subprocess.Popen(
            [_sys.executable, "-c", _replica_code(), json.dumps(cfg)],
            stdout=logf, stderr=subprocess.STDOUT, env=env,
        )
    finally:
        logf.close()  # the child holds its own dup of the fd
    p.log_path = log_path
    return p


def wait_portfile(path: str, timeout_s: float = 90.0) -> int:
    """Poll a replica's committed portfile; the bound port, or raises."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            with open(path) as f:
                text = f.read().strip()
            if text:
                return int(text)
        except (OSError, ValueError):
            pass
        time.sleep(0.02)
    raise TimeoutError(f"no replica port committed at {path}")


def smoke(verbose: bool = True) -> bool:
    """CI gate: a primary + standby replica pair as REAL subprocesses,
    one client batch round-tripped over real sockets, the primary
    SIGKILLed, and the client's retry asserted to land on the promoted
    standby. Returns True on success."""
    import shutil
    import tempfile

    from .client import RpcClient

    say = print if verbose else (lambda *a, **k: None)
    root = tempfile.mkdtemp(prefix="rpc_smoke_")
    primary = standby = None
    client = None
    ok = False
    try:
        shared = os.path.join(root, "shared")
        os.makedirs(shared, exist_ok=True)
        base = dict(
            dir=shared, lease_s=0.4, windows=2000, pace_s=0.01,
            vcap=64, run_s=300.0,
        )
        primary = spawn_replica(dict(
            base, role="primary",
            portfile=os.path.join(root, "primary.port"),
            events=os.path.join(root, "events.primary.jsonl"),
        ))
        standby = spawn_replica(dict(
            base, role="standby",
            portfile=os.path.join(root, "standby.port"),
            events=os.path.join(root, "events.standby.jsonl"),
        ))
        p_port = wait_portfile(os.path.join(root, "primary.port"))
        s_port = wait_portfile(os.path.join(root, "standby.port"))
        say(f"rpc-smoke: primary :{p_port}, standby :{s_port}")
        client = RpcClient(
            [f"127.0.0.1:{p_port}", f"127.0.0.1:{s_port}"],
        )
        answers = client.ask_batch(
            [ConnectedQuery(0, 1), ComponentSizeQuery(0)],
            deadline_s=60.0, timeout=60.0,
        )
        if answers[0].value is not True or int(answers[1].value) < 2:
            say(f"RPC SMOKE FAIL: pre-kill answers wrong: "
                f"{[a.value for a in answers]}")
            return False
        say(f"rpc-smoke: pre-kill batch ok "
            f"(connected={answers[0].value}, "
            f"size={answers[1].value}, window={answers[0].window})")
        primary.kill()
        primary.wait(30)
        t0 = time.perf_counter()
        answers = client.ask_batch(
            [ConnectedQuery(0, 1)], deadline_s=60.0, timeout=60.0,
        )
        blip = time.perf_counter() - t0
        if answers[0].value is not True:
            say("RPC SMOKE FAIL: post-kill answer wrong")
            return False
        events_path = os.path.join(root, "events.standby.jsonl")
        promoted = False
        with open(events_path) as f:
            for line in f:
                if '"serving.failover"' in line and "lease_lapse" in line:
                    promoted = True
                    break
        if not promoted:
            say("RPC SMOKE FAIL: standby never recorded the "
                "lease-lapse promotion")
            return False
        say(f"RPC SMOKE OK: primary killed, standby promoted on lease "
            f"lapse, client retry answered in {blip:.2f}s")
        ok = True
        return True
    finally:
        if client is not None:
            client.close()
        for p in (primary, standby):
            if p is not None and p.poll() is None:
                p.terminate()
                try:
                    p.wait(15)
                except Exception:
                    get_registry().counter(
                        "rpc.swallowed", site="smoke_teardown"
                    ).inc()
                    p.kill()
        if not ok and verbose and standby is not None:
            try:
                with open(standby.log_path, "rb") as f:
                    print("standby log tail:",
                          f.read()[-2000:].decode(errors="replace"))
            except OSError:
                pass
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    import sys

    if "--smoke" in sys.argv:
        sys.exit(0 if smoke() else 1)
    if "--replica" in sys.argv:
        replica_main(json.loads(
            sys.argv[sys.argv.index("--replica") + 1]
        ))
        sys.exit(0)
    print(
        "usage: python -m gelly_streaming_tpu.serving.rpc "
        "--smoke | --replica '<json cfg>'",
        file=sys.stderr,
    )
    sys.exit(2)
