"""Elastic resharding coordination: ownership-epoch plans over fabric.

The serving tier boots with a fixed shard count (``router.ShardRouter``
fans out by ``core.ingest.vertex_owner``).  A live split moves HALF of
one hot shard's keyspace to a new child shard without stopping the
stream.  The pieces:

- A **split plan** ``{"epoch", "parent", "child", "salt"}`` is agreed
  via :meth:`fabric.base.Transport.elect` — the same one-winner
  machinery as ``ElectedK`` (``fabric/agreement.py``).  Exactly one
  proposal wins per epoch; a replaying proposer finds the persisted
  winner and re-reads it, never re-votes.  The plan composes with the
  boot hash through :func:`core.ingest.vertex_owner_epoch`: keys whose
  ``split_side(ids, salt)`` bit is set move from ``parent`` to
  ``child``; the rest stay put.
- The child shard **publishes its address** under the same store once
  (and only once) it is servable.  A plan is *actionable* only when
  both the elected plan AND the child address exist — so a router can
  never adopt an epoch whose child would refuse traffic.
- Ownership epochs form a **dense prefix**: epoch ``k`` means plans
  ``1..k`` are all actionable.  :func:`actionable_plans` returns that
  longest prefix; its length IS the epoch.  A gap (plan 2 actionable
  but plan 1 not) stops the prefix at 0 — adoption is ordered, never
  speculative.
- :class:`ReshardWatcher` polls the store from a daemon thread and
  fires ``on_adopt`` when the prefix grows.  Shard replicas use it to
  learn the current epoch they stamp on reply frames
  (``rpc.RpcServer(epoch=...)``); routers learn new epochs from those
  frames and pull the plans here (``router.ShardRouter``).

Everything rides the CRC container (``put_framed``/``get_framed``), so
a torn plan or address reads as absent-and-recorded, never mis-parsed.
"""

from __future__ import annotations

import pickle
import threading
from typing import Callable, Dict, List, Optional

from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience.integrity import record_rejection

PLAN_PREFIX = "reshard.plan.e"
ADDR_PREFIX = "reshard.addr.e"

_PLAN_KEYS = ("epoch", "parent", "child", "salt")


def plan_tag(epoch: int) -> str:
    """Store tag for the elected split plan of ``epoch``."""
    return f"{PLAN_PREFIX}{int(epoch):08d}"


def addr_tag(epoch: int) -> str:
    """Store tag for the child shard's published address of
    ``epoch``."""
    return f"{ADDR_PREFIX}{int(epoch):08d}"


def _validate_plan(plan, origin: str) -> Optional[Dict[str, int]]:
    """Shape-check a decoded plan; a malformed one is RECORDED and read
    as absent (same contract as ``get_framed`` on a torn frame)."""
    if not isinstance(plan, dict) or any(k not in plan for k in _PLAN_KEYS):
        record_rejection(origin, f"malformed split plan: {plan!r:.120}")
        return None
    try:
        out = {k: int(plan[k]) for k in _PLAN_KEYS}
    except (TypeError, ValueError) as e:
        record_rejection(origin, f"non-integer split plan field: {e!r}")
        return None
    if out["parent"] == out["child"] or out["child"] < 0 or out["parent"] < 0:
        record_rejection(origin, f"degenerate split plan: {out!r}")
        return None
    return out


def propose_split(store, epoch: int, *, parent: int, child: int,
                  salt: int) -> Dict[str, int]:
    """Propose a split for ``epoch``; return the WINNING plan.

    One-winner: concurrent proposers for the same epoch all return the
    same plan (whichever the store's one-winner put picked), and a
    proposer replaying after a restart re-reads the persisted winner.
    The returned plan — not the proposal — is what everyone acts on.
    """
    from ..fabric import as_transport

    tr = as_transport(store)
    plan = {
        "epoch": int(epoch),
        "parent": int(parent),
        "child": int(child),
        "salt": int(salt) & (2 ** 64 - 1),
    }
    if plan["parent"] == plan["child"]:
        raise ValueError(f"split parent == child ({parent})")
    won = tr.elect(plan_tag(epoch), plan)
    out = _validate_plan(won, tr.describe(plan_tag(epoch)))
    if out is None:
        # the elected winner itself is malformed — this is not a torn
        # frame (elect CRC-checks) but a bad proposer; surface it
        raise ValueError(f"elected split plan malformed: {won!r:.120}")
    if _trace.on():
        get_registry().counter(
            "reshard.agree", epoch=str(out["epoch"]),
            parent=str(out["parent"]), child=str(out["child"]),
        ).inc()
    return out


def read_plan(store, epoch: int) -> Optional[Dict[str, int]]:
    """Non-proposing read of an elected plan (``None`` if not yet
    elected, torn, or malformed — torn/malformed are recorded)."""
    from ..fabric import as_transport

    tr = as_transport(store)
    data = tr.get_framed(plan_tag(epoch))
    if data is None:
        return None
    try:
        plan = pickle.loads(data)
    except Exception as e:
        record_rejection(tr.describe(plan_tag(epoch)),
                         f"undecodable split plan: {e!r}")
        return None
    return _validate_plan(plan, tr.describe(plan_tag(epoch)))


def publish_addr(store, epoch: int, addr: str) -> None:
    """Publish the child shard's serving address for ``epoch``.

    Overwrite is deliberate: a restarted child re-publishes its (new)
    port under the same epoch and routers re-resolve on their next
    adoption poll.
    """
    from ..fabric import as_transport

    as_transport(store).put_framed(
        addr_tag(epoch), str(addr).encode("utf-8"), overwrite=True)


def read_addr(store, epoch: int) -> Optional[str]:
    """Child address for ``epoch`` (``None`` if unpublished/torn)."""
    from ..fabric import as_transport

    tr = as_transport(store)
    data = tr.get_framed(addr_tag(epoch))
    if data is None:
        return None
    try:
        return data.decode("utf-8")
    except UnicodeDecodeError as e:
        record_rejection(tr.describe(addr_tag(epoch)),
                         f"undecodable child addr: {e!r}")
        return None


def actionable_plans(store, *, limit: int = 64) -> List[Dict]:
    """Longest DENSE prefix of actionable split plans.

    Epoch ``k`` is actionable when its plan is elected AND its child
    address is published.  Returns plans ``1..k`` (each with an
    ``"addr"`` key) for the largest such dense ``k``; the list length
    is the current ownership epoch.  Ordering matters: splits compose
    (``vertex_owner_epoch`` applies them in sequence), so a later plan
    must never be adopted before an earlier one.
    """
    out: List[Dict] = []
    for epoch in range(1, int(limit) + 1):
        plan = read_plan(store, epoch)
        if plan is None:
            break
        addr = read_addr(store, epoch)
        if addr is None:
            break
        out.append(dict(plan, addr=addr))
    return out


class ReshardWatcher:
    """Poll a reshard store for epoch growth from a daemon thread.

    ``on_adopt(plans)`` fires with the FULL actionable prefix each time
    it grows (never shrinks — adopted plans are immutable history).
    ``epoch()``/``splits()``/``addrs()`` read the latest adopted state
    without touching the store.  Poll errors are swallowed-and-counted
    (``reshard.swallowed{site=watch}``): a flaky store read must not
    kill the watcher, the next poll retries.
    """

    def __init__(self, store, *, poll_s: float = 0.1,
                 on_adopt: Optional[Callable[[List[Dict]], None]] = None,
                 limit: int = 64, start: bool = True) -> None:
        self.store = store
        self.poll_s = float(poll_s)
        self.limit = int(limit)
        self._on_adopt = on_adopt
        self._lock = threading.Lock()
        self._plans: List[Dict] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self.refresh()
            self._thread = threading.Thread(
                target=self._run, name="reshard-watch", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------- #
    def epoch(self) -> int:
        with self._lock:
            return len(self._plans)

    def splits(self) -> List[Dict]:
        """Adopted plans WITHOUT addresses — the ``splits`` argument
        for :func:`core.ingest.vertex_owner_epoch`."""
        with self._lock:
            return [{k: p[k] for k in _PLAN_KEYS} for p in self._plans]

    def addrs(self) -> List[str]:
        with self._lock:
            return [p["addr"] for p in self._plans]

    def plans(self) -> List[Dict]:
        with self._lock:
            return [dict(p) for p in self._plans]

    # ------------------------------------------------------------- #
    def refresh(self) -> int:
        """One synchronous poll; returns the current epoch."""
        plans = actionable_plans(self.store, limit=self.limit)
        fire = None
        with self._lock:
            if len(plans) > len(self._plans):
                self._plans = plans
                fire = [dict(p) for p in plans]
        if fire is not None and self._on_adopt is not None:
            self._on_adopt(fire)
        with self._lock:
            return len(self._plans)

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.refresh()
            except Exception:
                # counted, not fatal: the watcher must outlive one bad
                # store read — the next poll sees a consistent store
                get_registry().counter(
                    "reshard.swallowed", site="watch").inc()
            self._stop.wait(self.poll_s)

    def close(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._thread = None
