"""Typed point queries + the batched vectorized query engine.

A serving tier dies by per-query host loops: 10k concurrent
``connected(u, v)`` queries must not become 10k pointer chases in Python
or 10k device dispatches. The :class:`QueryEngine` answers a whole batch
per query class with ONE jitted lookup:

- CC queries gather the batch's endpoints out of the published pointer
  forest and chase ONLY those lanes to their roots (a batch-sized
  ``lax.while_loop`` of gathers — the same kernel shape as
  ``summaries/forest.py:chase_and_group``, sized by the batch, not the
  vertex capacity). Flat labels are a valid (depth-1) forest, so the one
  kernel serves every CC carry and restored checkpoints alike.
- Degree / rank queries are one table gather.
- Component-size queries canonicalize the forest once per snapshot
  version (cached) and bincount, then answer any number of batches from
  the cached size table.

Batch id arrays are padded to power-of-two buckets so a serving session
compiles O(log batch-size) jit signatures, the stream-ingest convention
(``core/edgeblock.py:bucket_capacity``).

Two execution paths, picked per backend (``prefer_host="auto"``):

- **device** (accelerators): the jitted batch kernels run where the
  payload lives; only the batch-sized result crosses the link — right
  when D2H bandwidth is the scarce resource (a remote-TPU tunnel moves
  ~4-18 MB/s, so shipping a vcap-sized table per snapshot would cap the
  read path at ~1 snapshot/s).
- **host** (the CPU backend): queries answered by the jitted path
  ENQUEUE at the tail of the same XLA dispatch queue the async window
  folds fill, so each batch waits out the whole in-flight pipeline
  (measured ~230 ms p50 behind 1M-edge windows) and its sync stalls
  ingest. Instead the engine lazily materializes ONE host copy of the
  payload table per snapshot version (a wait-on-this-array transfer,
  not a tail-of-queue dispatch) and answers with the same whole-batch
  vectorized chase in numpy — still never per-query loops.

Query ids are RAW vertex ids (what a client knows); the engine maps them
through the payload's vertex dictionary without inserting — unseen
vertices answer like the reference's ``DisjointSet`` would for a vertex
it never saw: connected only to itself, degree 0, rank 0.0, component
size 0.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.edgeblock import bucket_capacity
from .snapshot_store import PublishedSnapshot


# --------------------------------------------------------------------- #
# Query + answer records
# --------------------------------------------------------------------- #
class Query:
    """Marker base for point queries (raw vertex ids)."""

    __slots__ = ()


@dataclass(frozen=True)
class ConnectedQuery(Query):
    """Are ``u`` and ``v`` in one component? (``connected(u, v)``)."""

    u: int
    v: int


@dataclass(frozen=True)
class DegreeQuery(Query):
    """Current degree of ``v``."""

    v: int


@dataclass(frozen=True)
class RankQuery(Query):
    """Current PageRank mass of ``v``."""

    v: int


@dataclass(frozen=True)
class ComponentSizeQuery(Query):
    """Size of ``v``'s component (0 for a never-seen vertex)."""

    v: int


@dataclass(frozen=True)
class SummaryPullQuery(Query):
    """Pull this snapshot's CC forest as a mergeable summary (the
    sharded-serving router's cross-shard union input): per seen slot,
    the RAW vertex id and its component root's RAW id, as packed
    little-endian int64 columns (base64 in the JSON answer value).
    RAW-id space is the join key — per-shard compact ids never leave
    their shard. O(vcap) per snapshot version, cached by the engine, so
    any number of pulls per version cost one canonicalization."""

    __slots__ = ()


@dataclass(frozen=True)
class BipartiteQuery(Query):
    """Is the streamed graph (still) bipartite? Graph-global, like
    :class:`SummaryPullQuery`. The answer value is a typed dict::

        {"bipartite": bool, "witness": raw_id | None}

    ``witness`` is the smallest RAW vertex id whose two signed-cover
    nodes share a component — a vertex on an odd cycle, the conflict
    witness — when the graph is non-bipartite, else None. Answered from
    the published cover forest (``summaries/candidates.py`` layout:
    cover node (v,+) = v, (v,-) = v + vcap in a 2*vcap table), so the
    verdict recomputes from the structural truth rather than trusting a
    carried latch. O(vcap) per snapshot version, cached by the engine.
    """

    __slots__ = ()


@dataclass(frozen=True)
class Answer:
    """One query's result, stamped with the snapshot it was answered
    from: ``window`` is that snapshot's window index, ``staleness`` the
    windows-behind-head gap at answer time (0 = answered at the head),
    ``version`` the snapshot's publish version — the monotone counter a
    routing tier keys its cache invalidation on (reply frames carry it,
    so a router learns of shard progress from ordinary answers)."""

    value: Any
    window: int
    watermark: int
    staleness: int
    version: int = 0


# --------------------------------------------------------------------- #
# Vectorized kernels (batch-sized, payload-table-gathering)
# --------------------------------------------------------------------- #
@jax.jit
def _batch_roots(canon: jax.Array, ids: jax.Array) -> jax.Array:
    """Chase a BATCH of start ids to their forest roots. Read-only on
    ``canon``; terminates by the min-root invariant (chains strictly
    decrease). Padding lanes chase from 0, always self-rooted."""
    r = canon[ids]
    return lax.while_loop(
        lambda r: jnp.any(canon[r] != r), lambda r: canon[r], r
    )


@jax.jit
def _gather(table: jax.Array, ids: jax.Array) -> jax.Array:
    return table[ids]


@jax.jit
def _gather_sizes(lab: jax.Array, sizes: jax.Array, ids: jax.Array) -> jax.Array:
    """Fused root-resolve + size lookup over a canonical table: ONE
    dispatch, only the batch-sized result crosses the link."""
    return sizes[lab[ids]]


@jax.jit
def _component_size_table(canon: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Canonicalize the whole forest once and count members per root.
    O(vcap) — run once per snapshot version, cached by the engine.
    The canonicalization IS ``summaries/forest.py:resolve_flat`` (one
    copy of the kernel; this jit just fuses the bincount after it)."""
    from ..summaries.forest import resolve_flat

    lab = resolve_flat(canon)
    sizes = jnp.zeros(canon.shape[0], jnp.int32).at[lab].add(1)
    return lab, sizes


def _pad_ids(ids: np.ndarray) -> np.ndarray:
    """Bucket a compact-id batch to pow2 (pad with 0 — a safe self-rooted
    lane) so jit signatures stay O(log batch-size)."""
    n = len(ids)
    cap = bucket_capacity(max(n, 1), minimum=8)
    out = np.zeros(cap, np.int32)
    out[:n] = ids
    return out


def _lookup_batch(vdict, raw: np.ndarray) -> np.ndarray:
    """Raw -> compact ids WITHOUT inserting; -1 marks unseen vertices.
    Uses the dict's vectorized ``lookup_batch`` when it exists, else the
    per-id ``lookup``."""
    raw = np.asarray(raw, np.int64)
    batch = getattr(vdict, "lookup_batch", None)
    if batch is not None:
        return batch(raw)
    lookup = getattr(vdict, "lookup", None)
    if lookup is None:
        raise TypeError(
            f"payload vertex dict {type(vdict).__name__} supports neither "
            "lookup_batch nor lookup"
        )
    out = np.empty(len(raw), np.int32)
    for i, r in enumerate(raw.tolist()):
        c = lookup(r)
        out[i] = -1 if c is None else c
    return out


def _host_batch_roots(lab: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Whole-batch vectorized root chase on a host table (the CPU-backend
    fast path; same contract as :func:`_batch_roots`)."""
    r = lab[ids]
    while True:
        nxt = lab[r]
        if np.array_equal(nxt, r):
            return r
        r = nxt


class QueryEngine:
    """Answers homogeneous query batches against one snapshot.

    Stateless except for per-snapshot-version caches: the derived
    component-size table, and (host path) one host materialization of
    each payload table — the O(vcap) costs; everything else is
    batch-sized. One engine instance per server.

    ``prefer_host='auto'`` (default) picks the host path on the CPU
    backend and the jitted device path elsewhere (rationale in the
    module docstring); pass True/False to pin."""

    #: payload key each query class reads (also the capability probe:
    #: a snapshot serves a query class iff the key is present)
    PAYLOAD_KEYS = {
        ConnectedQuery: "labels",
        ComponentSizeQuery: "labels",
        SummaryPullQuery: "labels",
        DegreeQuery: "deg",
        RankQuery: "ranks",
        BipartiteQuery: "cover",
    }

    def __init__(self, prefer_host="auto"):
        if prefer_host == "auto":
            prefer_host = jax.default_backend() == "cpu"
        self.prefer_host = bool(prefer_host)
        self._size_cache: Tuple[Optional[tuple], Any, Any] = (
            None, None, None,
        )
        self._host_cache: dict = {}  # (version, payload key) -> np array
        self._pull_cache: Tuple[Optional[int], Optional[dict]] = (
            None, None,
        )
        self._bp_cache: Tuple[Optional[int], Optional[dict]] = (
            None, None,
        )

    # -- table access (per-version host cache on the host path) -------- #
    def _table(self, snap: PublishedSnapshot, key: str):
        """The payload table, as a host array (host path, cached per
        snapshot version) or the device array as-is (device path)."""
        table = snap.payload[key]
        if not self.prefer_host:
            return table
        ck = (snap.version, key)
        cached = self._host_cache.get(ck)
        if cached is None:
            # np.asarray waits for THIS array's producer, not the whole
            # dispatch queue — the property the host path exists for
            cached = np.asarray(table)
            self._host_cache.clear()  # only the newest version is hot
            self._host_cache[ck] = cached
        return cached

    def _roots(self, table, ids: np.ndarray) -> np.ndarray:
        if self.prefer_host:
            return _host_batch_roots(table, ids)
        return np.asarray(
            _batch_roots(jnp.asarray(table), jnp.asarray(_pad_ids(ids)))
        )[: len(ids)]

    # -- per-class batch kernels --------------------------------------- #
    def connected(
        self, snap: PublishedSnapshot, us: np.ndarray, vs: np.ndarray
    ) -> np.ndarray:
        """bool[n]: same component per (u, v) pair, one batched chase for
        all 2n endpoints."""
        canon = self._table(snap, "labels")
        vdict = snap.payload["vdict"]
        # ONE lookup for all 2n endpoints: the batched native lookup
        # takes the encoder mutex once per call, so separate u/v calls
        # would double lock contention with the ingest thread
        both = _lookup_batch(
            vdict, np.concatenate([np.asarray(us), np.asarray(vs)])
        )
        vcap = int(canon.shape[0])
        valid = (both >= 0) & (both < vcap)
        roots = self._roots(canon, np.where(valid, both, 0))
        n = len(us)
        ru, rv = roots[:n], roots[n:]
        ok = valid[:n] & valid[n:]
        # an unseen vertex is its own singleton: connected only to itself
        return np.where(ok, ru == rv, np.asarray(us) == np.asarray(vs))

    def component_size(
        self, snap: PublishedSnapshot, vs: np.ndarray
    ) -> np.ndarray:
        """int[n] component sizes; the size table derives once per
        snapshot version. Sizes count COMPACT ids sharing the root —
        vertices the stream has actually seen (plus the queried vertex's
        own singleton when it is seen but never merged)."""
        canon = self._table(snap, "labels")
        vdict = snap.payload["vdict"]
        cv = _lookup_batch(vdict, vs)
        key = (snap.version, id(snap.payload["labels"]))
        cached_key, lab, sizes = self._size_cache
        if cached_key != key:
            if self.prefer_host:
                from ..summaries.forest import resolve_flat_host

                lab = resolve_flat_host(np.asarray(canon))
                sizes = np.bincount(lab, minlength=len(canon))
            else:
                lab, sizes = _component_size_table(jnp.asarray(canon))
            # vcap-sized slots past the seen count are self-rooted
            # singletons; they root themselves, never a seen component,
            # so seen roots count only seen members
            self._size_cache = (key, lab, sizes)
        vcap = int(canon.shape[0])
        valid = (cv >= 0) & (cv < vcap)
        # the cached table is FULLY canonical: every vertex's root is one
        # gather away — no per-batch chase needed here
        safe = np.where(valid, cv, 0)
        if self.prefer_host:
            out = np.asarray(sizes)[np.asarray(lab)[safe]]
        else:
            out = np.asarray(
                _gather_sizes(lab, sizes, jnp.asarray(_pad_ids(safe)))
            )[: len(cv)]
        return np.where(valid, out, 0).astype(np.int64)

    def summary_pull(self, snap: PublishedSnapshot) -> dict:
        """The snapshot's CC forest as a mergeable raw-id summary (the
        :class:`SummaryPullQuery` answer value)::

            {"n": slots, "u64": b64(int64 raw ids),
             "r64": b64(int64 root raw ids)}

        Slot coverage is what the payload's vertex dict can decode
        (``len(vdict)`` slots): the shard's SEEN keyspace. Deployments
        that want untouched in-bound ids to count as singletons (the
        ``IdentityDict`` single-host semantics) observe their bound up
        front, like the serving demos do. Cached per snapshot version —
        the O(vcap) canonicalize + decode runs once however many
        routers pull."""
        import base64

        ver, cached = self._pull_cache
        if ver == snap.version and cached is not None:
            return cached
        from ..summaries.forest import resolve_flat_host

        canon = np.asarray(self._table(snap, "labels"))
        vdict = snap.payload["vdict"]
        lab = resolve_flat_host(canon)
        n = min(int(lab.shape[0]), len(vdict))
        slots = np.arange(n, dtype=np.int64)
        raws = np.asarray(vdict.decode(slots), np.int64)
        # min-rooted invariant: lab[i] <= i, so every root of the first
        # n slots is itself within the first n slots
        roots = np.asarray(vdict.decode(lab[:n].astype(np.int64)),
                           np.int64)
        doc = {
            "n": int(n),
            "u64": base64.b64encode(
                np.ascontiguousarray(raws).tobytes()).decode("ascii"),
            "r64": base64.b64encode(
                np.ascontiguousarray(roots).tobytes()).decode("ascii"),
        }
        self._pull_cache = (snap.version, doc)
        return doc

    def bipartite(self, snap: PublishedSnapshot) -> dict:
        """The :class:`BipartiteQuery` answer value (see its docstring).

        Seen base vertices come from the payload's touch evidence —
        either the append-only log view (``tids``/``tcount``, the
        forest-carry publish shape: the first ``tcount`` entries of an
        append-only log never change, so the published ref is a valid
        snapshot) or a ``touched`` bool table (the dense carry /
        restored-checkpoint shape). Cached per snapshot version: the
        O(vcap) canonicalize + conflict scan runs once however many
        clients ask."""
        ver, cached = self._bp_cache
        if ver == snap.version and cached is not None:
            return cached
        from ..summaries.forest import resolve_flat_host

        cover = np.asarray(self._table(snap, "cover"))
        vdict = snap.payload["vdict"]
        vcap = cover.shape[0] // 2
        lab = resolve_flat_host(cover)
        if "tids" in snap.payload:
            tids = np.asarray(
                snap.payload["tids"][: snap.payload["tcount"]], np.int64
            )
            tids = tids[tids < vcap]
        else:
            touched = np.asarray(snap.payload["touched"])
            tids = np.nonzero(touched[:vcap])[0]
        conflicted = tids[lab[tids] == lab[tids + vcap]]
        if len(conflicted):
            witness = int(
                np.min(np.asarray(vdict.decode(conflicted), np.int64))
            )
            doc = {"bipartite": False, "witness": witness}
        else:
            doc = {"bipartite": True, "witness": None}
        self._bp_cache = (snap.version, doc)
        return doc

    def degree(self, snap: PublishedSnapshot, vs: np.ndarray) -> np.ndarray:
        return self._table_gather(snap, "deg", vs, fill=0)

    def rank(self, snap: PublishedSnapshot, vs: np.ndarray) -> np.ndarray:
        return self._table_gather(snap, "ranks", vs, fill=0.0)

    def _table_gather(
        self, snap: PublishedSnapshot, key: str, vs: np.ndarray, fill
    ) -> np.ndarray:
        table = self._table(snap, key)
        vdict = snap.payload["vdict"]
        cv = _lookup_batch(vdict, vs)
        vcap = int(table.shape[0])
        valid = (cv >= 0) & (cv < vcap)
        safe = np.where(valid, cv, 0)
        if self.prefer_host:
            got = table[safe]
        else:
            got = np.asarray(
                _gather(jnp.asarray(table), jnp.asarray(_pad_ids(safe)))
            )[: len(cv)]
        return np.where(valid, got, fill)

    # -- heterogeneous batch ------------------------------------------- #
    def answer_batch(
        self,
        snap: PublishedSnapshot,
        queries: Sequence[Query],
        head_window: Optional[int] = None,
    ) -> List[Answer]:
        """Answer a mixed batch: group by query class, one vectorized
        kernel per class present, answers re-ordered to match the input.
        ``head_window`` (default: this snapshot's window) stamps each
        answer's staleness gauge."""
        head = snap.window if head_window is None else head_window
        staleness = max(0, head - snap.window)
        out: List[Optional[Answer]] = [None] * len(queries)
        groups: Dict[type, List[int]] = {}
        for i, q in enumerate(queries):
            groups.setdefault(type(q), []).append(i)
        for qcls, idxs in groups.items():
            key = self.PAYLOAD_KEYS.get(qcls)
            if key is None or key not in snap.payload:
                raise TypeError(
                    f"snapshot payload (keys {sorted(snap.payload)}) does "
                    f"not serve {qcls.__name__}"
                )
            if qcls in (SummaryPullQuery, BipartiteQuery):
                # one cached doc answers the whole group (dict-valued,
                # so it bypasses the ndarray tail below)
                doc = (
                    self.summary_pull(snap)
                    if qcls is SummaryPullQuery else self.bipartite(snap)
                )
                for i in idxs:
                    out[i] = Answer(
                        value=doc, window=snap.window,
                        watermark=snap.watermark, staleness=staleness,
                        version=snap.version,
                    )
                continue
            if qcls is ConnectedQuery:
                us = np.asarray([queries[i].u for i in idxs], np.int64)
                vs = np.asarray([queries[i].v for i in idxs], np.int64)
                vals = self.connected(snap, us, vs)
            else:
                vs = np.asarray([queries[i].v for i in idxs], np.int64)
                if qcls is DegreeQuery:
                    vals = self.degree(snap, vs)
                elif qcls is RankQuery:
                    vals = self.rank(snap, vs)
                else:
                    vals = self.component_size(snap, vs)
            for i, v in zip(idxs, vals.tolist()):
                out[i] = Answer(
                    value=v, window=snap.window,
                    watermark=snap.watermark, staleness=staleness,
                    version=snap.version,
                )
        return out  # type: ignore[return-value]
