"""Typed point queries + the batched vectorized query engine.

A serving tier dies by per-query host loops: 10k concurrent
``connected(u, v)`` queries must not become 10k pointer chases in Python
or 10k device dispatches. The :class:`QueryEngine` answers a whole batch
per query class with ONE jitted lookup:

- CC queries gather the batch's endpoints out of the published pointer
  forest and chase ONLY those lanes to their roots (a batch-sized
  ``lax.while_loop`` of gathers — the same kernel shape as
  ``summaries/forest.py:chase_and_group``, sized by the batch, not the
  vertex capacity). Flat labels are a valid (depth-1) forest, so the one
  kernel serves every CC carry and restored checkpoints alike.
- Degree / rank queries are one table gather.
- Component-size queries canonicalize the forest once per snapshot
  version (cached) and bincount, then answer any number of batches from
  the cached size table.

Batch id arrays are padded to power-of-two buckets so a serving session
compiles O(log batch-size) jit signatures, the stream-ingest convention
(``core/edgeblock.py:bucket_capacity``).

Two execution paths, picked per backend (``prefer_host="auto"``):

- **device** (accelerators): the jitted batch kernels run where the
  payload lives; only the batch-sized result crosses the link — right
  when D2H bandwidth is the scarce resource (a remote-TPU tunnel moves
  ~4-18 MB/s, so shipping a vcap-sized table per snapshot would cap the
  read path at ~1 snapshot/s).
- **host** (the CPU backend): queries answered by the jitted path
  ENQUEUE at the tail of the same XLA dispatch queue the async window
  folds fill, so each batch waits out the whole in-flight pipeline
  (measured ~230 ms p50 behind 1M-edge windows) and its sync stalls
  ingest. Instead the engine lazily materializes ONE host copy of the
  payload table per snapshot version (a wait-on-this-array transfer,
  not a tail-of-queue dispatch) and answers with the same whole-batch
  vectorized chase in numpy — still never per-query loops.

Query ids are RAW vertex ids (what a client knows); the engine maps them
through the payload's vertex dictionary without inserting — unseen
vertices answer like the reference's ``DisjointSet`` would for a vertex
it never saw: connected only to itself, degree 0, rank 0.0, component
size 0.
"""

from __future__ import annotations

import base64
import binascii
import pickle
import threading
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.edgeblock import bucket_capacity
from ..obs.registry import get_registry
from .snapshot_store import PublishedSnapshot


# --------------------------------------------------------------------- #
# Query + answer records
# --------------------------------------------------------------------- #
class Query:
    """Marker base for point queries (raw vertex ids)."""

    __slots__ = ()


@dataclass(frozen=True)
class ConnectedQuery(Query):
    """Are ``u`` and ``v`` in one component? (``connected(u, v)``)."""

    u: int
    v: int


@dataclass(frozen=True)
class DegreeQuery(Query):
    """Current degree of ``v``."""

    v: int


@dataclass(frozen=True)
class RankQuery(Query):
    """Current PageRank mass of ``v``."""

    v: int


@dataclass(frozen=True)
class ComponentSizeQuery(Query):
    """Size of ``v``'s component (0 for a never-seen vertex)."""

    v: int


@dataclass(frozen=True)
class SummaryPullQuery(Query):
    """Pull this snapshot's CC forest as a mergeable summary (the
    sharded-serving router's cross-shard union input): per seen slot,
    the RAW vertex id and its component root's RAW id, as packed
    little-endian int64 columns (base64 in the JSON answer value).
    RAW-id space is the join key — per-shard compact ids never leave
    their shard. O(vcap) per snapshot version, cached by the engine, so
    any number of pulls per version cost one canonicalization.

    ``since_version`` is the pull protocol's v2 field: a puller that
    already holds this shard's table at that version asks for only the
    rows whose ROOT assignment changed since then (a ``kind="delta"``
    reply, O(changed rows) on the wire). ``-1`` (the v1 shape — old
    peers never set the field) always answers the full table; a
    ``since_version`` older than the engine's bounded delta ring
    degrades HONESTLY to a full reply tagged with why."""

    since_version: int = -1


@dataclass(frozen=True)
class BipartiteQuery(Query):
    """Is the streamed graph (still) bipartite? Graph-global, like
    :class:`SummaryPullQuery`. The answer value is a typed dict::

        {"bipartite": bool, "witness": raw_id | None}

    ``witness`` is the smallest RAW vertex id whose two signed-cover
    nodes share a component — a vertex on an odd cycle, the conflict
    witness — when the graph is non-bipartite, else None. Answered from
    the published cover forest (``summaries/candidates.py`` layout:
    cover node (v,+) = v, (v,-) = v + vcap in a 2*vcap table), so the
    verdict recomputes from the structural truth rather than trusting a
    carried latch. O(vcap) per snapshot version, cached by the engine.
    """

    __slots__ = ()


@dataclass(frozen=True)
class Answer:
    """One query's result, stamped with the snapshot it was answered
    from: ``window`` is that snapshot's window index, ``staleness`` the
    windows-behind-head gap at answer time (0 = answered at the head),
    ``version`` the snapshot's publish version — the monotone counter a
    routing tier keys its cache invalidation on (reply frames carry it,
    so a router learns of shard progress from ordinary answers).
    ``event_ts`` is the snapshot's EVENT-TIME watermark (``-1`` when
    the pipeline carries no event time): next to ``staleness``'s
    windows-behind-head, it answers "how far behind the world" — the
    data's own clock at the moment the served summaries were true.
    ``shard`` and ``boot`` (ISSUE 20) complete the stamp a
    snapshot-pinned transaction needs: which shard answered (``-1``
    for an unsharded replica; the router re-stamps its fan-outs) and
    the answering store's lineage nonce — together with ``version``
    they are the ``(shard, version, boot)`` triple a
    :class:`~gelly_streaming_tpu.serving.txn.TxnContext` pins from
    ordinary replies, with no extra round trip."""

    value: Any
    window: int
    watermark: int
    staleness: int
    version: int = 0
    event_ts: int = -1
    shard: int = -1
    boot: str = ""


# --------------------------------------------------------------------- #
# Pull-doc wire codec (protocol v2: full | delta reply frames)
# --------------------------------------------------------------------- #
#: how many version-to-version delta segments the engine retains; a
#: ``since_version`` older than the ring reaches degrades to a full
#: reply (tagged ``why="stale"``) — the bounded-memory honesty rule
DELTA_RING = 8


class MalformedPull(ValueError):
    """A pull doc that fails decode, carrying WHICH geometry rule broke
    (``kind`` in {type, missing, b64, geometry, tag, base}) so the
    router can count malformed pulls by failure class instead of
    folding them into a generic pull error."""

    def __init__(self, kind: str, msg: str):
        super().__init__(msg)
        self.kind = kind


def _b64_cols(raws: np.ndarray, roots: np.ndarray) -> Tuple[str, str]:
    return (
        base64.b64encode(
            np.ascontiguousarray(raws, np.int64).tobytes()).decode("ascii"),
        base64.b64encode(
            np.ascontiguousarray(roots, np.int64).tobytes()).decode("ascii"),
    )


def encode_pull_doc(
    raws: np.ndarray,
    roots: np.ndarray,
    *,
    kind: str = "full",
    base: Optional[int] = None,
    why: Optional[str] = None,
) -> dict:
    """Pack ``(vertex, root)`` RAW-id columns as a pull reply doc.

    ``kind="full"`` is the whole-table frame (v1 peers decode it
    unchanged: the tag rides an extra dict key they never read);
    ``kind="delta"`` carries only changed rows plus ``base`` — the
    version the rows are a diff AGAINST, which the puller must already
    hold. ``why`` tags a full reply that a delta request degraded into
    (stale ring, no chain yet, puller ahead). Every key written here is
    read back in :func:`decode_pull_doc` (GL011 symmetry)."""
    u64, r64 = _b64_cols(raws, roots)
    doc = {"kind": kind, "n": int(len(raws)), "u64": u64, "r64": r64}
    if kind == "delta":
        if base is None:
            raise ValueError("delta pull docs must carry base")
        doc["base"] = int(base)
    if why is not None:
        doc["why"] = str(why)
    return doc


def decode_pull_doc(doc) -> dict:
    """Decode a pull reply into host columns::

        {"kind": "full"|"delta", "n": int,
         "u": int64[n], "r": int64[n], "base": int|None, "why": str|None}

    A doc with NO ``kind`` tag decodes as a full frame — that is the v1
    wire shape, so a v2 puller interops with an old shard by treating
    its replies as full tables and resetting its delta baseline.
    Raises :class:`MalformedPull` (kind-tagged) on any geometry
    mismatch; a decoded frame is safe to merge as-is."""
    if not isinstance(doc, dict):
        raise MalformedPull(
            "type", f"pull answer must be a dict, got {type(doc).__name__}"
        )
    kind = doc.get("kind", "full")
    if kind not in ("full", "delta"):
        raise MalformedPull("tag", f"unknown pull frame kind {kind!r}")
    for k in ("n", "u64", "r64"):
        if k not in doc:
            raise MalformedPull("missing", f"pull doc lacks {k!r}")
    n = doc["n"]
    if not isinstance(n, int) or isinstance(n, bool) or n < 0:
        raise MalformedPull("type", f"pull doc n must be an int >= 0, got {n!r}")
    if not isinstance(doc["u64"], str) or not isinstance(doc["r64"], str):
        raise MalformedPull("type", "pull doc u64/r64 must be base64 strings")
    try:
        ub = base64.b64decode(doc["u64"], validate=True)
        rb = base64.b64decode(doc["r64"], validate=True)
    except (binascii.Error, ValueError) as e:
        raise MalformedPull("b64", f"pull doc columns are not base64: {e}")
    if len(ub) != 8 * n or len(rb) != 8 * n:
        raise MalformedPull(
            "geometry",
            f"pull doc geometry mismatch: n={n} but columns carry "
            f"{len(ub)}/{len(rb)} bytes (want {8 * n})",
        )
    base = doc.get("base")
    if kind == "delta":
        if not isinstance(base, int) or isinstance(base, bool):
            raise MalformedPull(
                "base", f"delta pull doc must carry an int base, got {base!r}"
            )
    why = doc.get("why")
    return {
        "kind": kind,
        "n": n,
        "u": np.frombuffer(ub, np.int64),
        "r": np.frombuffer(rb, np.int64),
        "base": base if kind == "delta" else None,
        "why": str(why) if why is not None else None,
    }


# --------------------------------------------------------------------- #
# Vectorized kernels (batch-sized, payload-table-gathering)
# --------------------------------------------------------------------- #
@jax.jit
def _batch_roots(canon: jax.Array, ids: jax.Array) -> jax.Array:
    """Chase a BATCH of start ids to their forest roots. Read-only on
    ``canon``; terminates by the min-root invariant (chains strictly
    decrease). Padding lanes chase from 0, always self-rooted."""
    r = canon[ids]
    return lax.while_loop(
        lambda r: jnp.any(canon[r] != r), lambda r: canon[r], r
    )


@jax.jit
def _gather(table: jax.Array, ids: jax.Array) -> jax.Array:
    return table[ids]


@jax.jit
def _gather_sizes(lab: jax.Array, sizes: jax.Array, ids: jax.Array) -> jax.Array:
    """Fused root-resolve + size lookup over a canonical table: ONE
    dispatch, only the batch-sized result crosses the link."""
    return sizes[lab[ids]]


@jax.jit
def _component_size_table(canon: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Canonicalize the whole forest once and count members per root.
    O(vcap) — run once per snapshot version, cached by the engine.
    The canonicalization IS ``summaries/forest.py:resolve_flat`` (one
    copy of the kernel; this jit just fuses the bincount after it)."""
    from ..summaries.forest import resolve_flat

    lab = resolve_flat(canon)
    sizes = jnp.zeros(canon.shape[0], jnp.int32).at[lab].add(1)
    return lab, sizes


def _pad_ids(ids: np.ndarray) -> np.ndarray:
    """Bucket a compact-id batch to pow2 (pad with 0 — a safe self-rooted
    lane) so jit signatures stay O(log batch-size)."""
    n = len(ids)
    cap = bucket_capacity(max(n, 1), minimum=8)
    out = np.zeros(cap, np.int32)
    out[:n] = ids
    return out


def _lookup_batch(vdict, raw: np.ndarray) -> np.ndarray:
    """Raw -> compact ids WITHOUT inserting; -1 marks unseen vertices.
    Uses the dict's vectorized ``lookup_batch`` when it exists, else the
    per-id ``lookup``."""
    raw = np.asarray(raw, np.int64)
    batch = getattr(vdict, "lookup_batch", None)
    if batch is not None:
        return batch(raw)
    lookup = getattr(vdict, "lookup", None)
    if lookup is None:
        raise TypeError(
            f"payload vertex dict {type(vdict).__name__} supports neither "
            "lookup_batch nor lookup"
        )
    out = np.empty(len(raw), np.int32)
    for i, r in enumerate(raw.tolist()):
        c = lookup(r)
        out[i] = -1 if c is None else c
    return out


def _host_batch_roots(lab: np.ndarray, ids: np.ndarray) -> np.ndarray:
    """Whole-batch vectorized root chase on a host table (the CPU-backend
    fast path; same contract as :func:`_batch_roots`)."""
    r = lab[ids]
    while True:
        nxt = lab[r]
        if np.array_equal(nxt, r):
            return r
        r = nxt


class QueryEngine:
    """Answers homogeneous query batches against one snapshot.

    Stateless except for per-snapshot-version caches: the derived
    component-size table, and (host path) one host materialization of
    each payload table — the O(vcap) costs; everything else is
    batch-sized. One engine instance per server.

    ``prefer_host='auto'`` (default) picks the host path on the CPU
    backend and the jitted device path elsewhere (rationale in the
    module docstring); pass True/False to pin."""

    #: payload key each query class reads (also the capability probe:
    #: a snapshot serves a query class iff the key is present)
    PAYLOAD_KEYS = {
        ConnectedQuery: "labels",
        ComponentSizeQuery: "labels",
        SummaryPullQuery: "labels",
        DegreeQuery: "deg",
        RankQuery: "ranks",
        BipartiteQuery: "cover",
    }

    def __init__(self, prefer_host="auto"):
        if prefer_host == "auto":
            prefer_host = jax.default_backend() == "cpu"
        self.prefer_host = bool(prefer_host)
        self._size_cache: Tuple[Optional[tuple], Any, Any] = (
            None, None, None,
        )
        self._host_cache: dict = {}  # (epoch, version, payload key) -> np
        # pull docs cache: one dict per (epoch, version), keyed by the
        # effective since_version (-1 = full) — several routers at
        # different baselines share one engine without thrashing
        self._pull_key: Optional[tuple] = None
        self._pull_docs: dict = {}
        # historical (pinned) pull docs: a bounded side cache so
        # transactional merges never thrash the live head's cache
        self._hist_docs: dict = {}
        self._bp_cache: Tuple[Optional[tuple], Optional[dict]] = (
            None, None,
        )
        # delta chain: the canonical table at the last pulled version
        # plus a bounded ring of version-to-version changed-row segments
        self._chain_epoch: Optional[int] = None
        self._chain_version: int = -1
        self._chain_lab: Optional[np.ndarray] = None
        self._chain_n: int = 0
        self._ring: deque = deque(maxlen=DELTA_RING)
        # the chain is touched from the server worker (summary_pull)
        # AND, when a PullRingMirror is attached, from the ingest
        # thread's publish listener (chain_sync) — hence the lock
        self._chain_lock = threading.Lock()

    # -- table access (per-version host cache on the host path) -------- #
    def _table(self, snap: PublishedSnapshot, key: str):
        """The payload table, as a host array (host path, cached per
        snapshot (epoch, version)) or the device array as-is (device
        path)."""
        table = snap.payload[key]
        if not self.prefer_host:
            return table
        ck = (snap.epoch, snap.version, key)
        cached = self._host_cache.get(ck)
        if cached is None:
            # np.asarray waits for THIS array's producer, not the whole
            # dispatch queue — the property the host path exists for
            cached = np.asarray(table)
            self._host_cache.clear()  # only the newest version is hot
            self._host_cache[ck] = cached
        return cached

    def _roots(self, table, ids: np.ndarray) -> np.ndarray:
        if self.prefer_host:
            return _host_batch_roots(table, ids)
        return np.asarray(
            _batch_roots(jnp.asarray(table), jnp.asarray(_pad_ids(ids)))
        )[: len(ids)]

    # -- per-class batch kernels --------------------------------------- #
    def connected(
        self, snap: PublishedSnapshot, us: np.ndarray, vs: np.ndarray
    ) -> np.ndarray:
        """bool[n]: same component per (u, v) pair, one batched chase for
        all 2n endpoints."""
        canon = self._table(snap, "labels")
        vdict = snap.payload["vdict"]
        # ONE lookup for all 2n endpoints: the batched native lookup
        # takes the encoder mutex once per call, so separate u/v calls
        # would double lock contention with the ingest thread
        both = _lookup_batch(
            vdict, np.concatenate([np.asarray(us), np.asarray(vs)])
        )
        vcap = int(canon.shape[0])
        valid = (both >= 0) & (both < vcap)
        roots = self._roots(canon, np.where(valid, both, 0))
        n = len(us)
        ru, rv = roots[:n], roots[n:]
        ok = valid[:n] & valid[n:]
        # an unseen vertex is its own singleton: connected only to itself
        return np.where(ok, ru == rv, np.asarray(us) == np.asarray(vs))

    def component_size(
        self, snap: PublishedSnapshot, vs: np.ndarray
    ) -> np.ndarray:
        """int[n] component sizes; the size table derives once per
        snapshot version. Sizes count COMPACT ids sharing the root —
        vertices the stream has actually seen (plus the queried vertex's
        own singleton when it is seen but never merged)."""
        canon = self._table(snap, "labels")
        vdict = snap.payload["vdict"]
        cv = _lookup_batch(vdict, vs)
        key = (snap.epoch, snap.version, id(snap.payload["labels"]))
        cached_key, lab, sizes = self._size_cache
        if cached_key != key:
            if self.prefer_host:
                from ..summaries.forest import resolve_flat_host

                lab = resolve_flat_host(np.asarray(canon))
                sizes = np.bincount(lab, minlength=len(canon))
            else:
                lab, sizes = _component_size_table(jnp.asarray(canon))
            # vcap-sized slots past the seen count are self-rooted
            # singletons; they root themselves, never a seen component,
            # so seen roots count only seen members
            self._size_cache = (key, lab, sizes)
        vcap = int(canon.shape[0])
        valid = (cv >= 0) & (cv < vcap)
        # the cached table is FULLY canonical: every vertex's root is one
        # gather away — no per-batch chase needed here
        safe = np.where(valid, cv, 0)
        if self.prefer_host:
            out = np.asarray(sizes)[np.asarray(lab)[safe]]
        else:
            out = np.asarray(
                _gather_sizes(lab, sizes, jnp.asarray(_pad_ids(safe)))
            )[: len(cv)]
        return np.where(valid, out, 0).astype(np.int64)

    def summary_pull(
        self, snap: PublishedSnapshot, since_version: int = -1
    ) -> dict:
        """The snapshot's CC forest as a mergeable raw-id summary (the
        :class:`SummaryPullQuery` answer value; wire shape in
        :func:`encode_pull_doc`).

        ``since_version < 0`` answers the FULL table — slot coverage is
        what the payload's vertex dict can decode (``len(vdict)``
        slots): the shard's SEEN keyspace. Deployments that want
        untouched in-bound ids to count as singletons (the
        ``IdentityDict`` single-host semantics) observe their bound up
        front, like the serving demos do.

        ``since_version >= 0`` asks for only the rows whose root
        assignment changed since that version. The engine maintains a
        delta CHAIN: per pulled version it diffs the canonical table
        against the previous one over the TouchLog-seen candidate set
        (root changes only ever land on vertices some edge touched) and
        keeps the last :data:`DELTA_RING` segments. A covered
        ``since_version`` answers the deduped union of the covering
        segments (newest root per raw id); an uncovered one degrades
        honestly to a full reply tagged ``why`` (stale ring, no chain,
        or a puller ahead of this store — the restarted-shard case).
        Stale rows across segments stay sound to merge because the
        stream is add-only: a ``(vertex, root)`` pair once true is a
        connectivity fact forever. Docs are cached per
        ``(epoch, version, since)`` — the O(vcap) canonicalize + decode
        runs once however many routers pull.

        A pull against a snapshot BEHIND the chain head (a pinned
        transactional read from the retention ring, ISSUE 20) takes a
        read-only historical path: advancing the chain to an older
        version would CLEAR the ring (the backward-version reset), so
        the live chain is never touched — the historical version is
        served from the covering ring segments when they reach it,
        else from a full canonicalization of that snapshot's own
        payload (the ring retains payloads, so the table is right
        there)."""
        with self._chain_lock:
            key = (snap.epoch, snap.version)
            if (
                self._chain_lab is not None
                and self._chain_epoch == snap.epoch
                and snap.version < self._chain_version
            ):
                return self._historical_pull_locked(
                    snap, int(since_version))
            if self._pull_key != key:
                self._advance_chain_locked(snap)
                self._pull_key = key
                self._pull_docs = {}
            since = int(since_version)
            eff = since if since >= 0 else -1
            cached = self._pull_docs.get(eff)
            if cached is None:
                cached = self._build_pull_doc(snap, eff)
                self._pull_docs[eff] = cached
            return cached

    def chain_sync(self, snap: PublishedSnapshot) -> None:
        """Advance the delta chain to ``snap`` without answering a
        pull — the :class:`PullRingMirror` hook.  Runs on the ingest
        thread (publish listener); idempotent per (epoch, version), so
        a later ``summary_pull`` at the same snapshot reuses the
        already-advanced chain."""
        with self._chain_lock:
            key = (snap.epoch, snap.version)
            if self._pull_key != key:
                self._advance_chain_locked(snap)
                self._pull_key = key
                self._pull_docs = {}

    def _advance_chain_locked(self, snap: PublishedSnapshot) -> None:
        """Canonicalize this snapshot's forest and record the changed
        rows since the previous pulled version as one ring segment.
        Resets the chain (no segment) on a store swap — a new epoch or
        a version that went BACKWARD means the diff base is gone."""
        from ..summaries.forest import resolve_flat_host

        canon = np.asarray(self._table(snap, "labels"))
        vdict = snap.payload["vdict"]
        lab = resolve_flat_host(canon)
        n = min(int(lab.shape[0]), len(vdict))
        if (
            self._chain_lab is None
            or self._chain_epoch != snap.epoch
            or snap.version < self._chain_version
        ):
            self._ring.clear()
        else:
            n_old = self._chain_n
            old = self._chain_lab
            if "tids" in snap.payload:
                # the TouchLog novelty shadow bounds the diff: a root
                # can only change on a vertex some edge ever touched
                cand = np.asarray(
                    snap.payload["tids"][: snap.payload["tcount"]],
                    np.int64,
                )
                cand = cand[cand < n_old]
            else:
                cand = np.arange(n_old, dtype=np.int64)
            changed = cand[lab[cand] != old[cand]]
            if n > n_old:
                changed = np.concatenate(
                    [changed, np.arange(n_old, n, dtype=np.int64)]
                )
            changed = np.unique(changed)
            raws = np.asarray(vdict.decode(changed), np.int64)
            roots = np.asarray(
                vdict.decode(lab[changed].astype(np.int64)), np.int64
            )
            self._ring.append(
                {"base": self._chain_version, "to": snap.version,
                 "u": raws, "r": roots}
            )
        self._chain_epoch = snap.epoch
        self._chain_version = snap.version
        self._chain_lab = np.array(lab, copy=True)
        self._chain_n = n

    def _historical_pull_locked(
        self, snap: PublishedSnapshot, since: int
    ) -> dict:
        """Serve a pull pinned at a version BEHIND the chain head
        without touching the live chain (see :meth:`summary_pull`).
        Delta when the ring's consecutive segments span exactly
        ``(since, snap.version]``; else a full table canonicalized
        from the historical snapshot's own payload, tagged
        ``why="pinned"`` (or ``"ahead"`` for a baseline past the pin).
        Cached per ``(epoch, version, since)`` in a small side cache so
        a transaction's repeated merges cost one canonicalization."""
        eff = since if since >= 0 else -1
        hkey = (snap.epoch, snap.version, eff)
        cached = self._hist_docs.get(hkey)
        if cached is not None:
            return cached
        doc = None
        why = "pinned"
        if eff == snap.version:
            empty = np.zeros(0, np.int64)
            doc = encode_pull_doc(empty, empty, kind="delta", base=eff)
        elif eff > snap.version:
            why = "ahead"
        elif eff >= 0:
            segs = [s for s in self._ring
                    if eff < s["to"] <= snap.version]
            if (segs and segs[0]["base"] <= eff
                    and segs[-1]["to"] == snap.version):
                ru = np.concatenate([s["u"] for s in reversed(segs)])
                rr = np.concatenate([s["r"] for s in reversed(segs)])
                _, idx = np.unique(ru, return_index=True)
                doc = encode_pull_doc(
                    ru[idx], rr[idx], kind="delta", base=eff)
        if doc is None:
            from ..summaries.forest import resolve_flat_host

            # straight off the historical payload — NOT via _table's
            # single-slot host cache, which must stay hot for the head
            canon = np.asarray(snap.payload["labels"])
            vdict = snap.payload["vdict"]
            lab = resolve_flat_host(canon)
            n = min(int(lab.shape[0]), len(vdict))
            slots = np.arange(n, dtype=np.int64)
            raws = np.asarray(vdict.decode(slots), np.int64)
            roots = np.asarray(
                vdict.decode(lab[:n].astype(np.int64)), np.int64)
            doc = encode_pull_doc(raws, roots, kind="full", why=why)
        while len(self._hist_docs) >= 8:
            self._hist_docs.pop(next(iter(self._hist_docs)))
        self._hist_docs[hkey] = doc
        return doc

    def _build_pull_doc(self, snap: PublishedSnapshot, since: int) -> dict:
        vdict = snap.payload["vdict"]
        lab = self._chain_lab
        n = self._chain_n
        why = None
        if since >= 0:
            if since > snap.version:
                why = "ahead"
            elif since == snap.version:
                empty = np.zeros(0, np.int64)
                return encode_pull_doc(
                    empty, empty, kind="delta", base=since
                )
            else:
                segs = [s for s in self._ring if s["to"] > since]
                if segs and segs[0]["base"] <= since:
                    # newest-first concat + unique keeps the NEWEST
                    # root per raw id (unique returns first occurrence)
                    ru = np.concatenate(
                        [s["u"] for s in reversed(segs)])
                    rr = np.concatenate(
                        [s["r"] for s in reversed(segs)])
                    _, idx = np.unique(ru, return_index=True)
                    return encode_pull_doc(
                        ru[idx], rr[idx], kind="delta", base=since
                    )
                why = "stale" if self._ring else "no_chain"
        slots = np.arange(n, dtype=np.int64)
        raws = np.asarray(vdict.decode(slots), np.int64)
        # min-rooted invariant: lab[i] <= i, so every root of the first
        # n slots is itself within the first n slots
        roots = np.asarray(vdict.decode(lab[:n].astype(np.int64)),
                           np.int64)
        return encode_pull_doc(raws, roots, kind="full", why=why)

    # -- delta-ring persistence (ISSUE 19 satellite, PR 17 residual) --- #
    def chain_state(self) -> dict:
        """A picklable copy of the delta chain: the canonical table at
        the last pulled version plus the ring segments.  Empty dict
        before the chain exists.  The copy is what
        :class:`PullRingMirror` persists so a RESTARTED shard can keep
        serving delta pulls instead of always paying one full pull."""
        with self._chain_lock:
            if self._chain_lab is None:
                return {}
            return {
                "version": int(self._chain_version),
                "n": int(self._chain_n),
                "lab": np.array(self._chain_lab, copy=True),
                "ring": [
                    {"base": int(s["base"]), "to": int(s["to"]),
                     "u": np.array(s["u"], copy=True),
                     "r": np.array(s["r"], copy=True)}
                    for s in self._ring
                ],
            }

    def restore_chain(self, state: dict, epoch: int,
                      boot_version: int) -> bool:
        """Adopt a persisted chain after a restart.

        Accepted ONLY when the persisted chain head equals
        ``boot_version`` — the version the restarted store republished
        at boot (snapshot-mirror adoption with the version override).
        Any mismatch means the ring and the served state diverged
        (snapshot newer than the ring, or vice versa) and a delta
        built on it could claim coverage it does not have; the engine
        then keeps its empty chain and the next pull degrades to the
        existing full fallback, counted
        (``serving.pullring_rejected{reason}``)."""
        reason = None
        if not state or "lab" not in state:
            reason = "empty"
        elif int(state.get("version", -2)) != int(boot_version):
            reason = "version"
        if reason is not None:
            get_registry().counter(
                "serving.pullring_rejected", reason=reason).inc()
            return False
        with self._chain_lock:
            self._chain_epoch = int(epoch)
            self._chain_version = int(state["version"])
            self._chain_lab = np.asarray(state["lab"]).copy()
            self._chain_n = int(state["n"])
            self._ring.clear()
            for s in state.get("ring", []):
                self._ring.append(
                    {"base": int(s["base"]), "to": int(s["to"]),
                     "u": np.asarray(s["u"], np.int64),
                     "r": np.asarray(s["r"], np.int64)}
                )
            # the boot snapshot IS the restored chain head: mark it
            # current so the first pull serves from the ring instead
            # of appending a degenerate (V -> V) segment
            self._pull_key = (int(epoch), int(boot_version))
            self._pull_docs = {}
        return True

    def bipartite(self, snap: PublishedSnapshot) -> dict:
        """The :class:`BipartiteQuery` answer value (see its docstring).

        Seen base vertices come from the payload's touch evidence —
        either the append-only log view (``tids``/``tcount``, the
        forest-carry publish shape: the first ``tcount`` entries of an
        append-only log never change, so the published ref is a valid
        snapshot) or a ``touched`` bool table (the dense carry /
        restored-checkpoint shape). Cached per snapshot version: the
        O(vcap) canonicalize + conflict scan runs once however many
        clients ask."""
        bkey = (snap.epoch, snap.version)
        ver, cached = self._bp_cache
        if ver == bkey and cached is not None:
            return cached
        from ..summaries.forest import resolve_flat_host

        cover = np.asarray(self._table(snap, "cover"))
        vdict = snap.payload["vdict"]
        vcap = cover.shape[0] // 2
        lab = resolve_flat_host(cover)
        if "tids" in snap.payload:
            tids = np.asarray(
                snap.payload["tids"][: snap.payload["tcount"]], np.int64
            )
            tids = tids[tids < vcap]
        else:
            touched = np.asarray(snap.payload["touched"])
            tids = np.nonzero(touched[:vcap])[0]
        conflicted = tids[lab[tids] == lab[tids + vcap]]
        if len(conflicted):
            witness = int(
                np.min(np.asarray(vdict.decode(conflicted), np.int64))
            )
            doc = {"bipartite": False, "witness": witness}
        else:
            doc = {"bipartite": True, "witness": None}
        self._bp_cache = (bkey, doc)
        return doc

    def degree(self, snap: PublishedSnapshot, vs: np.ndarray) -> np.ndarray:
        return self._table_gather(snap, "deg", vs, fill=0)

    def rank(self, snap: PublishedSnapshot, vs: np.ndarray) -> np.ndarray:
        return self._table_gather(snap, "ranks", vs, fill=0.0)

    def _table_gather(
        self, snap: PublishedSnapshot, key: str, vs: np.ndarray, fill
    ) -> np.ndarray:
        table = self._table(snap, key)
        vdict = snap.payload["vdict"]
        cv = _lookup_batch(vdict, vs)
        vcap = int(table.shape[0])
        valid = (cv >= 0) & (cv < vcap)
        safe = np.where(valid, cv, 0)
        if self.prefer_host:
            got = table[safe]
        else:
            got = np.asarray(
                _gather(jnp.asarray(table), jnp.asarray(_pad_ids(safe)))
            )[: len(cv)]
        return np.where(valid, got, fill)

    # -- heterogeneous batch ------------------------------------------- #
    def answer_batch(
        self,
        snap: PublishedSnapshot,
        queries: Sequence[Query],
        head_window: Optional[int] = None,
    ) -> List[Answer]:
        """Answer a mixed batch: group by query class, one vectorized
        kernel per class present, answers re-ordered to match the input.
        ``head_window`` (default: this snapshot's window) stamps each
        answer's staleness gauge."""
        head = snap.window if head_window is None else head_window
        staleness = max(0, head - snap.window)
        out: List[Optional[Answer]] = [None] * len(queries)
        groups: Dict[type, List[int]] = {}
        for i, q in enumerate(queries):
            groups.setdefault(type(q), []).append(i)
        for qcls, idxs in groups.items():
            key = self.PAYLOAD_KEYS.get(qcls)
            if key is None or key not in snap.payload:
                raise TypeError(
                    f"snapshot payload (keys {sorted(snap.payload)}) does "
                    f"not serve {qcls.__name__}"
                )
            if qcls in (SummaryPullQuery, BipartiteQuery):
                # cached docs answer the whole group (dict-valued, so
                # they bypass the ndarray tail below); pulls key the
                # cache per since_version, so mixed baselines in one
                # batch still cost one canonicalization
                for i in idxs:
                    doc = (
                        self.summary_pull(
                            snap, queries[i].since_version)
                        if qcls is SummaryPullQuery
                        else self.bipartite(snap)
                    )
                    out[i] = Answer(
                        value=doc, window=snap.window,
                        watermark=snap.watermark, staleness=staleness,
                        version=snap.version, event_ts=snap.event_ts,
                        boot=getattr(snap, "boot", ""),
                    )
                continue
            if qcls is ConnectedQuery:
                us = np.asarray([queries[i].u for i in idxs], np.int64)
                vs = np.asarray([queries[i].v for i in idxs], np.int64)
                vals = self.connected(snap, us, vs)
            else:
                vs = np.asarray([queries[i].v for i in idxs], np.int64)
                if qcls is DegreeQuery:
                    vals = self.degree(snap, vs)
                elif qcls is RankQuery:
                    vals = self.rank(snap, vs)
                else:
                    vals = self.component_size(snap, vs)
            for i, v in zip(idxs, vals.tolist()):
                out[i] = Answer(
                    value=v, window=snap.window,
                    watermark=snap.watermark, staleness=staleness,
                    version=snap.version, event_ts=snap.event_ts,
                    boot=getattr(snap, "boot", ""),
                )
        return out  # type: ignore[return-value]


# --------------------------------------------------------------------- #
# Pull-ring persistence (ISSUE 19 satellite): checkpoint the delta
# chain alongside the snapshot mirror so a RESTARTED shard bridges
# routers with a delta pull instead of always paying one full pull.
# --------------------------------------------------------------------- #

PULL_RING_TAG = "pullring.bin"


class PullRingMirror:
    """Snapshot-store listener that keeps an engine's delta chain
    advancing with every publish and persists it next to the snapshot
    mirror (CRC-framed, overwrite — only the newest chain matters).

    ``every`` throttles the O(n) persist the same way
    ``SnapshotMirror(every=...)`` throttles snapshot writes; the chain
    itself advances on EVERY publish (ring segments are per-version,
    skipping one would tear the chain).  A failed persist is counted
    (``serving.swallowed{site=pullring_write}``) and retried on the
    next publish — the in-memory chain is still intact, only restart
    bridging is at stake."""

    def __init__(self, engine: QueryEngine, dirpath: str, *,
                 every: int = 1) -> None:
        self.engine = engine
        self.dirpath = dirpath
        self.every = max(1, int(every))
        self._published = 0

    def __call__(self, snap: PublishedSnapshot) -> None:
        from ..fabric import as_transport

        self.engine.chain_sync(snap)
        self._published += 1
        if self._published % self.every:
            return
        try:
            blob = pickle.dumps(self.engine.chain_state(), protocol=4)
            as_transport(self.dirpath).put_framed(
                PULL_RING_TAG, blob, overwrite=True)
        except Exception:
            get_registry().counter(
                "serving.swallowed", site="pullring_write").inc()


def load_pull_ring(dirpath: str) -> dict:
    """The persisted delta chain from ``dirpath`` (empty dict when
    absent, torn, or undecodable — torn/undecodable are recorded, and
    :meth:`QueryEngine.restore_chain` turns an empty dict into the
    counted full-fallback degrade)."""
    from ..fabric import as_transport
    from ..resilience.integrity import record_rejection

    tr = as_transport(dirpath)
    data = tr.get_framed(PULL_RING_TAG)
    if data is None:
        return {}
    try:
        state = pickle.loads(data)
    except Exception as e:
        record_rejection(tr.describe(PULL_RING_TAG),
                         f"undecodable pull ring: {e!r}")
        return {}
    return state if isinstance(state, dict) else {}
