"""Live query serving over streaming summaries (the read path).

The reference treats summaries as write-only: folded per window, emitted
as a stream, never *asked* anything while the stream runs. The ROADMAP
north star — heavy traffic from millions of users — needs the opposite
contract too: point queries (``connected(u, v)``, ``degree(v)``,
``rank(v)``) answered from the most recent published summary with bounded
staleness, without stalling ingestion. This package is that serving
stack:

- :mod:`snapshot_store` — a wait-free publish/read split: the ingest
  loop publishes an immutable :class:`PublishedSnapshot` (summary payload
  + window index + watermark) after each window; readers grab the latest
  by one atomic reference read, never a lock shared with the writer.
- :mod:`query` — typed point queries plus a :class:`QueryEngine` that
  answers a whole concurrent batch with ONE vectorized jitted lookup per
  query class (a batch root-chase gather for CC, a table gather for
  degrees/ranks) instead of per-query host loops.
- :mod:`server` — :class:`StreamServer`: runs any emission iterator on a
  background thread (reusing ``core/pipeline.py``'s producer discipline),
  publishes snapshots, exposes ``submit(query) -> Future`` and a
  synchronous ``ask()``, rejects with :class:`Overloaded` past the
  admission limit, and drains cleanly on ``close()``.
- :mod:`failover` — :class:`FailoverServer`: a standby ``StreamServer``
  attached to the shared snapshot store, promoted when the primary's
  query worker dies — expired in-flight queries fail
  ``DeadlineExceeded``, the rest are re-answered from the standby's
  newest snapshot, and admission/shedding/retry policies carry over.
- :mod:`stats` — per-query-class latency histograms + staleness gauges,
  exported as plain dict snapshots (metrics stay ordinary output
  streams, the reference's design stance).
- :mod:`router` — :class:`ShardRouter`: the sharded-serving tier —
  vertex-ownership partition over N shard servers, scatter-gather
  fan-out with per-class merges (cross-shard CC union via summary
  pulls + the group-fold merge), a version-stamped hot-key answer
  cache, and per-shard failover through each shard's address list.
- :mod:`reshard` — elastic resharding (ISSUE 19): one-winner split
  plans elected over the fabric, child-address publication, the
  dense actionable-prefix rule that defines the live ownership
  epoch, and the :class:`~.reshard.ReshardWatcher` replicas and
  routers adopt it through.
- :mod:`txn` — snapshot-pinned read transactions (ISSUE 20): a
  :class:`~.txn.TxnContext` pins a per-shard ``{shard: (version,
  boot)}`` vector from ordinary reply stamps, every later read is
  answered AT the pinned snapshot or raises the typed, counted
  :class:`~.txn.TxnSnapshotExpired` — never a silently fresher
  answer — and non-transactional sessions get monotonic reads via
  the client's per-shard version floor.

Workloads opt in via a small ``servable()`` adapter
(``library/connected_components.py``, ``library/degrees.py``,
``library/pagerank.py``) mapping their carry to a snapshot payload;
``aggregate/checkpoint.py:restore_server`` boots a server from a
checkpoint so it serves the restored summary while catching up.
"""

from .query import (
    Answer,
    BipartiteQuery,
    ComponentSizeQuery,
    ConnectedQuery,
    DegreeQuery,
    Query,
    QueryEngine,
    RankQuery,
    SummaryPullQuery,
)
from ..resilience.errors import DeadlineExceeded
from ..resilience.retry import RetryPolicy
from .failover import FailoverServer
from .server import Overloaded, Servable, Shed, StreamServer
from .snapshot_store import (
    PublishedSnapshot,
    SnapshotMirror,
    SnapshotStore,
    follow_snapshots,
)
from .stats import ServingStats
from .txn import TxnContext, TxnSnapshotExpired

#: PEP 562 lazy exports: the RPC modules are runnable CLIs
#: (``python -m gelly_streaming_tpu.serving.rpc --smoke``), and an
#: eager package-level import would double-import them under runpy
_LAZY = {
    "HeartbeatLease": ".rpc",
    "ReplicaServer": ".rpc",
    "RpcServer": ".rpc",
    "RpcClient": ".client",
    "RpcError": ".client",
    "ShardRouter": ".router",
    "ReshardWatcher": ".reshard",
}


def __getattr__(name):
    rel = _LAZY.get(name)
    if rel is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}"
        )
    from importlib import import_module

    return getattr(import_module(rel, __name__), name)


__all__ = [
    "Answer",
    "BipartiteQuery",
    "ComponentSizeQuery",
    "ConnectedQuery",
    "DeadlineExceeded",
    "DegreeQuery",
    "FailoverServer",
    "HeartbeatLease",
    "Overloaded",
    "PublishedSnapshot",
    "Query",
    "QueryEngine",
    "RankQuery",
    "ReplicaServer",
    "RetryPolicy",
    "RpcClient",
    "ReshardWatcher",
    "RpcError",
    "RpcServer",
    "Servable",
    "ServingStats",
    "ShardRouter",
    "Shed",
    "SnapshotMirror",
    "SnapshotStore",
    "StreamServer",
    "SummaryPullQuery",
    "TxnContext",
    "TxnSnapshotExpired",
    "follow_snapshots",
]
