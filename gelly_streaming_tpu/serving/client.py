"""RPC query client: reconnect-and-resubmit over the serving wire.

The client half of :mod:`~gelly_streaming_tpu.serving.rpc`. One
:class:`RpcClient` owns one framed connection at a time to a list of
replica addresses and gives callers the SAME future surface as a local
``StreamServer.submit`` — the wire is an implementation detail:

- ``submit_batch`` registers the batch under an idempotent client id
  and sends one REQ frame; answers settle the futures whenever the
  server's RESP arrives (async, out of submission order).
- ``overloaded`` wire rejections honor the client's
  :class:`~gelly_streaming_tpu.resilience.RetryPolicy` — bounded,
  jittered, deadline-clamped re-asks (``rpc.client_retries``); ``shed``
  is TERMINAL and never retried (the server sheds that class to lose
  exactly this traffic); ``not_primary`` retries with its own backoff
  while a standby finishes promoting.
- On disconnect the client reconnects (cycling the address list under
  bounded exponential backoff) and RESUBMITS every pending batch under
  its original id; the server's dedupe cache absorbs double delivery,
  so a serving-process kill is visible only as a latency blip. Batches
  whose own ``deadline_s`` lapses mid-outage fail
  :class:`~gelly_streaming_tpu.resilience.errors.DeadlineExceeded`
  cleanly (``rpc.client_deadline_expired`` +
  ``rpc.client_sweeper_expired``) — every submitted query is ALWAYS
  answered or cleanly expired, never lost.
- With tracing on (``obs.enable()``) each batch mints ONE
  :class:`~gelly_streaming_tpu.obs.trace.TraceContext` that rides every
  send (first, retry, reconnect resubmit) in the frame body, so server
  spans on every replica that touched the batch join one trace;
  ``rpc.client.batch`` / ``rpc.client.retry`` / ``rpc.client.resubmit``
  spans carry the client half of the story, and the per-batch
  ``rpc.client_wire_seconds`` histogram (always on) gains exemplar
  trace ids linking its tail to concrete traces.
  :meth:`RpcClient.stats_snapshot` is the client-side stats parity
  surface.
"""

from __future__ import annotations

import itertools
import json
import os
import socket as _socket
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import List, Optional, Sequence, Tuple, Union

from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience.errors import DeadlineExceeded
from ..resilience.retry import RetryPolicy, exp_backoff, jittered
from .query import Answer, Query
from .rpc import (
    BAD_REQUEST,
    DEFAULT_MAX_FRAME,
    Disconnect,
    MalformedFrame,
    NOT_PRIMARY,
    OK,
    OVERLOADED,
    SHED,
    T_REQ,
    T_RESP,
    Wire,
    encode_queries,
    pack_frame,
)
from .server import Overloaded, Shed
from .txn import TxnContext, TxnSnapshotExpired


class RpcError(RuntimeError):
    """Terminal wire-level failure (server error / bad request / spent
    routing budget). Never retried by the client."""


def _sent_pin(sent, shard: int):
    """The ``(version, boot)`` pin the batch's LAST send carried for
    ``shard`` (from its recorded wire txn field), or None when that
    shard was unpinned at send time — the distinction that tells "the
    peer ignored my pin" (honest typed failure) from "this answer is
    doing the pinning" (observe it)."""
    if not sent:
        return None
    p = sent.get("pin")
    if p is not None:
        return int(p[0]), str(p[1]) if len(p) > 1 else ""
    vec = sent.get("vec")
    if not vec:
        return None
    q = vec.get(str(int(shard)))
    if q is None:
        return None
    return int(q[0]), str(q[1]) if len(q) > 1 else ""


class _Batch:
    """One pending wire batch (client side). ``ctx`` is the batch's
    :class:`~gelly_streaming_tpu.obs.trace.TraceContext` (None when
    tracing was off at submit): every send — first, retry, reconnect
    resubmit — rides the SAME context, so server-side spans on every
    replica that ever touched the batch join one trace. ``parent_sid``
    is the span the batch ROOT parents to when the caller handed in an
    upstream context (the router's fan-out span) — None for a true
    root."""

    __slots__ = ("id", "enc", "futures", "deadline_abs",
                 "attempts", "routes", "ctx", "parent_sid",
                 "t0", "t_send", "t_resp",
                 "txn_ctx", "txn_doc", "txn_sent", "reasks")

    def __init__(self, qid: str, enc: list, futures: list,
                 deadline_abs: Optional[float]):
        self.id = qid
        self.enc = enc
        self.futures = futures
        self.deadline_abs = deadline_abs
        self.attempts = 0   # overloaded re-asks
        self.routes = 0     # not_primary re-asks
        self.ctx = None
        self.parent_sid = None
        self.t0 = 0.0       # perf_counter at submit (e2e measurement)
        self.t_send = 0.0   # perf_counter at the LAST send attempt
        self.t_resp = 0.0   # perf_counter when the RESP frame arrived
        self.txn_ctx = None   # TxnContext riding this batch (ISSUE 20)
        self.txn_doc = None   # raw wire txn dict (router sub-requests)
        self.txn_sent = None  # the txn field the LAST send carried
        self.reasks = 0       # floor-regression fresh-id re-asks

    def remaining_s(self) -> Optional[float]:
        if self.deadline_abs is None:
            return None
        return self.deadline_abs - time.monotonic()


class RpcClient:
    """Framed-socket client for one serving replica set.

    ``addresses`` is one ``"host:port"`` (or ``(host, port)``) or a
    list of them — give it BOTH replicas of a failover pair and the
    reconnect loop finds whichever currently serves. ``retry_policy``
    governs ``overloaded`` re-asks (default: the stock
    :class:`RetryPolicy`); pass None explicitly via
    ``retry_policy=RetryPolicy(attempts=0)`` semantics if rejections
    should surface immediately.
    """

    #: deadline sweep cadence (client-side expiry during outages)
    SWEEP_S = 0.02
    #: not_primary re-ask backoff shape (a standby mid-promotion)
    ROUTE_BASE_S = 0.02
    ROUTE_MAX_S = 0.25
    #: monotonic-floor regression re-asks (fresh id each — the old id
    #: would replay the server's CACHED stale answer) before the typed
    #: failure; backoff shape for the staler survivor to catch up
    FLOOR_REASKS = 6
    FLOOR_BASE_S = 0.02
    FLOOR_MAX_S = 0.25

    def __init__(
        self,
        addresses: Union[str, Tuple[str, int], Sequence],
        *,
        retry_policy: Optional[RetryPolicy] = None,
        reconnect_base_s: float = 0.02,
        reconnect_max_s: float = 1.0,
        connect_timeout_s: float = 5.0,
        route_attempts: int = 512,
        max_frame: int = DEFAULT_MAX_FRAME,
        seed: int = 0,
        start_index: int = 0,
    ):
        if isinstance(addresses, str) or (
            isinstance(addresses, tuple)
            and len(addresses) == 2
            and isinstance(addresses[1], int)
        ):
            addresses = [addresses]
        self._addrs = [self._parse(a) for a in addresses]
        if not self._addrs:
            raise ValueError("at least one replica address is required")
        self.retry_policy = retry_policy if retry_policy is not None \
            else RetryPolicy()
        self.reconnect_base_s = float(reconnect_base_s)
        self.reconnect_max_s = float(reconnect_max_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.route_attempts = int(route_attempts)
        self.max_frame = int(max_frame)
        self.seed = int(seed)
        self._lock = threading.Lock()
        self._pending: dict = {}
        self._wire: Optional[Wire] = None
        # first address tried; an EXPLICIT spread knob, deliberately
        # not seed-derived — against a primary/standby pair an implicit
        # spread would park half the clients on a standby that never
        # promotes, spinning on not_primary. A router FLEET (every
        # member serves) passes start_index=i to balance connections.
        self._addr_i = int(start_index) % len(self._addrs)
        # highest ownership epoch any reply frame carried (the router
        # fleet reads this off its shard clients to learn of a live
        # split from ordinary traffic — serving/reshard.py)
        self.epoch_observed = 0
        # monotonic-read floor: highest (version, boot) answered per
        # shard. Every later non-pinned answer from the same lineage
        # must be >= it — a resubmit that lands on a staler survivor is
        # DETECTED here (counted rpc.client_regressions) and re-asked
        # under a fresh id, never delivered as silent time travel.
        # Mutated only on the io thread (_settle_ok); boot "" answers
        # (router-merged, no single lineage) are excluded.
        self._vfloor: dict = {}
        self._closing = threading.Event()
        self._counter = itertools.count()
        self._id_prefix = f"{os.getpid():x}.{os.urandom(3).hex()}"
        self._io_thread = threading.Thread(
            target=self._io_loop, name="rpc-client-io", daemon=True
        )
        self._sweep_thread = threading.Thread(
            target=self._sweep, name="rpc-client-sweep", daemon=True
        )
        self._io_thread.start()
        self._sweep_thread.start()

    @staticmethod
    def _parse(addr) -> Tuple[str, int]:
        if isinstance(addr, tuple):
            return str(addr[0]), int(addr[1])
        host, _, port = str(addr).rpartition(":")
        return host or "127.0.0.1", int(port)

    def __enter__(self) -> "RpcClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------------ #
    # Submission surface
    # ------------------------------------------------------------------ #
    def submit_batch(
        self,
        queries: Sequence[Query],
        *,
        deadline_s: Optional[float] = None,
        ctx=None,
        txn=None,
    ) -> List["Future[Answer]"]:
        """Send one query batch; one future per query. ``deadline_s``
        bounds each query's TOTAL budget — network, retries, reconnects,
        and the server-side wait all spend it; expiry fails the future
        with :class:`DeadlineExceeded` (client- or server-side,
        whichever notices first).

        ``ctx`` (optional, tracing only) is an UPSTREAM
        :class:`~gelly_streaming_tpu.obs.trace.TraceContext` to join:
        the batch stays on that trace id and its root span parents to
        ``ctx.parent_sid`` — the hop a fan-out router makes so client,
        router, and shard spans form one causal tree.

        ``txn`` (ISSUE 20) is a
        :class:`~gelly_streaming_tpu.serving.txn.TxnContext` (or a
        pre-encoded wire txn dict, the router's per-shard form): the
        batch rides the transaction's pinned vector on every send and
        observes OK answers back into the context; a pinned read is
        answered at the pinned snapshot or fails
        :class:`TxnSnapshotExpired` — never silently fresher."""
        if self._closing.is_set():
            raise RuntimeError("rpc client is closed")
        enc = encode_queries(queries)
        qid = f"{self._id_prefix}-{next(self._counter)}"
        futures: List["Future[Answer]"] = [Future() for _ in queries]
        tctx = tdoc = None
        if txn is not None:
            if isinstance(txn, TxnContext):
                tctx = txn
                # GL008: the transaction's ONE deadline budget bounds
                # every read issued under it — a batch never grants
                # itself more clock than the transaction has left
                rem = tctx.remaining_s()
                if rem is not None:
                    deadline_s = rem if deadline_s is None \
                        else min(float(deadline_s), rem)
            else:
                tdoc = dict(txn)
        deadline_abs = (
            None if deadline_s is None
            else time.monotonic() + float(deadline_s)
        )
        batch = _Batch(qid, enc, futures, deadline_abs)
        batch.txn_ctx = tctx
        batch.txn_doc = tdoc
        batch.t0 = time.perf_counter()
        if _trace.on():
            # mint ONE context per batch; its parent sid is reserved
            # now so server-side spans can parent to the client's root
            # span before that root is emitted (at settle). With an
            # upstream ctx the trace id is INHERITED, not minted.
            if ctx is not None:
                batch.ctx = _trace.TraceContext(
                    trace_id=ctx.trace_id,
                    parent_sid=_trace.next_sid(),
                )
                batch.parent_sid = ctx.parent_sid
            else:
                batch.ctx = _trace.TraceContext(
                    parent_sid=_trace.next_sid()
                )
        with self._lock:
            self._pending[qid] = batch
        wire = self._wire
        if wire is not None:
            try:
                self._send_batch(wire, batch)
            except OSError:
                # the reconnect loop owns recovery; the batch is
                # registered and will be resubmitted on the next
                # connection — count the undelivered first send
                get_registry().counter(
                    "rpc.swallowed", site="client_submit_send"
                ).inc()
        return futures

    def submit(self, query: Query, *,
               deadline_s: Optional[float] = None,
               ctx=None, txn=None) -> "Future[Answer]":
        return self.submit_batch(
            [query], deadline_s=deadline_s, ctx=ctx, txn=txn
        )[0]

    def ask_batch(
        self,
        queries: Sequence[Query],
        *,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
        txn=None,
    ) -> List[Answer]:
        futures = self.submit_batch(
            queries, deadline_s=deadline_s, txn=txn
        )
        # `timeout` bounds the WHOLE batch wait (GL008): each result()
        # spends what remains of one budget — N sequential waits of
        # the full timeout would wait N× what the caller asked for
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        out = []
        for f in futures:
            out.append(f.result(
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            ))
        return out

    def ask(self, query: Query, timeout: Optional[float] = None,
            deadline_s: Optional[float] = None, txn=None) -> Answer:
        return self.submit(
            query, deadline_s=deadline_s, txn=txn
        ).result(timeout)

    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def stats_snapshot(self) -> dict:
        """Client-side serving stats as a plain dict — the parity
        surface for the server's ``ServingStats.snapshot()`` (ISSUE 9
        satellite): retries, reroutes, reconnects, resubmits, sweeper
        expiries, and the per-batch wire latency histogram, all read
        from the shared process registry (the same instruments the
        cluster event stream ships), so a client process's view of an
        outage is inspectable without scraping the server::

            {"pending": 0, "retries": 2, "reconnects": 1, ...,
             "wire_ms": {"count": 120, "p50": 1.9, "p99": 410.0}}
        """
        reg = get_registry()

        def _count(name: str) -> int:
            total = 0.0
            for _labels, inst in reg.find(name):
                total += inst.value
            return int(total)

        hist = reg.histogram("rpc.client_wire_seconds")
        doc = {
            "pending": self.pending(),
            "epoch_observed": self.epoch_observed,
            "connects": _count("rpc.client_connects"),
            "disconnects": _count("rpc.client_disconnects"),
            "reconnects": _count("rpc.client_reconnects"),
            "resubmitted": _count("rpc.client_resubmitted"),
            "retries": _count("rpc.client_retries"),
            "reroutes": _count("rpc.client_reroutes"),
            "regressions": _count("rpc.client_regressions"),
            "sweeper_expired": _count("rpc.client_sweeper_expired"),
            "deadline_expired": _count("rpc.client_deadline_expired"),
            "wire_ms": {
                "count": hist.count,
                "p50": hist.percentile(50) * 1e3,
                "p99": hist.percentile(99) * 1e3,
                "max": hist.max * 1e3,
            },
        }
        exemplars = hist.exemplars()
        if exemplars:
            doc["wire_ms"]["exemplars"] = [
                {"ms": v * 1e3, "trace": t} for v, t in exemplars
            ]
        return doc

    # ------------------------------------------------------------------ #
    # Wire plumbing
    # ------------------------------------------------------------------ #
    def _send_batch(self, wire: Wire, batch: _Batch) -> None:
        doc = {"id": batch.id, "q": batch.enc}
        remaining = batch.remaining_s()
        if remaining is not None:
            # ship the REMAINING budget, not the original one: a
            # resubmit after an outage must not grant the server a
            # fresh full deadline the client no longer has
            doc["deadline_s"] = max(0.001, remaining)
        if batch.txn_ctx is not None:
            # the vector is re-read at EVERY send (first, retry,
            # reconnect resubmit): pins acquired since the last send
            # ride too, and txn_sent records exactly what THIS send
            # carried — the settle path compares the answer stamp
            # against it to detect a peer that ignored the pin
            batch.txn_sent = batch.txn_ctx.wire_doc()
            doc["txn"] = batch.txn_sent
        elif batch.txn_doc is not None:
            batch.txn_sent = batch.txn_doc
            doc["txn"] = batch.txn_doc
        if _trace.on() and batch.ctx is not None:
            doc["tc"] = batch.ctx.to_wire()
        batch.t_send = time.perf_counter()
        wire.send(pack_frame(T_REQ, json.dumps(doc).encode("utf-8")))

    def _io_loop(self) -> None:
        reg = get_registry()
        while not self._closing.is_set():
            wire = self._connect()
            if wire is None:
                return
            self._wire = wire
            reg.counter("rpc.client_connects").inc()
            self._resubmit_all(wire)
            self._read_loop(wire)
            self._wire = None
            wire.close()
            reg.counter("rpc.client_disconnects").inc()

    def _connect(self) -> Optional[Wire]:
        """Cycle the address list under bounded exponential backoff
        until a connection lands (or the client closes)."""
        attempt = 0
        while not self._closing.is_set():
            for off in range(len(self._addrs)):
                i = (self._addr_i + off) % len(self._addrs)
                host, port = self._addrs[i]
                try:
                    sock = _socket.create_connection(
                        (host, port), timeout=self.connect_timeout_s
                    )
                except OSError:
                    continue
                try:
                    sock.settimeout(None)
                    sock.setsockopt(
                        _socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1
                    )
                except OSError:
                    # the server reset the fresh connection before the
                    # options landed: release THIS socket and try the
                    # next address — an uncaught raise here would leak
                    # the fd and kill the io thread (GL010)
                    get_registry().counter(
                        "rpc.swallowed", site="connect_config"
                    ).inc()
                    sock.close()
                    continue
                self._addr_i = i
                return Wire(sock)
            delay = jittered(
                exp_backoff(
                    attempt, self.reconnect_base_s, self.reconnect_max_s
                ),
                0.5, self.seed, attempt,
            )
            get_registry().counter("rpc.client_reconnects").inc()
            self._closing.wait(delay)
            attempt += 1
        return None

    def _resubmit_all(self, wire: Wire) -> None:
        with self._lock:
            batches = list(self._pending.values())
        if not batches:
            return
        get_registry().counter(
            "rpc.client_resubmitted"
        ).inc(len(batches))
        for b in batches:
            # t_send == 0 means the batch was registered but never yet
            # on any wire (submit raced the first connect): that is a
            # first send, not an outage — no resubmit span for it
            if _trace.on() and b.ctx is not None and b.t_send > 0.0:
                # the batch's client-visible outage: last send on the
                # dead connection -> resubmit on the new one. This span
                # is the attribution of a failover's latency blip — it
                # is what joins the dead replica's partial spans to the
                # promoted replica's full ones in the merged timeline
                _trace.record_span(
                    "rpc.client.resubmit",
                    time.perf_counter() - b.t_send,
                    trace_id=b.ctx.trace_id,
                    parent=b.ctx.parent_sid,
                    attrs={"id": b.id},
                )
            try:
                self._send_batch(wire, b)
            except OSError:
                # this connection is already dead; the loop will build
                # a new one and resubmit again — visible, not fatal
                get_registry().counter(
                    "rpc.swallowed", site="client_resubmit_send"
                ).inc()
                return

    def _read_loop(self, wire: Wire) -> None:
        reg = get_registry()
        while not self._closing.is_set():
            try:
                ftype, payload = wire.read(max_frame=self.max_frame)
            except Disconnect:
                return
            except MalformedFrame as e:
                reg.counter("rpc.malformed", kind=e.kind).inc()
                return
            except ConnectionResetError:
                # injected rpc.frame disconnect or a real peer reset
                return
            except OSError:
                reg.counter(
                    "rpc.swallowed", site="client_read"
                ).inc()
                return
            if ftype != T_RESP:
                reg.counter("rpc.malformed", kind="type").inc()
                return
            t_frame = time.perf_counter()  # frame-arrival stamp
            try:
                doc = json.loads(payload.decode("utf-8"))
            except (ValueError, UnicodeDecodeError):
                reg.counter("rpc.malformed", kind="json").inc()
                continue
            self._handle_resp(doc, t_frame)

    # ------------------------------------------------------------------ #
    # Response handling
    # ------------------------------------------------------------------ #
    def _handle_resp(self, doc: dict,
                     t_frame: Optional[float] = None) -> None:
        reg = get_registry()
        qid = doc.get("id")
        if qid is None:
            # a server-side notification about an unidentifiable frame
            # (our own malformed send, in practice): nothing to settle
            reg.counter("rpc.client_anon_errors").inc()
            return
        with self._lock:
            batch = self._pending.get(qid)
        if batch is None:
            return  # late duplicate of an already-settled batch
        if t_frame is not None:
            batch.t_resp = t_frame
        ep = doc.get("epoch")
        if ep is not None and int(ep) > self.epoch_observed:
            # monotone adoption: reply frames from pre-split servers
            # keep arriving after the bump and must not flap it back
            self.epoch_observed = int(ep)
        status = doc.get("status")
        if status == OK:
            self._settle_ok(batch, doc.get("answers"))
        elif status == OVERLOADED:
            attempt = batch.attempts
            batch.attempts = attempt + 1
            remaining = batch.remaining_s()
            if remaining is not None and remaining <= 0:
                # the DEADLINE spent the budget, not the retry policy:
                # defer to the sweeper so the batch fails
                # DeadlineExceeded, as the module contract promises
                return
            delay = self.retry_policy.delay_before(attempt, remaining)
            if delay is None:
                self._fail(batch, Overloaded(
                    doc.get("error") or "server overloaded "
                    "(client retry budget spent)"
                ))
            else:
                reg.counter("rpc.client_retries").inc()
                self._schedule_resend(batch, delay)
        elif status == NOT_PRIMARY:
            routes = batch.routes
            batch.routes = routes + 1
            if routes >= self.route_attempts:
                self._fail(batch, RpcError(
                    "no replica would serve (routing budget spent)"
                ))
                return
            remaining = batch.remaining_s()
            if remaining is not None and remaining <= 0:
                return  # the sweeper expires it
            delay = jittered(
                exp_backoff(routes, self.ROUTE_BASE_S, self.ROUTE_MAX_S),
                0.5, self.seed, routes,
            )
            if remaining is not None:
                delay = min(delay, max(0.001, remaining))
            reg.counter("rpc.client_reroutes").inc()
            self._schedule_resend(batch, delay)
        elif status == SHED:
            self._fail(batch, Shed(
                doc.get("error") or "query class shed under pressure"
            ))
        elif status == BAD_REQUEST:
            self._fail(batch, RpcError(
                doc.get("error") or "bad request"
            ))
        else:
            self._fail(batch, RpcError(
                doc.get("error") or f"server error (status {status!r})"
            ))

    def _schedule_resend(self, batch: _Batch, delay: float) -> None:
        t = threading.Timer(delay, self._resend, args=(batch,))
        t.daemon = True
        t.start()

    def _resend(self, batch: _Batch) -> None:
        if self._closing.is_set():
            return
        with self._lock:
            if batch.id not in self._pending:
                return
        wire = self._wire
        if wire is None:
            return  # the reconnect path resubmits every pending batch
        if _trace.on() and batch.ctx is not None:
            # an overloaded/not_primary re-ask: round trip + backoff
            # since the last send, on the SAME trace — retries are part
            # of the query's causal story, not fresh queries
            _trace.record_span(
                "rpc.client.retry",
                time.perf_counter() - batch.t_send,
                trace_id=batch.ctx.trace_id,
                parent=batch.ctx.parent_sid,
                attrs={"attempts": batch.attempts,
                       "routes": batch.routes},
            )
        try:
            self._send_batch(wire, batch)
        except OSError:
            get_registry().counter(
                "rpc.swallowed", site="client_resend"
            ).inc()

    def _settle_ok(self, batch: _Batch, answers) -> None:
        with self._lock:
            self._pending.pop(batch.id, None)
        e2e_s = time.perf_counter() - batch.t0
        if not isinstance(answers, list) or \
                len(answers) != len(batch.futures):
            # a malformed OK payload is a FAILED batch: it must not
            # land in the wire-latency histogram (or become its p99
            # exemplar) or emit a completed batch-root span
            err = RpcError(
                f"answer count mismatch ({answers!r:.120})"
            )
            for f in batch.futures:
                self._set_exc(f, err)
            return
        # monotonic-floor regression scan BEFORE delivery: a resubmit
        # that landed on a staler survivor must not answer BEHIND an
        # already-delivered answer — re-ask under a FRESH id (the old
        # id would replay the server's cached stale RESP) while the
        # survivor catches up, typed failure when the budget is spent
        floor_fail = self._regressed(batch, answers)
        if floor_fail is None:
            return  # re-asked; the batch is pending again
        # per-batch wire latency (submit -> answered), always recorded:
        # client-side latency parity with the server's ServingStats.
        # The exemplar (tracing only) links this histogram's tail to a
        # concrete trace id.
        traced = _trace.on() and batch.ctx is not None
        get_registry().histogram("rpc.client_wire_seconds").observe(
            e2e_s, exemplar=batch.ctx.trace_id if traced else None
        )
        if traced:
            # the batch's ROOT span, emitted under the sid reserved at
            # submit — every server/retry span already parents to it.
            # send_s/recv_s are the CLIENT-LOCAL stages of the
            # attribution table: submit -> last send on the wire, and
            # response-frame arrival -> this settle (encode, io-thread
            # wakeup, response parse — the milliseconds a server-only
            # view can never account for)
            now = time.perf_counter()
            _trace.record_span(
                "rpc.client.batch", e2e_s,
                trace_id=batch.ctx.trace_id,
                sid=batch.ctx.parent_sid,
                parent=batch.parent_sid,
                attrs={"n": len(batch.futures),
                       "attempts": batch.attempts,
                       "routes": batch.routes,
                       "send_s": round(
                           max(0.0, batch.t_send - batch.t0), 6),
                       "recv_s": round(
                           max(0.0, now - batch.t_resp)
                           if batch.t_resp > 0.0 else 0.0, 6)},
            )
        for i, (f, a) in enumerate(zip(batch.futures, answers)):
            try:
                if a[0] == "ok":
                    ans = Answer(
                        value=a[1], window=int(a[2]),
                        watermark=int(a[3]), staleness=int(a[4]),
                        # the snapshot version rides newer servers'
                        # replies (cache-invalidation key); absent on a
                        # v1 peer's answers, which read as version 0.
                        # the event-time watermark stamp follows it —
                        # absent reads as -1, "no event time"; the
                        # shard + boot-lineage stamps after THAT are
                        # what a transaction pins from (ISSUE 20)
                        version=int(a[5]) if len(a) > 5 else 0,
                        event_ts=int(a[6]) if len(a) > 6 else -1,
                        shard=int(a[7]) if len(a) > 7 else -1,
                        boot=str(a[8]) if len(a) > 8 else "",
                    )
                    pin = _sent_pin(batch.txn_sent, ans.shard)
                    if pin is not None and \
                            (ans.version, ans.boot) != pin:
                        # the peer ignored the pin (a v1 txn-unaware
                        # server, or a stripped tag): DETECTED from
                        # the reply stamp and failed honestly — the
                        # transaction is never quietly handed this
                        # fresher (or older) answer
                        get_registry().counter(
                            "txn.unaware_peer"
                        ).inc()
                        self._set_exc(f, TxnSnapshotExpired(
                            f"pinned read (v{pin[0]}) answered at "
                            f"v{ans.version} by a txn-unaware peer",
                            kind="unaware_peer",
                        ))
                        continue
                    if i in floor_fail:
                        self._set_exc(f, RpcError(
                            f"monotonic read violated: shard "
                            f"{ans.shard} answered v{ans.version} "
                            f"behind the delivered floor "
                            f"(re-ask budget spent)"
                        ))
                        continue
                    if pin is None:
                        if batch.txn_ctx is not None:
                            batch.txn_ctx.observe(ans)
                        self._floor_note(
                            ans.shard, ans.version, ans.boot)
                    self._set_res(f, ans)
                elif a[0] == "txn_expired":
                    # typed honest expiry from the server's pinned
                    # answer path — re-raised per answer, counted at
                    # the server's raise site
                    self._set_exc(f, TxnSnapshotExpired(
                        str(a[1]),
                        kind=str(a[2]) if len(a) > 2 else "expired",
                    ))
                elif a[0] == "deadline":
                    # a SERVER-reported expiry (the answer rode a RESP
                    # frame): counted into the deadline total so
                    # deadline_expired - sweeper_expired isolates the
                    # outages the server never answered at all
                    get_registry().counter(
                        "rpc.client_deadline_expired"
                    ).inc()
                    self._set_exc(f, DeadlineExceeded(str(a[1])))
                else:
                    self._set_exc(f, RpcError(str(a[1])))
            except (IndexError, TypeError, ValueError):
                get_registry().counter(
                    "rpc.malformed", kind="answer"
                ).inc()
                self._set_exc(f, RpcError(f"malformed answer {a!r:.120}"))

    def _regressed(self, batch: _Batch, answers):
        """Floor-regression scan over a decoded OK payload.

        Returns the set of answer indices that must fail typed (re-ask
        budget spent), an empty set when nothing regressed, or None
        when the whole batch was RE-ASKED under a fresh id (satellite
        1: the resubmit-behind-the-floor bug). Pinned answers are
        exempt — a pin is exact-match checked at settle, not
        floor-checked. Runs on the io thread only (like _vfloor)."""
        hit = set()
        for i, a in enumerate(answers):
            try:
                if not (isinstance(a, list) and a and a[0] == "ok"
                        and len(a) > 8):
                    continue
                shard = int(a[7])
                boot = str(a[8])
                version = int(a[5])
            except (IndexError, TypeError, ValueError):
                continue  # the settle loop reports malformed answers
            if not boot or version <= 0:
                continue  # unstamped/merged answers carry no lineage
            if _sent_pin(batch.txn_sent, shard) is not None:
                continue
            fl = self._vfloor.get(shard)
            if fl is not None and fl[1] == boot and version < fl[0]:
                hit.add(i)
        if not hit:
            return hit
        get_registry().counter("rpc.client_regressions").inc()
        if batch.reasks >= self.FLOOR_REASKS:
            return hit  # typed failure at settle, never time travel
        batch.reasks += 1
        batch.id = f"{self._id_prefix}-{next(self._counter)}"
        with self._lock:
            self._pending[batch.id] = batch
        delay = jittered(
            exp_backoff(batch.reasks - 1, self.FLOOR_BASE_S,
                        self.FLOOR_MAX_S),
            0.5, self.seed, batch.reasks,
        )
        remaining = batch.remaining_s()
        if remaining is not None:
            delay = min(delay, max(0.001, remaining))
        self._schedule_resend(batch, delay)
        return None

    def _floor_note(self, shard: int, version: int, boot: str) -> None:
        """Advance the monotonic floor from one DELIVERED answer; a
        boot change is a new lineage and resets the shard's floor."""
        if not boot or version <= 0:
            return
        fl = self._vfloor.get(shard)
        if fl is None or fl[1] != boot or version > fl[0]:
            self._vfloor[shard] = (version, boot)

    def _fail(self, batch: _Batch, exc: BaseException) -> None:
        with self._lock:
            self._pending.pop(batch.id, None)
        for f in batch.futures:
            self._set_exc(f, exc)

    @staticmethod
    def _set_res(f: Future, ans: Answer) -> None:
        if not f.done():
            try:
                f.set_result(ans)
            except InvalidStateError:
                get_registry().counter(
                    "rpc.swallowed", site="client_settle_race"
                ).inc()

    @staticmethod
    def _set_exc(f: Future, exc: BaseException) -> None:
        if not f.done():
            try:
                f.set_exception(exc)
            except InvalidStateError:
                get_registry().counter(
                    "rpc.swallowed", site="client_settle_race"
                ).inc()

    # ------------------------------------------------------------------ #
    # Deadline sweeper (client-side expiry survives a dead server)
    # ------------------------------------------------------------------ #
    def _sweep(self) -> None:
        while not self._closing.wait(self.SWEEP_S):
            now = time.monotonic()
            expired = []
            with self._lock:
                for qid, b in list(self._pending.items()):
                    if b.deadline_abs is not None and \
                            now > b.deadline_abs:
                        expired.append(self._pending.pop(qid))
            for b in expired:
                # deadline_expired totals EVERY client-visible expiry
                # (these sweeper batches + the server-reported
                # per-answer expiries counted in _settle_ok);
                # sweeper_expired (ISSUE 9 satellite) isolates the
                # ones the server never answered at all — the outage
                # signal invisible to the obs plane until now
                get_registry().counter(
                    "rpc.client_deadline_expired"
                ).inc()
                get_registry().counter(
                    "rpc.client_sweeper_expired"
                ).inc()
                exc = DeadlineExceeded(
                    "query batch unanswered within its deadline "
                    "(server unreachable or slow)"
                )
                for f in b.futures:
                    self._set_exc(f, exc)

    # ------------------------------------------------------------------ #
    def close(self) -> None:
        if self._closing.is_set():
            return
        self._closing.set()
        wire = self._wire
        if wire is not None:
            wire.close()
        self._io_thread.join(5.0)
        self._sweep_thread.join(5.0)
        with self._lock:
            leftovers = list(self._pending.values())
            self._pending.clear()
        exc = RpcError("rpc client closed with the batch pending")
        for b in leftovers:
            for f in b.futures:
                self._set_exc(f, exc)
