"""StreamServer: concurrent point-query serving beside live ingest.

Thread layout (one server = two daemon threads, same discipline as
``core/pipeline.py:prefetch`` — the producer owns the device step loop,
consumers never stall it):

- **ingest thread**: drives the servable's emission iterator (any
  per-window payload stream) and publishes one immutable snapshot per
  window into the :class:`~.snapshot_store.SnapshotStore`. Publishing is
  one atomic reference swap, so ingest never waits on readers.
- **query worker thread**: drains ALL currently-pending queries in one
  sweep, groups them by class, and answers each group with one
  vectorized :class:`~.query.QueryEngine` kernel against the latest
  snapshot — concurrent load COALESCES into bigger batches instead of
  queueing per-query dispatches (the serving analog of window batching).

Admission control is explicit: past ``max_pending`` in-flight queries,
:meth:`StreamServer.submit` raises :class:`Overloaded` immediately
instead of buffering unboundedly or blocking the caller — clients see
back-pressure, ingest sees nothing. ``close()`` stops ingest at the next
window boundary, answers every already-admitted query from the final
snapshot, and joins both threads.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from concurrent.futures import Future, InvalidStateError
from typing import Iterator, Optional, Tuple

from ..obs import flight as _flight
from ..obs import trace as _trace
from ..obs.registry import get_registry
from ..resilience import faults as _faults
from ..resilience.errors import DeadlineExceeded, InjectedFault
from ..resilience.retry import RetryPolicy
from .query import Answer, Query, QueryEngine
from .snapshot_store import PublishedSnapshot, SnapshotStore
from .stats import ServingStats
from .txn import PinnedQuery, TxnSnapshotExpired


def _unwrap(q):
    """The engine-facing query behind a possibly-pinned entry."""
    return q.q if isinstance(q, PinnedQuery) else q


class Overloaded(RuntimeError):
    """The server's admission limit is reached; retry with back-off.
    Raised from ``submit``/``ask`` so rejection is synchronous and
    explicit — an overloaded serving tier must shed, not buffer.
    ``submit`` retries these internally when a
    :class:`~gelly_streaming_tpu.resilience.RetryPolicy` is configured."""


class Shed(Overloaded):
    """The query's CLASS is being load-shed under sustained pressure
    (see ``StreamServer`` ``shed_classes``). Never retried by the
    built-in retry policy: shedding exists to lose exactly this
    traffic so the protected classes keep their latency."""


class Servable:
    """Adapter contract a workload implements to be served (see
    ``library/connected_components.py:servable`` et al.).

    ``payloads(source)`` is the emission iterator the ingest thread
    drives: per window it yields ``(payload, watermark)`` where
    ``payload`` is an immutable mapping the :class:`QueryEngine`
    understands (``labels``/``deg``/``ranks`` + ``vdict``) and
    ``watermark`` a monotone progress counter (cumulative edges where
    cheap to count, else the window ordinal). ``boot_payload()`` returns
    the same pair from already-restored carry state (or None when there
    is nothing to serve yet) — the checkpoint-boot path publishes it as
    window -1 before the first live window lands.
    """

    #: query classes this servable's payloads answer (documentation +
    #: eager misconfiguration checks)
    query_classes: tuple = ()

    def payloads(self, source) -> Iterator[Tuple[dict, int]]:
        raise NotImplementedError

    def boot_payload(self) -> Optional[Tuple[dict, int]]:
        return None


class StreamServer:
    """Serve point queries from a live stream's running summary.

    Parameters
    ----------
    servable:
        A :class:`Servable` (or any object with its ``payloads``
        contract). A bare iterator of ``(payload, watermark)`` pairs is
        accepted with ``source=None``.
    source:
        The stream / event iterable handed to ``servable.payloads``.
    max_pending:
        Admission limit: queries admitted but not yet answered. At the
        limit, ``submit`` raises :class:`Overloaded`.
    retry_policy:
        Default :class:`~gelly_streaming_tpu.resilience.RetryPolicy` for
        :class:`Overloaded` rejections: ``submit`` blocks the CALLER
        through bounded-exponential, jittered re-admission attempts
        before giving up (clients get back-pressure-with-patience
        instead of hand-rolling retry loops). None (default) keeps
        rejections immediate. :class:`Shed` rejections never retry.
    shed_classes:
        Query classes (types or type names) to LOAD-SHED under
        sustained pressure: once admitted load has stayed at or above
        ``shed_watermark * max_pending`` for ``shed_after_s`` seconds,
        submits of these classes raise :class:`Shed` immediately
        (counted as ``serving.shed{cls=...}`` in the obs registry)
        while other classes keep the remaining headroom. Pressure
        clears the moment load drops below the watermark.
    watchdog_s:
        Arms a worker stall watchdog: a daemon thread that warns (and
        counts ``serving.worker_stalls``) whenever queries are pending
        but the worker loop has not completed a sweep within this many
        seconds — the serving analog of the prefetch stall watchdog.
    autotune:
        Load-aware admission (ISSUE 15): an
        :class:`~gelly_streaming_tpu.control.AdmissionTuner` re-tunes
        ``max_pending`` and the shed watermark from MEASURED queue wait
        vs the deadline budgets queries actually carry — queue wait is
        the leading signal, so shedding tightens while protected
        classes still have headroom, and recovers toward the configured
        ceiling when load clears (bounded steps, hysteresis, every move
        a ``control.retune`` event). The configured ``max_pending`` /
        ``shed_watermark`` stay the CEILING — the tuner only moves
        inside them. With no deadlines in the traffic, set
        ``target_wait_s`` or the tuner holds (nothing to compare
        against).
    """

    def __init__(
        self,
        servable,
        source=None,
        *,
        max_pending: int = 1024,
        store: Optional[SnapshotStore] = None,
        engine: Optional[QueryEngine] = None,
        stats: Optional[ServingStats] = None,
        retry_policy: Optional[RetryPolicy] = None,
        shed_classes: tuple = (),
        shed_watermark: float = 0.8,
        shed_after_s: float = 0.05,
        watchdog_s: Optional[float] = None,
        autotune: bool = False,
        target_wait_s: Optional[float] = None,
    ):
        self._servable = servable
        self._source = source
        self.store = store or SnapshotStore()
        self.engine = engine or QueryEngine()
        self.stats = stats or ServingStats()
        self.max_pending = int(max_pending)
        self.retry_policy = retry_policy
        self._shed_names = frozenset(
            c if isinstance(c, str) else c.__name__ for c in shed_classes
        )
        self._shed_level = max(1, int(shed_watermark * self.max_pending))
        self.shed_after_s = float(shed_after_s)
        self.admission = None
        if autotune:
            from ..control import AdmissionTuner

            self.admission = AdmissionTuner(
                max_pending=self.max_pending,
                shed_watermark=shed_watermark,
                target_wait_s=target_wait_s,
            )
        self._pressure_t0: Optional[float] = None  # sustained-load start
        self.watchdog_s = watchdog_s
        self._worker_beat = time.monotonic()
        self._watchdog_thread: Optional[threading.Thread] = None
        self._watchdog_stop = threading.Event()
        # (query, future, t_submit, deadline_abs_or_None, trace_ctx)
        self._pending: deque = deque()
        self._inflight = 0  # drained by the worker, not yet answered
        # the drained batch's entries, kept until _settle: if the worker
        # thread DIES mid-sweep (injected crash, answer-path bug past
        # the guards) these futures would otherwise be unreachable —
        # failover promotion re-homes them onto the standby
        self._inflight_entries: list = []
        self._sweeps = 0  # completed worker sweeps (fault-plan ordinal)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop_ingest = threading.Event()
        self._ingest_done = threading.Event()
        self._ingest_error: Optional[BaseException] = None
        self._closing = False
        self._closed = False
        self._window = -1  # last published live window
        self._ingest_thread: Optional[threading.Thread] = None
        self._worker_thread: Optional[threading.Thread] = None
        # flipped by a failover promotion (ReplicaServer.promote): a
        # pinned read expiring AFTER promotion is a failover casualty
        # and is additionally counted txn.failover_expired — the storm
        # gate separates those honest expiries from ring churn
        self.txn_failover = False

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #
    def publish_boot(self, payload: dict, watermark: int = 0,
                     version: Optional[int] = None,
                     boot: Optional[str] = None) -> None:
        """Publish a pre-ingest snapshot (window -1): the checkpoint-boot
        path serves the restored summary immediately, before the first
        catch-up window folds. Must run before :meth:`start`.
        ``version`` carries the mirrored snapshot's original version
        through a restart (see :meth:`SnapshotStore.publish`); ``boot``
        carries its lineage nonce the same way, so a restart-adopted
        replica stays addressable by pinned transactions."""
        if self._ingest_thread is not None:
            raise RuntimeError("publish_boot must precede start()")
        self.store.publish(payload, window=-1, watermark=watermark,
                           version=version, boot=boot)

    def start(self) -> "StreamServer":
        if self._ingest_thread is not None:
            raise RuntimeError("server already started")
        self._ingest_thread = threading.Thread(
            target=self._ingest, name="stream-server-ingest", daemon=True
        )
        self._worker_thread = threading.Thread(
            target=self._worker, name="stream-server-queries", daemon=True
        )
        self._ingest_thread.start()
        self._worker_thread.start()
        if self.watchdog_s is not None:
            self._watchdog_thread = threading.Thread(
                target=self._watchdog, name="stream-server-watchdog",
                daemon=True,
            )
            self._watchdog_thread.start()
        return self

    def __enter__(self) -> "StreamServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def _payload_iter(self) -> Iterator[Tuple[dict, int]]:
        payloads = getattr(self._servable, "payloads", None)
        if payloads is not None:
            return payloads(self._source)
        if self._source is not None:
            raise TypeError(
                f"{type(self._servable).__name__} has no payloads(); "
                "pass a Servable, or a bare (payload, watermark) "
                "iterator with source=None"
            )
        return iter(self._servable)

    def _ingest(self) -> None:
        it = self._payload_iter()
        try:
            for payload, watermark in it:
                if self._stop_ingest.is_set():
                    break
                if payload is None:  # a window with nothing servable
                    continue
                self._window += 1
                # a mirror follower smuggles the PRIMARY's version and
                # boot lineage through the payload (carry_version) so a
                # standby's ring mirrors the primary's stamps; pop the
                # smuggled keys off a COPY — the published payload must
                # look like any other servable payload
                version = boot = None
                if hasattr(payload, "get") and "snap_version" in payload:
                    payload = dict(payload)
                    version = int(payload.pop("snap_version"))
                    boot = payload.pop("snap_boot", None)
                # an event-time pipeline's servable carries its
                # watermark stamp in the payload; count windows do not
                # (-1 = "no event time", the Answer default)
                self.store.publish(
                    payload, self._window, int(watermark),
                    event_ts=int(payload.get("event_ts", -1))
                    if hasattr(payload, "get") else -1,
                    version=version, boot=boot,
                )
        except BaseException as e:  # surfaced via join()/close()
            self._ingest_error = e
        finally:
            if self._stop_ingest.is_set():
                close = getattr(it, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:
                        # the stream is already torn down; the close
                        # failure must not mask the shutdown, but it
                        # must be visible in the event stream
                        get_registry().counter(
                            "serving.swallowed", site="ingest_close"
                        ).inc()
            self._ingest_done.set()
            self._wake.set()  # the worker re-checks exit conditions

    # ------------------------------------------------------------------ #
    # Query surface
    # ------------------------------------------------------------------ #
    def submit(
        self,
        query: Query,
        *,
        deadline_s: Optional[float] = None,
        retry_policy: Optional[RetryPolicy] = None,
        ctx=None,
        txn=None,
    ) -> "Future[Answer]":
        """Admit one query; resolves to an :class:`~.query.Answer`.
        Raises :class:`Overloaded` at the admission limit — immediately,
        on the caller's thread, so clients get synchronous back-pressure
        — unless a retry policy (per-call, else the server default)
        absorbs it: then the CALLER blocks through bounded-backoff
        re-admission attempts (``serving.retries`` counts them) and
        only a spent budget re-raises. :class:`Shed` never retries.

        ``deadline_s`` bounds how long the query may WAIT: if the
        worker has not answered it that many seconds after submission,
        its future fails with
        :class:`~gelly_streaming_tpu.resilience.errors.DeadlineExceeded`
        (``serving.deadline_expired`` counts it) instead of returning
        an arbitrarily stale answer to a caller that stopped caring.

        ``ctx`` is an optional
        :class:`~gelly_streaming_tpu.obs.trace.TraceContext` the query
        rides through the pending queue: the worker stamps its stage
        spans with the trace id, and the context survives failover
        adoption, so a re-answered query stays on its original trace.
        When omitted (and tracing is on) the submitting thread's active
        context is captured — same-process callers inside a span get
        joined-up traces for free.

        ``txn`` is a decoded transaction doc (see
        :func:`~gelly_streaming_tpu.serving.txn.decode_txn`): when it
        carries a ``pin``, the query is answered AT that pinned
        ``(version, boot)`` snapshot from the retention ring, or fails
        with a typed
        :class:`~gelly_streaming_tpu.serving.txn.TxnSnapshotExpired` —
        never a silently fresher answer."""
        pin = None if txn is None else txn.get("pin")
        if pin is not None:
            query = PinnedQuery(query, pin[0], pin[1])
        policy = retry_policy if retry_policy is not None else self.retry_policy
        attempt = 0
        # the deadline is a TOTAL budget (GL008): pin it to a wall
        # clock once, spend retry sleeps against it, and admit with
        # what REMAINS — a query re-admitted after backoff must not be
        # granted a fresh full deadline measured from its late t0
        deadline = None if deadline_s is None \
            else time.monotonic() + float(deadline_s)
        while True:
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            try:
                return self._admit(query, remaining, ctx)
            except Shed:
                raise
            except Overloaded:
                delay = None if policy is None \
                    else policy.delay_before(attempt, remaining)
                if delay is None:
                    raise
                attempt += 1
                get_registry().counter("serving.retries").inc()
                time.sleep(delay)

    def _admit(
        self, query: Query, deadline_s: Optional[float], ctx=None
    ) -> "Future[Answer]":
        declared = getattr(self._servable, "query_classes", ())
        if declared and not isinstance(_unwrap(query), tuple(declared)):
            # reject the wrong class SYNCHRONOUSLY on the caller's
            # thread: batched answering would otherwise fail the whole
            # drained sweep (hundreds of valid concurrent queries) on
            # one client's misdirected query
            raise TypeError(
                f"{type(self._servable).__name__} serves "
                f"{[c.__name__ for c in declared]}, not "
                f"{type(_unwrap(query)).__name__}"
            )
        f: "Future[Answer]" = Future()
        with self._lock:
            # the closing check must sit INSIDE the lock: an unlocked
            # read could pass just before close() flips the flag, and an
            # append landing after close()'s final leftover drain would
            # hang its future forever (no worker left to answer it).
            # Inside the lock, any append that beats the flag is still
            # caught by close()'s drain, which runs after the flag set.
            if self._closing or self._closed:
                raise RuntimeError("server is closed")
            # count the worker's drained-but-unanswered batch too, or a
            # slow answer sweep would let admissions reach 2x the limit
            admitted = len(self._pending) + self._inflight
            # sustained-pressure tracking for class shedding: the clock
            # starts when load reaches the watermark and clears the
            # moment it drops below (a burst alone never sheds)
            now = time.monotonic()
            if admitted >= self._shed_level:
                if self._pressure_t0 is None:
                    self._pressure_t0 = now
            else:
                self._pressure_t0 = None
            qname = type(_unwrap(query)).__name__
            if (
                self._shed_names
                and self._pressure_t0 is not None
                and now - self._pressure_t0 >= self.shed_after_s
                and qname in self._shed_names
            ):
                self.stats.record_rejected()
                get_registry().counter(
                    "serving.shed", cls=qname
                ).inc()
                raise Shed(
                    f"{qname} shed under sustained "
                    f"pressure ({admitted}/{self.max_pending} in flight)"
                )
            if admitted >= self.max_pending:
                self.stats.record_rejected()
                raise Overloaded(
                    f"{admitted} queries in flight "
                    f"(max_pending={self.max_pending})"
                )
            t0 = time.perf_counter()
            deadline = None if deadline_s is None else t0 + float(deadline_s)
            if ctx is None and _trace.on():
                ctx = _trace.current_context()
            self._pending.append((query, f, t0, deadline, ctx))
            self.stats.set_pending(admitted + 1)  # admission gauge
        self._wake.set()
        return f

    def submit_many(
        self,
        queries,
        *,
        deadline_s: Optional[float] = None,
        ctx=None,
        txn=None,
    ) -> list:
        """Admit a whole wire batch under ONE lock acquisition — the
        RPC front end's fast path (a 32-query frame previously paid 32
        lock/wake round trips; admission is all-or-nothing, so a
        rejected batch leaves nothing half-admitted, exactly the
        cancel-the-partial-batch semantics the wire already promises).
        Raises like :meth:`submit`; no retry-policy absorption (the
        wire client owns retry pacing). ``txn`` pins the whole batch
        at one snapshot, as in :meth:`submit`."""
        declared = getattr(self._servable, "query_classes", ())
        if declared:
            for q in queries:
                if not isinstance(q, tuple(declared)):
                    raise TypeError(
                        f"{type(self._servable).__name__} serves "
                        f"{[c.__name__ for c in declared]}, not "
                        f"{type(q).__name__}"
                    )
        pin = None if txn is None else txn.get("pin")
        if pin is not None:
            queries = [PinnedQuery(q, pin[0], pin[1]) for q in queries]
        futures = [Future() for _ in queries]
        t0 = time.perf_counter()
        deadline = None if deadline_s is None \
            else t0 + float(deadline_s)
        if ctx is None and _trace.on():
            ctx = _trace.current_context()
        with self._lock:
            if self._closing or self._closed:
                raise RuntimeError("server is closed")
            admitted = len(self._pending) + self._inflight
            now = time.monotonic()
            # pressure/shed accounting tracks each query's would-be
            # admission depth, EXACTLY like N sequential _admit calls
            # (a batch whose tail crosses the watermark must shed the
            # same classes the per-query loop would have) — but the
            # wire cancels a partially-admitted batch on Shed anyway,
            # so rejection here is all-or-nothing
            for i, q in enumerate(queries):
                cur = admitted + i
                if cur >= self._shed_level:
                    if self._pressure_t0 is None:
                        self._pressure_t0 = now
                else:
                    self._pressure_t0 = None
                qname = type(_unwrap(q)).__name__
                if (
                    self._shed_names
                    and self._pressure_t0 is not None
                    and now - self._pressure_t0 >= self.shed_after_s
                    and qname in self._shed_names
                ):
                    self.stats.record_rejected()
                    get_registry().counter(
                        "serving.shed", cls=qname
                    ).inc()
                    raise Shed(
                        f"{qname} shed under sustained "
                        f"pressure ({cur}/{self.max_pending} "
                        "in flight)"
                    )
            if admitted + len(queries) > self.max_pending:
                self.stats.record_rejected()
                raise Overloaded(
                    f"{admitted} queries in flight "
                    f"(max_pending={self.max_pending})"
                )
            self._pending.extend(
                (q, f, t0, deadline, ctx)
                for q, f in zip(queries, futures)
            )
            self.stats.set_pending(admitted + len(queries))
        self._wake.set()
        return futures

    def ask(self, query: Query, timeout: Optional[float] = None,
            deadline_s: Optional[float] = None) -> Answer:
        """Synchronous point query (submit + wait)."""
        return self.submit(query, deadline_s=deadline_s).result(timeout)

    def snapshot(self) -> Optional[PublishedSnapshot]:
        """The snapshot queries are currently answered from."""
        return self.store.latest()

    # ------------------------------------------------------------------ #
    # Worker
    # ------------------------------------------------------------------ #
    def _drain(self) -> list:
        with self._lock:
            drained = list(self._pending)
            self._pending.clear()
            # deadline sweep happens at drain time (the worker's
            # cadence): an expired query is settled with
            # DeadlineExceeded instead of joining the answer batch —
            # it must not spend engine time on an answer nobody wants
            batch = []
            now = time.perf_counter()
            expired = []
            for entry in drained:
                dl = entry[3]
                if dl is not None and now > dl:
                    expired.append(entry)
                else:
                    batch.append(entry)
            self._inflight = len(batch)
            self._inflight_entries = batch
        for q, f, t0, dl, _ctx in expired:
            self._expire(q, f, t0, dl, "unanswered after")
        if expired and not batch:
            # the whole drain expired: nothing will reach the answer
            # path's _settle, so settle here or an idle server reports
            # the expired burst as a phantom backlog forever
            self._settle()
        if batch:
            # coalescing evidence: how many concurrent queries one
            # vectorized sweep absorbed (empty sweeps are not recorded —
            # the idle poll would drown the signal)
            self.stats.record_drain(len(batch))
        return batch

    @staticmethod
    def _expire(q, f, t0, dl, verb: str) -> None:
        """Settle one deadline-expired query: count it and fail its
        future, with the same cancel-race guard as the answer path (a
        client may cancel() mid-sweep; set_exception then raises, and
        that must never kill the worker)."""
        get_registry().counter("serving.deadline_expired").inc()
        if not f.done():
            try:
                f.set_exception(DeadlineExceeded(
                    f"{type(q).__name__} {verb} its {dl - t0:.3f}s "
                    "deadline"
                ))
            except InvalidStateError:
                # client cancel() raced the sweep; the future is
                # already settled — count the race, don't hide it
                get_registry().counter(
                    "serving.swallowed", site="expire_settle_race"
                ).inc()

    def _settle(self) -> None:
        with self._lock:
            self._inflight = 0
            self._inflight_entries = []
            # the answered batch left flight: the admission gauge must
            # fall back to what is actually still waiting, or an idle
            # server reports the last burst as a phantom backlog forever
            self.stats.set_pending(len(self._pending))

    def _answer(self, batch: list) -> None:
        # during live ingest, trade bounded staleness (READY_LOOKBACK
        # windows at most) for latency: answer from the freshest snapshot
        # whose arrays already materialized instead of blocking on the
        # just-dispatched window's fold. Once the stream has ended the
        # head is insisted on, so post-stream answers are staleness-0.
        snap = self.store.latest(
            prefer_ready=not self._ingest_done.is_set()
        )
        if snap is None:
            # admitted before the first publish and the stream is gone:
            # fail explicitly rather than hang the futures
            err = RuntimeError(
                "server closed before any snapshot was published"
            )
            if self._ingest_error is not None:
                err.__cause__ = self._ingest_error
            for _, f, *_rest in batch:
                f.set_exception(err)
            return
        # partition pinned transactional reads out of the sweep: each
        # distinct (version, boot) pin answers from ITS ring snapshot
        # (or expires typed), the rest from the freshest as ever
        pinned: dict = {}
        plain = []
        for entry in batch:
            q = entry[0]
            if isinstance(q, PinnedQuery):
                pinned.setdefault((q.version, q.boot), []).append(entry)
            else:
                plain.append(entry)
        for (ver, boot), group in pinned.items():
            self._answer_pinned(ver, boot, group)
        if not plain:
            return
        batch = plain
        queries = [q for q, *_rest in batch]
        tracing = _trace.on()
        t_dispatch = time.perf_counter()
        try:
            with _trace.span(
                "serving.answer",
                {"batch": len(batch), "window": snap.window}
                if tracing else None,
            ):
                answers = self.engine.answer_batch(
                    snap, queries, head_window=self.store.head_window()
                )
        except Exception as e:
            for _, f, *_rest in batch:
                if not f.done():
                    f.set_exception(e)
            return
        now = time.perf_counter()
        self.stats.record_batch()
        if self.admission is not None:
            # load-aware admission tap (one per sweep, never per query):
            # the sweep's OLDEST queue wait — entries drain in FIFO
            # order, so the batch head waited longest — against the
            # tightest deadline budget the sweep carried
            if self.admission.tap_entries(
                t_dispatch - batch[0][2],
                ((t0_, dl_) for _q, _f, t0_, dl_, _c in batch),
            ):
                with self._lock:
                    self.max_pending = self.admission.max_pending
                    self._shed_level = self.admission.shed_level()
        # per-trace attribution (ISSUE 9): entries from one wire batch
        # share a TraceContext; group on it so each traced batch gets
        # ONE serving.query span carrying the stage breakdown (per-query
        # spans would multiply the event log by the batch size for no
        # extra information — queries of a sweep share the dispatch)
        groups: dict = {} if tracing else None
        dispatch_s = now - t_dispatch
        snapshot_age_s = time.monotonic() - snap.published_at
        for (q, f, t0, dl, ctx), ans in zip(batch, answers):
            # deadline re-check at settle time: a query drained in time
            # but answered late (a slow engine sweep) must still honor
            # its deadline rather than deliver a stale answer the
            # caller stopped waiting for
            if dl is not None and now > dl:
                self._expire(q, f, t0, dl, "answered after")
                continue
            self.stats.record(
                type(q).__name__, now - t0, ans.staleness,
                exemplar=ctx.trace_id if tracing and ctx is not None
                else None,
            )
            if tracing and ctx is not None:
                g = groups.get(id(ctx))
                if g is None:
                    groups[id(ctx)] = [ctx, t0, 1, ans.staleness]
                else:
                    g[1] = min(g[1], t0)
                    g[2] += 1
                    g[3] = max(g[3], ans.staleness)
            # a client may have cancel()ed its future mid-sweep;
            # settling it then raises InvalidStateError, which must not
            # poison the rest of the batch's answers
            if not f.done():
                try:
                    f.set_result(ans)
                except InvalidStateError:
                    get_registry().counter(
                        "serving.swallowed", site="answer_settle_race"
                    ).inc()
        if tracing and groups:
            settle_s = time.perf_counter() - now
            for ctx, t0_min, n, stale in groups.values():
                _trace.record_span(
                    "serving.query",
                    now - t0_min,
                    trace_id=ctx.trace_id,
                    parent=ctx.parent_sid,
                    attrs={
                        "n": n,
                        "queue_wait_s": round(t_dispatch - t0_min, 6),
                        "dispatch_s": round(dispatch_s, 6),
                        "settle_s": round(settle_s, 6),
                        "snapshot_age_s": round(snapshot_age_s, 6),
                        "staleness": int(stale),
                        "window": snap.window,
                    },
                )

    def _answer_pinned(self, version: int, boot: str,
                       group: list) -> None:
        """Answer one pinned group AT its ``(version, boot)`` snapshot.
        An expired pin fails the whole group with the typed error it
        deserves — the honesty contract: a transaction is told its
        snapshot is gone, never handed a fresher answer. After a
        failover promotion the expiry is additionally counted
        ``txn.failover_expired`` (the storm gate's honest-expiry lane)."""
        try:
            psnap = self.store.at_version(version, boot)
        except TxnSnapshotExpired as e:
            if self.txn_failover:
                get_registry().counter("txn.failover_expired").inc()
            for _q, f, *_rest in group:
                if not f.done():
                    try:
                        f.set_exception(e)
                    except InvalidStateError:
                        get_registry().counter(
                            "serving.swallowed",
                            site="answer_settle_race",
                        ).inc()
            return
        queries = [entry[0].q for entry in group]
        try:
            answers = self.engine.answer_batch(
                psnap, queries, head_window=self.store.head_window()
            )
        except Exception as e:
            for _q, f, *_rest in group:
                if not f.done():
                    f.set_exception(e)
            return
        get_registry().counter("txn.pinned_reads").inc(len(group))
        now = time.perf_counter()
        for (q, f, t0, dl, _ctx), ans in zip(group, answers):
            if dl is not None and now > dl:
                self._expire(q, f, t0, dl, "answered after")
                continue
            self.stats.record(type(q.q).__name__, now - t0,
                              ans.staleness)
            if not f.done():
                try:
                    f.set_result(ans)
                except InvalidStateError:
                    get_registry().counter(
                        "serving.swallowed", site="answer_settle_race"
                    ).inc()

    def _worker(self) -> None:
        try:
            self._worker_loop()
        except InjectedFault:
            # the fault plan's simulated worker death: count it and end
            # the thread QUIETLY (no interpreter-level thread traceback
            # — the death is the experiment, the failover monitor's
            # promotion is the observable)
            get_registry().counter("serving.worker_deaths").inc()
            _flight.dump_installed("serving.worker_death:injected")
        except BaseException as e:
            # the loop's answer path already survives everything; an
            # exception HERE is real worker death (a drain-path bug) —
            # record it so the failover monitor can promote a standby,
            # commit the flight recorder's ring (the events that led
            # here are this death's black box), and let the thread
            # traceback surface
            get_registry().counter("serving.worker_deaths").inc()
            _flight.dump_installed(
                "serving.worker_death", error=repr(e)[:200]
            )
            raise

    def worker_alive(self) -> bool:
        """True while the query worker thread is running — the liveness
        signal the failover monitor polls."""
        t = self._worker_thread
        return t is not None and t.is_alive()

    def heartbeat_age_s(self) -> float:
        """Seconds since the worker last completed (started) a sweep —
        the liveness AGE an external probe reads to tell a wedged
        worker (old beat, thread alive) from a healthy idle one (fresh
        beat): ``worker_alive`` alone cannot make that distinction."""
        return max(0.0, time.monotonic() - self._worker_beat)

    def metrics_endpoint(self, **kw):
        """Start a scrape endpoint wired to this server:
        ``/metrics`` renders the process registry, ``/healthz`` reports
        worker liveness / pending depth / ingest state. Keyword args
        pass through to
        :class:`~gelly_streaming_tpu.obs.endpoint.MetricsEndpoint`
        (``port=0`` binds an ephemeral port). The caller owns
        ``close()``."""
        from ..obs.endpoint import MetricsEndpoint

        return MetricsEndpoint.for_server(self, **kw).start()

    def _adopt(self, entries: list) -> None:
        """Enqueue already-admitted ``(query, future, t0, deadline,
        ctx)`` entries from another server — the failover promotion
        path. The entries keep their original submit times, deadlines,
        AND trace contexts, so re-answered queries still report honest
        latency and stay on their original trace (the promoted
        replica's answer span joins the same causal story); adoption
        bypasses admission on purpose (the queries were admitted once;
        failover must not shed them)."""
        if not entries:
            return
        with self._lock:
            self._pending.extend(entries)
            self.stats.set_pending(
                len(self._pending) + self._inflight
            )
        self._wake.set()

    def _worker_loop(self) -> None:
        while True:
            # heartbeat first: the watchdog reads it to distinguish a
            # stalled sweep (answer wedged on a device op) from idling
            self._worker_beat = time.monotonic()
            if _faults.active():  # chaos hook: worker stall / crash
                _faults.fire("serving.worker", index=self._sweeps)
            self._sweeps += 1
            batch = self._drain()
            if batch:
                if self.store.latest() is None and not (
                    self._closing or self._ingest_done.is_set()
                ):
                    # nothing published yet: hold the batch until the
                    # first window (or shutdown) instead of failing
                    self.store.wait_for(1, timeout=0.1)
                    with self._lock:
                        self._pending.extendleft(reversed(batch))
                        self._inflight = 0
                        self._inflight_entries = []
                    continue
                try:
                    self._answer(batch)
                except BaseException as e:
                    # the worker thread must survive ANY answer-path
                    # error — a dead worker hangs every future forever;
                    # fail this batch and keep serving
                    for _, f, *_rest in batch:
                        if not f.done():
                            f.set_exception(e)
                finally:
                    self._settle()
                continue
            if self._closing and not self._pending:
                return
            self._wake.wait(0.05)
            self._wake.clear()

    def _watchdog(self) -> None:
        """Stall watchdog (armed via ``watchdog_s``): flags a worker
        that has queries WAITING but has not completed a sweep within
        the threshold — wedged in an answer, not idle. Warns once per
        stall episode and counts ``serving.worker_stalls``; detection
        only (restart policy belongs to the operator — killing a thread
        blocked in a device op is not safe from here)."""
        flagged = False
        interval = max(self.watchdog_s / 2, 0.01)
        # interruptible wait: close() sets the stop event, so shutdown
        # never blocks on a half-period sleep
        while not self._watchdog_stop.wait(interval):
            with self._lock:
                waiting = bool(self._pending) or self._inflight > 0
            stalled = (
                waiting
                and self._worker_thread is not None
                and self._worker_thread.is_alive()
                and time.monotonic() - self._worker_beat > self.watchdog_s
            )
            if stalled and not flagged:
                flagged = True
                get_registry().counter("serving.worker_stalls").inc()
                warnings.warn(
                    f"serving worker made no progress for "
                    f"{self.watchdog_s}s with queries pending",
                    RuntimeWarning,
                )
            elif not stalled:
                flagged = False

    # ------------------------------------------------------------------ #
    # Shutdown
    # ------------------------------------------------------------------ #
    def ingest_finished(self) -> bool:
        """True once the servable's emission iterator is exhausted (or
        failed); the server keeps serving from the final snapshot."""
        return self._ingest_done.is_set()

    def join(self, timeout: Optional[float] = None) -> None:
        """Wait for ingest to finish the stream (server keeps serving
        from the final snapshot). Re-raises an ingest-side error."""
        if not self._ingest_done.wait(timeout):
            raise TimeoutError("ingest still running")
        if self._ingest_error is not None:
            raise self._ingest_error

    def close(self, timeout: float = 30.0) -> None:
        """Stop ingest at the next window boundary, answer every
        already-admitted query from the final snapshot, join both
        threads. Idempotent. ``timeout`` bounds the WHOLE close: each
        join gets what remains of the one budget (GL008), so a wedged
        ingest thread cannot triple the caller's wait."""
        if self._closed:
            return
        deadline = time.monotonic() + float(timeout)

        def remaining() -> float:
            return max(0.0, deadline - time.monotonic())

        with _trace.span("serving.drain"):
            self._closing = True
            self._stop_ingest.set()
            self._wake.set()
            if self._ingest_thread is not None:
                self._ingest_thread.join(remaining())
            if self._worker_thread is not None:
                self._worker_thread.join(remaining())
            # a submit racing the closing flag can slip one entry past
            # the worker's exit check; answer stragglers here so no
            # future hangs
            leftovers = self._drain()
            if leftovers:
                try:
                    self._answer(leftovers)
                except BaseException as e:
                    for _, f, *_rest in leftovers:
                        if not f.done():
                            f.set_exception(e)
                finally:
                    self._settle()
            self.store.close()
            self._closed = True
            self._watchdog_stop.set()
            if self._watchdog_thread is not None:
                self._watchdog_thread.join(remaining())
        if self._ingest_error is not None:
            raise self._ingest_error
