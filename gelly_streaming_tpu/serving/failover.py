"""Serving replica failover: a standby server promoted on primary death.

The serving tier's availability story so far ends at the worker thread:
``StreamServer``'s answer path survives any per-batch error, but a death
of the worker itself (an injected crash in the chaos harness, a bug past
the guards, a wedged device op the watchdog can only report) leaves
every admitted future hanging forever. This module adds the replica
analog of the pipeline's supervised recovery:

- :class:`FailoverServer` runs a PRIMARY :class:`~.server.StreamServer`
  (which owns ingest) and a STANDBY attached to the SAME
  :class:`~.snapshot_store.SnapshotStore`. Snapshots are immutable and
  publication is one reference swap, so the standby needs no catch-up
  protocol — the store IS the replicated state, and the standby's first
  answer is as fresh as the newest published snapshot.
- A monitor thread polls primary worker liveness; on death (or an
  explicit :meth:`promote`) the standby starts, the primary's admitted
  queries move over, and new submits route to the standby. In-flight
  queries past their deadline fail
  :class:`~gelly_streaming_tpu.resilience.errors.DeadlineExceeded`
  (counted ``serving.failover_expired`` on top of the usual
  ``serving.deadline_expired``); the rest are RE-ANSWERED from the
  standby's newest snapshot with their original submit times and
  deadlines (``serving.failover_requeued``).
- Admission, shedding, and retry policies carry over: both replicas are
  constructed from the same configuration and share one
  :class:`~.stats.ServingStats`, so ``max_pending``, ``shed_classes``,
  the default ``retry_policy``, and the stats continuity a dashboard
  depends on are identical before and after promotion.

Ingest is NOT failed over here: if the primary's ingest thread is alive
it keeps publishing into the shared store (a worker death does not stop
the stream), and if ingest died the standby serves the newest snapshot —
the same keep-serving-from-final-state contract a closed stream already
has. Process-level ingest recovery belongs to the supervisor/cluster
layer (``resilience/supervisor.py``, ``resilience/coordinated.py``).

Every promotion is visible in the obs registry:
``serving.failover{reason=...}``, ``serving.failover_requeued``,
``serving.failover_expired``, the ``serving.promotion_seconds``
takeover-latency histogram (plus a ``serving.promotion`` span when
tracing is on), and the ``serving.worker_deaths`` the server itself
records.
"""

from __future__ import annotations

import threading
import time
from typing import Iterator, Optional, Tuple

from ..obs import trace as _trace
from ..obs.registry import get_registry
from .query import Answer, Query
from .server import StreamServer
from .snapshot_store import PublishedSnapshot, SnapshotStore
from .stats import ServingStats


def _follow_ingest(primary_done, stop) -> Iterator[Tuple[dict, int]]:
    """The standby's ingest: publish nothing, but END only when the
    PRIMARY's ingest ends (or this replica is told to stop). The
    standby also shares the primary's ``_ingest_done`` event, so the
    stream is "over" for the standby exactly when it is over for the
    shared store. An instantly-finishing empty ingest would instead
    flip the standby into post-stream mode while the primary is still
    publishing: its answers would insist on the head snapshot (whose
    arrays may reference the just-dispatched fold — the latency cliff
    ``prefer_ready`` exists to avoid) and a promotion BEFORE the first
    publish would fail adopted queries instead of holding them."""
    while not primary_done.is_set() and not stop.is_set():
        primary_done.wait(0.05)
    return
    yield  # unreachable: makes this a lazy, closeable generator


class FailoverServer:
    """A primary/standby :class:`StreamServer` pair over one shared
    snapshot store.

    Construct and :meth:`start` it exactly like a ``StreamServer`` —
    ``submit``/``ask``/``snapshot``/``close`` route to whichever replica
    is active. ``monitor_s`` sets the liveness poll period (None
    disables the monitor; promotion is then manual via
    :meth:`promote`). All other keyword arguments are the
    ``StreamServer`` configuration, applied to BOTH replicas.
    """

    #: how long a MANUAL promotion waits for a still-alive primary
    #: worker to settle its in-flight batch before stealing it
    INFLIGHT_GRACE_S = 1.0

    def __init__(
        self,
        servable,
        source=None,
        *,
        monitor_s: Optional[float] = 0.02,
        store: Optional[SnapshotStore] = None,
        stats: Optional[ServingStats] = None,
        **server_kwargs,
    ):
        self.store = store or SnapshotStore()
        self.stats = stats or ServingStats()
        self._kwargs = dict(
            server_kwargs, store=self.store, stats=self.stats
        )
        self.primary = StreamServer(servable, source, **self._kwargs)
        self.standby = StreamServer(iter(()), None, **self._kwargs)
        # follower wiring: ingest stays the primary's job, so the
        # standby's stream-ended signal must BE the primary's (shared
        # event), and its own ingest thread must outlive the primary's
        # publishing instead of finishing instantly — see _follow_ingest
        self.standby._ingest_done = self.primary._ingest_done
        self.standby._servable = _follow_ingest(
            self.primary._ingest_done, self.standby._stop_ingest
        )
        self._active = self.primary
        self.promoted = False
        self.monitor_s = monitor_s
        self._plock = threading.Lock()
        self._monitor_stop = threading.Event()
        self._monitor_thread: Optional[threading.Thread] = None
        self._closed = False

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #
    def start(self) -> "FailoverServer":
        self.primary.start()
        if self.monitor_s is not None:
            self._monitor_thread = threading.Thread(
                target=self._monitor, name="stream-server-failover",
                daemon=True,
            )
            self._monitor_thread.start()
        return self

    def __enter__(self) -> "FailoverServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def publish_boot(self, payload: dict, watermark: int = 0) -> None:
        self.primary.publish_boot(payload, watermark)

    def _monitor(self) -> None:
        while not self._monitor_stop.wait(self.monitor_s):
            if self.promoted or self._closed:
                return
            p = self.primary
            if p._worker_thread is not None and not p.worker_alive():
                self.promote(reason="worker_death")
                return

    # ------------------------------------------------------------------ #
    # Promotion
    # ------------------------------------------------------------------ #
    def promote(self, reason: str = "manual") -> None:
        """Switch serving to the standby. Safe to call once; later calls
        are no-ops. The primary's admitted-but-unanswered queries are
        re-homed: entries past their deadline fail ``DeadlineExceeded``
        (they are late no matter who answers), the rest are adopted by
        the standby and re-answered from its newest snapshot with their
        original submit times and deadlines intact.

        Promotion LATENCY is first-class telemetry: the whole takeover
        (admission fence to active-replica switch) is timed into the
        ``serving.promotion_seconds`` histogram and, when tracing is
        on, a ``serving.promotion`` span — worker deaths were counted
        before this, but how long clients waited on the switch was
        invisible."""
        t_promo = time.perf_counter()
        with self._plock:
            if self.promoted or self._closed:
                return
            reg = get_registry()
            with _trace.span(
                "serving.promotion",
                {"reason": reason} if _trace.on() else None,
            ):
                reg.counter("serving.failover", reason=reason).inc()
                primary = self.primary
                # refuse stragglers at the primary's admission gate;
                # the flag flips under ITS lock so no submit can slip
                # between the queue steal below and the reroute of
                # self._active
                with primary._lock:
                    primary._closing = True
                    entries = list(primary._pending)
                    primary._pending.clear()
                self.standby.start()
                # the in-flight batch: if the primary worker is still
                # alive (a MANUAL switchover), it is mid-answer on
                # exactly these entries — adopting them too would
                # compute every query twice and double-record stats.
                # Give the worker a short grace to settle, then steal
                # whatever remains (the worker-death path skips the
                # wait entirely; for a wedged worker the futures'
                # done() guards make any late primary-side settle
                # harmless).
                deadline = time.monotonic() + self.INFLIGHT_GRACE_S
                while (primary.worker_alive() and primary._inflight
                       and time.monotonic() < deadline):
                    # the grace wait deliberately holds _plock: submit()
                    # and active MUST queue behind an in-flight
                    # promotion (their documented contract), and the
                    # wait is bounded by INFLIGHT_GRACE_S; probes use
                    # active_nowait to stay lock-free
                    time.sleep(0.001)  # graftlint: disable=GL009 (bounded grace wait; holding the promotion lock here IS the contract submit()/active wait on — active_nowait is the lock-free probe path)
                with primary._lock:
                    entries.extend(primary._inflight_entries)
                    primary._inflight = 0
                    primary._inflight_entries = []
                now = time.perf_counter()
                keep = []
                # entries keep their TRACE CONTEXT through adoption:
                # the standby's answer spans join the same trace the
                # client minted, so the merged timeline shows one story
                # spanning submit, death, and the promoted re-answer
                for q, f, t0, dl, ctx in entries:
                    if f.done():
                        continue
                    if dl is not None and now > dl:
                        StreamServer._expire(
                            q, f, t0, dl, "failed over after"
                        )
                        reg.counter("serving.failover_expired").inc()
                    else:
                        keep.append((q, f, t0, dl, ctx))
                self.standby._adopt(keep)
                if keep:
                    reg.counter(
                        "serving.failover_requeued"
                    ).inc(len(keep))
                self._active = self.standby
                self.promoted = True
            # client-visible takeover latency: admission fence to
            # active-replica switch (always on — a promotion is
            # operational truth, like every resilience event)
            reg.histogram("serving.promotion_seconds").observe(
                time.perf_counter() - t_promo
            )

    # ------------------------------------------------------------------ #
    # Query surface (routed to the active replica)
    # ------------------------------------------------------------------ #
    @property
    def active(self) -> StreamServer:
        with self._plock:
            return self._active

    @property
    def active_nowait(self) -> StreamServer:
        """The active replica WITHOUT waiting out an in-flight
        promotion (``active`` does, and promote() holds the lock
        through its in-flight grace wait): a liveness probe must
        answer immediately mid-failover, and the reference swap is
        atomic — momentarily stale is a correct liveness answer."""
        return self._active

    @property
    def role(self) -> str:
        """Which replica is serving: ``primary`` until a promotion,
        ``standby`` after — the label an external probe needs to tell
        a healthy standby takeover from normal operation."""
        return "standby" if self.promoted else "primary"

    def heartbeat_age_s(self) -> float:
        """The ACTIVE replica's worker-beat age (see
        ``StreamServer.heartbeat_age_s``); read without waiting out an
        in-flight promotion, for the same reason as ``active_nowait``."""
        return self.active_nowait.heartbeat_age_s()

    def submit(self, query: Query, **kw):
        srv = self.active
        try:
            return srv.submit(query, **kw)
        except RuntimeError as e:
            # possibly lost the race with a concurrent promotion: the
            # primary refuses as "closed" the moment promote() starts,
            # BEFORE the standby is ready. Taking the promotion lock
            # waits out any in-flight promote; if the active replica
            # changed, one re-route settles it (promotion is one-shot).
            # A genuinely closed replica set re-raises.
            if "closed" not in str(e) or self._closed:
                raise
            with self._plock:
                now = self._active
            if now is not srv:
                return now.submit(query, **kw)
            raise

    def ask(self, query: Query, timeout: Optional[float] = None,
            deadline_s: Optional[float] = None) -> Answer:
        return self.submit(query, deadline_s=deadline_s).result(timeout)

    def snapshot(self) -> Optional[PublishedSnapshot]:
        return self.store.latest()

    def metrics_endpoint(self, **kw):
        """Start a scrape endpoint wired to the replica set:
        ``/healthz`` reports the ACTIVE replica's liveness plus the
        promotion state. See ``StreamServer.metrics_endpoint``."""
        from ..obs.endpoint import MetricsEndpoint

        return MetricsEndpoint.for_server(self, **kw).start()

    def join(self, timeout: Optional[float] = None) -> None:
        self.primary.join(timeout)

    # ------------------------------------------------------------------ #
    # Shutdown
    # ------------------------------------------------------------------ #
    def close(self, timeout: float = 30.0) -> None:
        """Close both replicas (idempotent). The primary closes first so
        ingest stops at a window boundary; each replica answers its own
        admitted stragglers on the way down. ``timeout`` bounds the
        WHOLE close (GL008): the monitor join and both replica closes
        spend one shared budget, not a fresh copy each."""
        with self._plock:
            if self._closed:
                return
            self._closed = True
        deadline = time.monotonic() + float(timeout)

        def remaining() -> float:
            return max(0.0, deadline - time.monotonic())

        self._monitor_stop.set()
        if self._monitor_thread is not None:
            self._monitor_thread.join(remaining())
        errors = []
        for srv in (self.primary, self.standby):
            try:
                srv.close(remaining())
            except BaseException as e:
                errors.append(e)
        if errors:
            raise errors[0]
