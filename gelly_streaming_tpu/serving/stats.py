"""Serving metrics: per-query-class latency + staleness, as plain dicts.

The reference's design stance is that metrics are ordinary output
streams (``utils/profiling.py`` docstring); the serving tier keeps it:
no metrics server, no registry — :meth:`ServingStats.snapshot` returns a
plain dict and :meth:`ServingStats.stream` yields those dicts like any
other emission iterator. Percentiles reuse
:class:`~gelly_streaming_tpu.utils.profiling.StreamProfiler` (one per
query class; each answered query records as one "window").
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator

from ..utils.profiling import StreamProfiler, WindowStats


def _pct(sorted_xs: list, q: float) -> float:
    """Percentile over an ALREADY-SORTED sample list (the same
    nearest-rank rule as ``StreamProfiler.latency_percentile``)."""
    if not sorted_xs:
        return 0.0
    k = min(
        len(sorted_xs) - 1,
        max(0, int(round(q / 100 * (len(sorted_xs) - 1)))),
    )
    return sorted_xs[k]


class ServingStats:
    """Aggregates per-query-class latency histograms and staleness
    gauges. Thread-safe: the query worker records, any thread reads.

    Latency samples are bounded per class (``MAX_SAMPLES``; the oldest
    half drops when full, so percentiles describe the recent window) —
    a long-lived server must not grow a list per query forever. The
    staleness gauges and counts stay exact over the full lifetime."""

    #: per-class latency sample cap (drop-oldest-half on overflow)
    MAX_SAMPLES = 1 << 16

    def __init__(self):
        self._lock = threading.Lock()
        self._lat: Dict[str, StreamProfiler] = {}
        self._counts: Dict[str, int] = {}  # lifetime (samples are capped)
        self._stale_sum: Dict[str, int] = {}
        self._stale_max: Dict[str, int] = {}
        self._rejected = 0
        self._batches = 0

    # -- write side (query worker) ------------------------------------- #
    def record(self, qclass: str, seconds: float, staleness: int) -> None:
        """One answered query: wall seconds from submit to answer, and
        the answer's windows-behind-head staleness."""
        with self._lock:
            prof = self._lat.get(qclass)
            if prof is None:
                prof = self._lat[qclass] = StreamProfiler()
                self._stale_sum[qclass] = 0
                self._stale_max[qclass] = 0
                self._counts[qclass] = 0
            if len(prof.stats) >= self.MAX_SAMPLES:
                prof.stats = prof.stats[self.MAX_SAMPLES // 2 :]
            prof.record(WindowStats(len(prof.stats), seconds, None))
            self._counts[qclass] += 1
            self._stale_sum[qclass] += staleness
            self._stale_max[qclass] = max(
                self._stale_max[qclass], staleness
            )

    def record_batch(self) -> None:
        with self._lock:
            self._batches += 1

    def record_rejected(self) -> None:
        with self._lock:
            self._rejected += 1

    # -- read side ------------------------------------------------------ #
    def snapshot(self) -> dict:
        """Plain-dict gauge/histogram export::

            {"rejected": 0, "batches": 12,
             "queries": {"ConnectedQuery": {
                 "count": 10000, "p50_ms": 0.8, "p99_ms": 3.1,
                 "staleness_mean": 0.2, "staleness_max": 2}}}
        """
        # copy under the lock, sort OUTSIDE it: sorting 64k samples per
        # class while holding the lock would block the query worker's
        # record() (futures settle after it) for milliseconds — tail
        # latency injected by the act of measuring it
        with self._lock:
            out = {
                "rejected": self._rejected,
                "batches": self._batches,
                "queries": {},
            }
            copied = {
                qclass: (
                    [s.wall_seconds for s in prof.stats],
                    self._counts[qclass],
                    self._stale_sum[qclass],
                    self._stale_max[qclass],
                )
                for qclass, prof in self._lat.items()
            }
        for qclass, (xs, n, ssum, smax) in copied.items():
            xs.sort()  # one sort serves both percentiles
            out["queries"][qclass] = {
                "count": n,
                "p50_ms": _pct(xs, 50) * 1e3,
                "p99_ms": _pct(xs, 99) * 1e3,
                "staleness_mean": ssum / n if n else 0.0,
                "staleness_max": smax,
            }
        return out

    def stream(self) -> Iterator[dict]:
        """Unbounded metrics stream: each ``next()`` yields the current
        snapshot dict (pull-based, like every other emission stream)."""
        while True:
            yield self.snapshot()
