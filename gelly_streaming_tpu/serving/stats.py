"""Serving metrics: per-query-class latency + staleness, as plain dicts.

The reference's design stance is that metrics are ordinary output
streams (``utils/profiling.py`` docstring); the serving tier keeps it:
no metrics server — :meth:`ServingStats.snapshot` returns a plain dict
and :meth:`ServingStats.stream` yields those dicts like any other
emission iterator. Since ISSUE 3 the class is a VIEW over a
:class:`~gelly_streaming_tpu.obs.registry.MetricRegistry` rather than a
private dict-of-lists: the same counters/histograms surface through the
obs exporters (Prometheus text, JSONL event log), and a recorded event
log replays to an identical snapshot
(:func:`~gelly_streaming_tpu.obs.export.replay` +
:meth:`ServingStats.from_events` — the serving bench's honesty check).

Each ``ServingStats`` owns a PRIVATE registry by default so two servers
in one process never blend their counts; pass ``registry=`` to share or
to wrap a replayed one. Percentiles are the repo-wide nearest-rank rule
(:func:`~gelly_streaming_tpu.obs.registry.nearest_rank`) over a bounded
recent sample window (``MAX_SAMPLES``, drop-oldest-half — a long-lived
server must not grow a list per query forever); counts and staleness
sum/max stay exact over the full lifetime.
"""

from __future__ import annotations

from typing import Iterable, Iterator, Optional

from ..obs.registry import MetricRegistry


class ServingStats:
    """Per-query-class latency histograms + staleness gauges, backed by
    a metric registry. Thread-safe: the query worker records, any
    thread reads (instrument-level locks; snapshot sorts copies outside
    them, so reading percentiles never stalls ``record``)."""

    #: per-class latency sample cap (drop-oldest-half on overflow)
    MAX_SAMPLES = 1 << 16

    def __init__(self, registry: Optional[MetricRegistry] = None):
        self.registry = registry if registry is not None else MetricRegistry()
        self._rejected = self.registry.counter("serving.rejected")
        self._batches = self.registry.counter("serving.batches")

    # -- write side (query worker / server) ----------------------------- #
    def record(self, qclass: str, seconds: float, staleness: int,
               exemplar: Optional[str] = None) -> None:
        """One answered query: wall seconds from submit to answer, and
        the answer's windows-behind-head staleness. ``exemplar`` (a
        trace id, passed only when tracing is on) links the latency
        histogram's tail to a concrete trace — see
        :meth:`~gelly_streaming_tpu.obs.registry.Histogram.observe`."""
        self.registry.histogram(
            "serving.query_seconds", max_samples=self.MAX_SAMPLES,
            cls=qclass,
        ).observe(seconds, exemplar=exemplar)
        self.registry.histogram(
            "serving.staleness_windows", max_samples=self.MAX_SAMPLES,
            cls=qclass,
        ).observe(staleness)

    def record_batch(self) -> None:
        self._batches.inc()

    def record_rejected(self) -> None:
        self._rejected.inc()

    def set_pending(self, n: int) -> None:
        """Admission gauge: queries admitted but not yet answered."""
        self.registry.gauge("serving.pending").set(n)

    def record_drain(self, batch_size: int) -> None:
        """One worker sweep: how many pending queries coalesced into a
        single vectorized answer batch."""
        self.registry.histogram(
            "serving.batch_size", max_samples=self.MAX_SAMPLES
        ).observe(batch_size)

    # -- event-log plumbing --------------------------------------------- #
    def attach_sink(self, sink) -> None:
        """Mirror every stat mutation to ``sink.emit(event)`` (a
        :class:`~gelly_streaming_tpu.obs.export.JsonlSink` makes the
        stats replayable from their own log)."""
        self.registry.add_sink(sink)

    def detach_sink(self, sink) -> None:
        self.registry.remove_sink(sink)

    @classmethod
    def from_events(cls, events: Iterable[dict]) -> "ServingStats":
        """Rebuild stats from a recorded event log (see
        :func:`~gelly_streaming_tpu.obs.export.replay`); the returned
        view's :meth:`snapshot` equals the live run's."""
        from ..obs.export import replay

        return cls(registry=replay(events))

    # -- read side ------------------------------------------------------ #
    def snapshot(self) -> dict:
        """Plain-dict gauge/histogram export::

            {"rejected": 0, "batches": 12,
             "queries": {"ConnectedQuery": {
                 "count": 10000, "p50_ms": 0.8, "p99_ms": 3.1,
                 "staleness_mean": 0.2, "staleness_max": 2}}}
        """
        out = {
            "rejected": int(self._rejected.value),
            "batches": int(self._batches.value),
            "queries": {},
        }
        for labels, lat in self.registry.find("serving.query_seconds"):
            qclass = labels["cls"]
            stal = self.registry.histogram(
                "serving.staleness_windows", max_samples=self.MAX_SAMPLES,
                cls=qclass,
            )
            n = lat.count
            out["queries"][qclass] = {
                "count": n,
                "p50_ms": lat.percentile(50) * 1e3,
                "p99_ms": lat.percentile(99) * 1e3,
                "staleness_mean": stal.sum / n if n else 0.0,
                "staleness_max": int(stal.max),
            }
        return out

    def stream(self) -> Iterator[dict]:
        """Unbounded metrics stream: each ``next()`` yields the current
        snapshot dict (pull-based, like any other emission iterator)."""
        while True:
            yield self.snapshot()
