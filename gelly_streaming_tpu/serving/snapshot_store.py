"""Wait-free snapshot publication: the serving stack's write/read split.

The ingest loop must never block on readers and readers must never block
on ingest — the same discipline as the producer loop's zero-D2H rule
(``core/pipeline.py``). The contract here:

- A snapshot is an IMMUTABLE :class:`PublishedSnapshot`: payload arrays
  are never mutated after publish. The carries make this free — JAX
  updates are functional, so each window's fold allocates a fresh device
  buffer and the previous window's buffer stays alive for any reader
  still holding it (the same property that makes per-window lazy
  emissions valid snapshots, ``summaries/forest.py``).
- Publication is ONE reference assignment. CPython guarantees attribute
  stores are atomic under the GIL, so a reader either sees the old
  snapshot or the new one, never a torn mix — the double-buffer swap of
  a classic seqlock without the retry loop, because the buffers behind
  the references are frozen.
- Readers call :meth:`SnapshotStore.latest` — one attribute read, no
  lock, O(1) regardless of writer activity. The store's lock exists only
  for :meth:`wait_for` (condition-variable sleeps of readers who want a
  *newer* snapshot than the current one); the writer grabs it just to
  notify, after the swap is already visible.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Mapping, Optional

from ..obs.registry import get_registry


def _payload_ready(payload) -> bool:
    """True when every array in the payload has finished computing
    (host arrays and objects without ``is_ready`` count as ready)."""
    for v in payload.values():
        ready = getattr(v, "is_ready", None)
        if ready is not None:
            try:
                if not ready():
                    return False
            except Exception:
                # a broken is_ready probe must never break a read —
                # the value counts as ready — but it is evidence the
                # payload contract is off, so it stays visible
                get_registry().counter(
                    "serving.swallowed", site="payload_ready_probe"
                ).inc()
    return True


@dataclass(frozen=True)
class PublishedSnapshot:
    """One published summary state.

    ``payload`` is a workload-defined mapping (see the ``servable()``
    adapters) whose arrays must never be mutated after publish. The one
    non-array member is the ``vdict`` entry: the LIVE vertex dictionary,
    which is append-only (existing raw->compact mappings never change)
    and whose lookup paths are safe against concurrent ingest (native
    mutex / atomic index snapshot) — a reader may see a few ids newer
    than the snapshot's tables, which the engines treat as unseen-or-
    self-rooted, never inconsistent.
    ``window`` is the index of the last window folded in (``-1`` for a
    checkpoint boot snapshot published before any live window).
    ``watermark`` is a monotone progress counter — cumulative edges or
    events folded when the servable can count them cheaply, else the
    window index — so staleness is meaningful even across restores.
    """

    payload: Mapping[str, Any]
    window: int
    watermark: int
    version: int
    published_at: float = field(default_factory=time.monotonic)


class SnapshotStore:
    """Single-writer, many-reader snapshot cell.

    The writer (the server's ingest thread) calls :meth:`publish` once
    per window; any number of reader threads call :meth:`latest`
    wait-free. ``version`` increases by one per publish, so readers can
    detect progress without comparing payloads.
    """

    #: how many recent snapshots stay reachable for ``prefer_ready``
    #: reads (beyond the newest); the windows-behind-head staleness a
    #: latency-preferring reader can be handed is bounded by this
    READY_LOOKBACK = 3

    def __init__(self):
        self._current: Optional[PublishedSnapshot] = None
        self._recent: tuple = ()  # newest-first, immutable (atomic swap)
        self._cond = threading.Condition()
        self._closed = False

    # -- read side ----------------------------------------------------- #
    def latest(self, prefer_ready: bool = False) -> Optional[PublishedSnapshot]:
        """The newest published snapshot (or None before the first
        publish). One atomic reference read; never blocks.

        ``prefer_ready=True`` trades bounded staleness for latency: it
        returns the newest snapshot whose payload arrays have finished
        computing (``jax.Array.is_ready``), looking back at most
        ``READY_LOOKBACK`` windows. The head snapshot references the
        JUST-DISPATCHED window's async output — a reader that insists on
        it blocks until the fold pipeline catches up, while the window
        before is typically already materialized."""
        if not prefer_ready:
            return self._current
        recent = self._recent
        for snap in recent:
            if _payload_ready(snap.payload):
                return snap
        return self._current

    @staticmethod
    def payload_ready(payload) -> bool:
        return _payload_ready(payload)

    def head_window(self) -> int:
        """Window index of the newest snapshot; -2 before any publish
        (so a boot snapshot's ``-1`` still reads as ahead of nothing)."""
        snap = self._current
        return -2 if snap is None else snap.window

    def wait_for(
        self, min_version: int = 1, timeout: Optional[float] = None
    ) -> Optional[PublishedSnapshot]:
        """Block until a snapshot with ``version >= min_version`` exists
        (or the store closes / the timeout lapses); returns the newest
        snapshot either way. Readers that only want *some* snapshot pass
        the default ``min_version=1``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                snap = self._current
                if snap is not None and snap.version >= min_version:
                    return snap
                if self._closed:
                    return snap
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return snap
                self._cond.wait(remaining)

    # -- write side ---------------------------------------------------- #
    def publish(
        self, payload: Mapping[str, Any], window: int, watermark: int
    ) -> PublishedSnapshot:
        """Swap in a new snapshot and wake waiters. The assignment to
        ``_current`` IS the publication point; the lock below only
        guards the condition notify."""
        prev = self._current
        snap = PublishedSnapshot(
            payload=payload,
            window=window,
            watermark=watermark,
            version=1 if prev is None else prev.version + 1,
        )
        # both swaps are single reference assignments (atomic under the
        # GIL); _recent is an immutable tuple rebuilt per publish
        self._recent = (snap, *self._recent)[: self.READY_LOOKBACK + 1]
        self._current = snap
        with self._cond:
            self._cond.notify_all()
        return snap

    def close(self) -> None:
        """Release any ``wait_for`` sleepers; the last snapshot stays
        readable (a closed server still answers from its final state)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
