"""Wait-free snapshot publication: the serving stack's write/read split.

The ingest loop must never block on readers and readers must never block
on ingest — the same discipline as the producer loop's zero-D2H rule
(``core/pipeline.py``). The contract here:

- A snapshot is an IMMUTABLE :class:`PublishedSnapshot`: payload arrays
  are never mutated after publish. The carries make this free — JAX
  updates are functional, so each window's fold allocates a fresh device
  buffer and the previous window's buffer stays alive for any reader
  still holding it (the same property that makes per-window lazy
  emissions valid snapshots, ``summaries/forest.py``).
- Publication is ONE reference assignment. CPython guarantees attribute
  stores are atomic under the GIL, so a reader either sees the old
  snapshot or the new one, never a torn mix — the double-buffer swap of
  a classic seqlock without the retry loop, because the buffers behind
  the references are frozen.
- Readers call :meth:`SnapshotStore.latest` — one attribute read, no
  lock, O(1) regardless of writer activity. The store's lock exists only
  for :meth:`wait_for` (condition-variable sleeps of readers who want a
  *newer* snapshot than the current one); the writer grabs it just to
  notify, after the swap is already visible.
"""

from __future__ import annotations

import itertools
import os
import pickle
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Iterator, Mapping, Optional, Tuple

from ..obs.registry import get_registry


def _payload_ready(payload) -> bool:
    """True when every array in the payload has finished computing
    (host arrays and objects without ``is_ready`` count as ready)."""
    for v in payload.values():
        ready = getattr(v, "is_ready", None)
        if ready is not None:
            try:
                if not ready():
                    return False
            except Exception:
                # a broken is_ready probe must never break a read —
                # the value counts as ready — but it is evidence the
                # payload contract is off, so it stays visible
                get_registry().counter(
                    "serving.swallowed", site="payload_ready_probe"
                ).inc()
    return True


@dataclass(frozen=True)
class PublishedSnapshot:
    """One published summary state.

    ``payload`` is a workload-defined mapping (see the ``servable()``
    adapters) whose arrays must never be mutated after publish. The one
    non-array member is the ``vdict`` entry: the LIVE vertex dictionary,
    which is append-only (existing raw->compact mappings never change)
    and whose lookup paths are safe against concurrent ingest (native
    mutex / atomic index snapshot) — a reader may see a few ids newer
    than the snapshot's tables, which the engines treat as unseen-or-
    self-rooted, never inconsistent.
    ``window`` is the index of the last window folded in (``-1`` for a
    checkpoint boot snapshot published before any live window).
    ``watermark`` is a monotone progress counter — cumulative edges or
    events folded when the servable can count them cheaply, else the
    window index — so staleness is meaningful even across restores.
    ``epoch`` is the publishing STORE's process-unique nonce: version
    numbers restart from 1 when a store is rebuilt (a promoted standby,
    a restarted replica), so any cache keyed on version alone can serve
    a stale entry across a store swap at a coincidentally-equal
    version. Caches key on ``(epoch, version)`` instead; 0 marks a
    hand-built snapshot that never went through a store.
    ``event_ts`` is the EVENT-TIME watermark the summaries were built
    at (``-1`` when the pipeline carries no event time) — the stamp
    answers forward so a consumer can tell "how far behind the world"
    an answer is, next to ``staleness``'s "how far behind the head".
    ``boot`` is the store's CROSS-PROCESS lineage nonce (ISSUE 20):
    ``epoch`` is process-local, so a snapshot-pinned transaction
    talking through the wire needs a stamp that survives serialization
    and distinguishes a restarted store whose version counter happens
    to pass the pinned number. A standby following a mirror ADOPTS the
    primary's boot, so promotion preserves the lineage a pin names;
    a cold restart mints a new one and honestly expires old pins.
    """

    payload: Mapping[str, Any]
    window: int
    watermark: int
    version: int
    published_at: float = field(default_factory=time.monotonic)
    epoch: int = 0
    event_ts: int = -1
    boot: str = ""


class SnapshotStore:
    """Single-writer, many-reader snapshot cell.

    The writer (the server's ingest thread) calls :meth:`publish` once
    per window; any number of reader threads call :meth:`latest`
    wait-free. ``version`` increases by one per publish, so readers can
    detect progress without comparing payloads.
    """

    #: how many recent snapshots stay reachable for ``prefer_ready``
    #: reads (beyond the newest); the windows-behind-head staleness a
    #: latency-preferring reader can be handed is bounded by this
    READY_LOOKBACK = 3

    #: process-wide epoch allocator: each store instance gets a distinct
    #: nonce so (epoch, version) pairs never collide across store swaps
    _epochs = itertools.count(1)

    def __init__(self, *, retention: Optional[int] = None):
        self.epoch = next(SnapshotStore._epochs)
        # cross-process lineage nonce (see PublishedSnapshot.boot);
        # adopted wholesale when a publish carries the upstream boot
        self.boot = os.urandom(4).hex()
        # how many snapshots BEHIND the head stay version-addressable
        # for pinned transactional reads; defaults to the prefer_ready
        # lookback so the knob never shrinks what latest() could serve
        self.retention = (
            self.READY_LOOKBACK if retention is None
            else max(1, int(retention))
        )
        self._current: Optional[PublishedSnapshot] = None
        self._recent: tuple = ()  # newest-first, immutable (atomic swap)
        self._cond = threading.Condition()
        self._closed = False
        self._listeners: tuple = ()  # immutable, swapped whole

    # -- read side ----------------------------------------------------- #
    def latest(self, prefer_ready: bool = False) -> Optional[PublishedSnapshot]:
        """The newest published snapshot (or None before the first
        publish). One atomic reference read; never blocks.

        ``prefer_ready=True`` trades bounded staleness for latency: it
        returns the newest snapshot whose payload arrays have finished
        computing (``jax.Array.is_ready``), looking back at most
        ``READY_LOOKBACK`` windows. The head snapshot references the
        JUST-DISPATCHED window's async output — a reader that insists on
        it blocks until the fold pipeline catches up, while the window
        before is typically already materialized."""
        if not prefer_ready:
            return self._current
        recent = self._recent
        for snap in recent:
            if _payload_ready(snap.payload):
                return snap
        return self._current

    @staticmethod
    def payload_ready(payload) -> bool:
        return _payload_ready(payload)

    def at_version(
        self, version: int, boot: Optional[str] = None
    ) -> PublishedSnapshot:
        """The snapshot PINNED at ``(version, boot)`` — the transactional
        read path (ISSUE 20). Returns the exact version from the
        retention ring or raises a counted, typed
        :class:`~gelly_streaming_tpu.serving.txn.TxnSnapshotExpired`;
        it NEVER substitutes a fresher snapshot — a transaction is told
        its snapshot is gone, not quietly handed different data.

        ``boot`` (when given) must match the snapshot's lineage nonce:
        version numbers restart across cold store swaps, so a
        numerically-equal version from a different lineage is a
        different graph and expires the pin (``kind="lineage"``)."""
        from .txn import TxnSnapshotExpired

        version = int(version)
        head = self._current
        for snap in self._recent:
            if snap.version == version:
                if boot and snap.boot and snap.boot != boot:
                    break  # same number, different lineage: not it
                return snap
        if boot and boot != self.boot:
            kind = "lineage"
            msg = (f"pinned v{version} names lineage {boot!r}; this "
                   f"store is lineage {self.boot!r} (restarted?)")
        elif head is None or version > head.version:
            kind = "ahead"
            msg = (f"pinned v{version} is ahead of this store "
                   f"(head v{0 if head is None else head.version})")
        else:
            kind = "ring_slid"
            msg = (f"pinned v{version} slid out of the retention ring "
                   f"(oldest retained v{self.oldest_retained()}, "
                   f"retention {self.retention})")
        get_registry().counter("txn.snapshot_expired", reason=kind).inc()
        raise TxnSnapshotExpired(msg, kind=kind)

    def oldest_retained(self) -> int:
        """Oldest version still version-addressable (``-1`` before any
        publish) — the health surface's oldest-pinned-readable stamp."""
        recent = self._recent
        return recent[-1].version if recent else -1

    def ring_depth(self) -> int:
        """How many snapshots the retention ring currently holds."""
        return len(self._recent)

    def head_window(self) -> int:
        """Window index of the newest snapshot; -2 before any publish
        (so a boot snapshot's ``-1`` still reads as ahead of nothing)."""
        snap = self._current
        return -2 if snap is None else snap.window

    def wait_for(
        self, min_version: int = 1, timeout: Optional[float] = None
    ) -> Optional[PublishedSnapshot]:
        """Block until a snapshot with ``version >= min_version`` exists
        (or the store closes / the timeout lapses); returns the newest
        snapshot either way. Readers that only want *some* snapshot pass
        the default ``min_version=1``."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                snap = self._current
                if snap is not None and snap.version >= min_version:
                    return snap
                if self._closed:
                    return snap
                remaining = (
                    None if deadline is None
                    else deadline - time.monotonic()
                )
                if remaining is not None and remaining <= 0:
                    return snap
                self._cond.wait(remaining)

    # -- write side ---------------------------------------------------- #
    def publish(
        self, payload: Mapping[str, Any], window: int, watermark: int,
        event_ts: int = -1, version: Optional[int] = None,
        boot: Optional[str] = None,
    ) -> PublishedSnapshot:
        """Swap in a new snapshot and wake waiters. The assignment to
        ``_current`` IS the publication point; the lock below only
        guards the condition notify.

        ``version`` overrides the monotone counter for ONE publish —
        the restart-adoption boot path republishes the mirrored
        snapshot under its original version so downstream delta
        baselines (routers, the persisted pull ring) stay valid
        instead of watching versions restart from 1. Later publishes
        continue from the override. ``boot`` likewise ADOPTS an
        upstream store's lineage nonce: a standby mirroring its
        primary publishes under the primary's boot, so a pinned
        ``(version, boot)`` survives promotion; absent, the store
        keeps its own lineage."""
        prev = self._current
        if version is None:
            version = 1 if prev is None else prev.version + 1
        if boot is not None and boot:
            self.boot = str(boot)
        snap = PublishedSnapshot(
            payload=payload,
            window=window,
            watermark=watermark,
            version=int(version),
            epoch=self.epoch,
            event_ts=int(event_ts),
            boot=self.boot,
        )
        # both swaps are single reference assignments (atomic under the
        # GIL); _recent is an immutable tuple rebuilt per publish
        keep = max(self.retention, self.READY_LOOKBACK) + 1
        self._recent = (snap, *self._recent)[:keep]
        self._current = snap
        with self._cond:
            self._cond.notify_all()
        for cb in self._listeners:
            try:
                cb(snap)
            except Exception:
                # a listener failure (a full disk under the snapshot
                # mirror, say) must never take the ingest thread down
                # with it — the local snapshot is already published
                get_registry().counter(
                    "serving.swallowed", site="publish_listener"
                ).inc()
        return snap

    def add_listener(self, cb) -> None:
        """Call ``cb(snapshot)`` on the WRITER's thread after every
        publish — the hook the cross-process failover mirror uses to
        persist each snapshot. Listeners run inline with ingest, so
        they must be cheap or throttle themselves; a raising listener
        is counted and skipped, never fatal."""
        self._listeners = (*self._listeners, cb)

    def remove_listener(self, cb) -> None:
        self._listeners = tuple(x for x in self._listeners if x is not cb)

    def close(self) -> None:
        """Release any ``wait_for`` sleepers; the last snapshot stays
        readable (a closed server still answers from its final state)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()


# --------------------------------------------------------------------- #
# Cross-process half: the shared snapshot directory
# --------------------------------------------------------------------- #
# A standby serving BINARY cannot share an in-memory store with its
# primary; what it can share is a cluster store — a shared directory
# (the historical shape) or the exchange daemon, either way reached
# through a :class:`~gelly_streaming_tpu.fabric.Transport`. The mirror
# persists each published snapshot with the checkpoint commit
# discipline (the transport's atomic put of a CRC-framed container —
# a kill at any byte leaves the previous snapshot fully loadable), and
# the follower turns that store back into a ``(payload, watermark)``
# emission iterator a standby ``StreamServer`` ingests like any other
# servable. Torn or bit-rotted artifacts are REJECTED (counted,
# warned) and the follower falls back to the newest older snapshot —
# the standby never serves a half-written table.

#: snapshot tag prefix in a shared serving store
SNAP_PREFIX = "snap.v"


def _snap_tag(version: int) -> str:
    return f"{SNAP_PREFIX}{version:010d}.bin"


def _snap_path(dirpath: str, version: int) -> str:
    """The shared-dir backend's on-disk name for a snapshot version —
    kept for the recovery tests that corrupt artifacts in place."""
    return os.path.join(dirpath, _snap_tag(version))


def _snap_versions(target) -> list:
    """Committed snapshot versions in the store, newest first."""
    from ..fabric import as_transport

    out = []
    for n in as_transport(target).list(SNAP_PREFIX):
        if n.endswith(".bin"):
            try:
                out.append(int(n[len(SNAP_PREFIX):-len(".bin")]))
            except ValueError:
                continue
    out.sort(reverse=True)
    return out


class SnapshotMirror:
    """Primary-side disk mirror: persist every Nth published snapshot.

    Attach via ``store.add_listener(mirror)``; runs on the ingest
    thread, so ``every`` throttles the disk cost for fast windows. With
    ``every > 1`` up to ``every - 1`` TRAILING windows are not on disk
    at any instant — a primary killed mid-stride fails over to the
    newest committed stride, the bounded-staleness trade the knob buys.
    :meth:`flush` closes the gap at the points where it can be closed:
    the replica runtime calls it when ingest ENDS and on clean close,
    so the final published snapshot always lands then. Payload values
    must be picklable — numpy/JAX arrays are materialized to host
    numpy at write time; a payload that cannot be pickled (an exotic
    vertex dict holding native state) cannot be disk-mirrored and
    should publish a host-shaped payload instead.

    ``dirpath`` is any store-backed cluster
    :class:`~gelly_streaming_tpu.fabric.Transport`; a bare path keeps
    the historical shared-directory layout byte-identical.
    """

    def __init__(self, dirpath, *, keep: int = 2, every: int = 1):
        from ..fabric import as_transport

        self.dirpath = dirpath
        self.transport = as_transport(dirpath)
        self.keep = max(1, int(keep))
        self.every = max(1, int(every))
        self._written = -1  # newest version committed by THIS mirror

    def __call__(self, snap: PublishedSnapshot) -> None:
        if snap.version % self.every == 0:
            self.write(snap)

    def flush(self, store: "SnapshotStore") -> None:
        """Commit the store's newest snapshot if the stride skipped it.
        Idempotent per version; a concurrent listener write of the same
        version is harmless (same content, atomic replace)."""
        snap = store.latest()
        if snap is not None and snap.version > self._written:
            self.write(snap)

    def write(self, snap: PublishedSnapshot) -> str:
        """Commit one snapshot atomically; returns the committed path."""
        import numpy as np

        from ..resilience import integrity

        payload = {}
        for k, v in snap.payload.items():
            # arrays go to host now (a disk mirror of a device buffer
            # is a copy either way); non-array values (the vdict) ride
            # pickle as-is
            payload[k] = np.asarray(v) if hasattr(v, "shape") else v
        doc = {
            "window": snap.window,
            "watermark": snap.watermark,
            "version": snap.version,
            "boot": snap.boot,
            "payload": payload,
        }
        data = integrity.wrap_checksummed(pickle.dumps(doc, protocol=4))
        tag = _snap_tag(snap.version)
        self.transport.put(tag, data, overwrite=True)
        if snap.version > self._written:
            self._written = snap.version
        self._prune()
        return self.transport.describe(tag)

    def _prune(self) -> None:
        for v in _snap_versions(self.transport)[self.keep:]:
            if not self.transport.delete(_snap_tag(v)):
                # already gone (swept by an earlier prune's race) — the
                # store converges either way; visible, not fatal
                get_registry().counter(
                    "serving.swallowed", site="snapshot_prune"
                ).inc()


def load_newest_snapshot(
    dirpath, *, newer_than: int = -1
) -> Optional[dict]:
    """The newest COMMITTED-AND-VALID snapshot doc in the store with
    ``version > newer_than`` (or None). Torn/corrupt artifacts are
    rejected through
    :func:`~gelly_streaming_tpu.resilience.integrity.record_rejection`
    and the scan falls back to the next older one — the same
    newest-first-with-fallback discipline as barrier restore."""
    from ..fabric import as_transport
    from ..resilience import integrity
    from ..resilience.errors import CheckpointCorrupt

    tr = as_transport(dirpath)
    for v in _snap_versions(tr):
        if v <= newer_than:
            return None
        tag = _snap_tag(v)
        data = tr.get(tag)
        if data is None:
            continue  # pruned between list and read: benign race
        origin = tr.describe(tag)
        try:
            doc = pickle.loads(
                integrity.unwrap_checksummed(
                    data, origin=f"serving snapshot {origin}"
                )
            )
        except (CheckpointCorrupt, OSError, pickle.UnpicklingError,
                EOFError, AttributeError) as e:
            integrity.record_rejection(origin, repr(e))
            continue
        if doc.get("payload") is None:
            integrity.record_rejection(origin, "no payload in snapshot doc")
            continue
        # geometry validation (GL011 symmetry with SnapshotMirror.write:
        # every committed key is consumed here): a doc missing its
        # window/watermark/version ints is not a snapshot this follower
        # can sequence — reject it visibly and fall back
        if not (isinstance(doc.get("window"), int)
                and isinstance(doc.get("watermark"), int)
                and isinstance(doc.get("version"), int)):
            integrity.record_rejection(
                origin, "snapshot doc geometry keys missing or invalid")
            continue
        return doc
    return None


def follow_snapshots(
    dirpath,
    stop: threading.Event,
    *,
    poll_s: float = 0.05,
    carry_version: bool = False,
) -> Iterator[Tuple[dict, int]]:
    """Standby-side emission iterator over a shared snapshot store:
    yields ``(payload, watermark)`` once per NEW committed snapshot
    version until ``stop`` is set. Plug it into a ``StreamServer`` as a
    bare servable (``source=None``) and the standby serves whatever the
    primary last mirrored — including after the primary dies (the
    keep-serving-from-final-state contract, now across processes).

    ``carry_version=True`` smuggles the PRIMARY's version and boot
    lineage through the payload (``snap_version``/``snap_boot`` keys,
    popped by the ingest loop before publish): the standby's ring then
    mirrors the primary's stamps, so a promotion answers pinned
    transactional reads from the mirrored ring instead of restarting
    versions from 1 (which would both expire every pin and trip the
    router's restart-adoption slack)."""
    from ..fabric import as_transport

    tr = as_transport(dirpath)
    last = -1
    while not stop.is_set():
        doc = load_newest_snapshot(tr, newer_than=last)
        if doc is None:
            stop.wait(poll_s)
            continue
        last = int(doc["version"])
        payload = doc["payload"]
        if carry_version and isinstance(payload, dict):
            payload = dict(
                payload,
                snap_version=last,
                snap_boot=str(doc.get("boot", "")),
            )
        yield payload, int(doc["watermark"])
