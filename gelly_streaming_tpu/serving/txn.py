"""Snapshot-pinned read transactions (ISSUE 20, ROADMAP 2(a)).

Every tier until now answered from whatever snapshot was freshest: a
client issuing two related queries could observe two different graph
versions, and a failover or live split mid-sequence made the skew
arbitrary. This module is the client-visible half of the fix:

- A :class:`TxnContext` pins a per-shard ``{shard: (version, boot)}``
  VECTOR from the stamps ordinary reply frames already carry
  (``Answer.version`` + the ISSUE 20 ``shard``/``boot`` trailers) — no
  extra round trip. The first answer a transaction sees from a shard
  pins that shard; every later read the context rides is answered AT
  the pinned snapshot or fails honestly.
- Expiry is TYPED and counted, never silent: a pinned version that
  slid out of the serving ring (``SnapshotStore.at_version``), a
  peer that ignored the pin (detected via the reply stamp), or a
  failover that lost the pinned state all raise
  :class:`TxnSnapshotExpired` with a ``kind`` tag — a transaction is
  told its snapshot is gone, it is never quietly handed a fresher
  answer.
- ``boot`` is the snapshot store's LINEAGE nonce: version numbers
  restart across store swaps, so a pin is only satisfied by the same
  (version, lineage) pair — a cold-restarted shard whose counter
  happens to pass the pinned number can never coincidentally satisfy
  it (the PR 12 restart rule RESETS a pin, it does not feed it).

The wire codec (:func:`encode_txn`/:func:`decode_txn`) is tolerant in
both directions: v1 peers ignore the ``txn`` REQ field entirely (the
client detects the unpinned answer from the reply stamp), and a decoder
handed garbage reads it as "no transaction" rather than dying.

A transaction's deadline is ONE budget (GL008): pinned at
:class:`TxnContext` construction and spent across begin, every read,
and the expiry sweeps — a retry never grants itself a fresh clock.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from ..obs.registry import get_registry


class TxnSnapshotExpired(RuntimeError):
    """A pinned snapshot is no longer readable. ``kind`` names why:

    - ``ring_slid``: the pinned version aged out of the serving ring
      under sustained publishing (the retention bound).
    - ``ahead``: the pin is newer than anything this store published —
      the pin came from a different incarnation's future.
    - ``lineage``: the store's boot lineage changed (cold restart /
      fresh store); a numerically-equal version is NOT the snapshot.
    - ``unaware_peer``: the answering peer ignored the pin (a v1
      server) — detected from the reply stamp, failed honestly.
    - ``failover``: a promoted standby's mirrored ring does not hold
      the pinned state (counted ``txn.failover_expired``).

    Always counted at the raise site; never replaced by a silently
    fresher answer.
    """

    def __init__(self, msg: str, *, kind: str = "expired"):
        super().__init__(msg)
        self.kind = kind


class PinnedQuery:
    """Server-side wrapper marrying one query to its pinned snapshot.

    Rides the serving worker's pending entries in the query slot so the
    answer path can group a drained sweep by pin and answer each group
    from ``SnapshotStore.at_version`` — the wrapper never crosses the
    wire (the REQ ``txn`` field does) and never reaches an engine
    kernel (the worker unwraps ``.q``)."""

    __slots__ = ("q", "version", "boot")

    def __init__(self, q, version: int, boot: str = ""):
        self.q = q
        self.version = int(version)
        self.boot = str(boot)

    def __repr__(self) -> str:  # surfaces in deadline/expiry messages
        return (f"PinnedQuery({type(self.q).__name__}"
                f"@v{self.version})")


# --------------------------------------------------------------------- #
# Wire codec (GL011 pair: every key written here is read back below)
# --------------------------------------------------------------------- #
def encode_txn(txn_id: str, *, pin: Optional[tuple] = None,
               vec: Optional[dict] = None) -> dict:
    """Pack a transaction's identity + pins as the REQ ``txn`` field.

    ``pin`` is the single ``(version, boot)`` a shard-directed
    sub-request carries (the router's per-owner form); ``vec`` is the
    full ``{shard: (version, boot)}`` vector a client sends a router.
    Either, both, or neither may be present — a bare id announces a
    transaction that has not pinned anything yet (its first answers do
    the pinning)."""
    doc: dict = {"id": str(txn_id)}
    if pin is not None:
        doc["pin"] = [int(pin[0]), str(pin[1])]
    if vec is not None:
        doc["vec"] = {
            str(int(s)): [int(v), str(b)] for s, (v, b) in vec.items()
        }
    return doc


def decode_txn(doc) -> Optional[dict]:
    """Decode a REQ ``txn`` field into ``{"id", "pin", "vec"}``
    (``pin`` a ``(version, boot)`` tuple or None, ``vec`` a
    ``{int shard: (version, boot)}`` dict or None).

    Tolerant by contract: None/garbage decodes as None ("no
    transaction", counted ``rpc.malformed{kind=txn}`` when the field
    was present but unreadable) — a malformed pin must degrade to an
    unpinned request the CLIENT then fails via the reply stamp, never
    to a dead handler thread."""
    if doc is None:
        return None
    try:
        if not isinstance(doc, dict):
            raise TypeError("txn field must be a dict")
        out: dict = {"id": str(doc.get("id", "")), "pin": None,
                     "vec": None}
        raw = doc.get("pin")
        if raw is not None:
            out["pin"] = (
                int(raw[0]), str(raw[1]) if len(raw) > 1 else "",
            )
        rawv = doc.get("vec")
        if rawv is not None:
            vec: Dict[int, Tuple[int, str]] = {}
            for k, item in rawv.items():
                vec[int(k)] = (
                    int(item[0]),
                    str(item[1]) if len(item) > 1 else "",
                )
            out["vec"] = vec
        return out
    except (TypeError, ValueError, KeyError, IndexError):
        get_registry().counter("rpc.malformed", kind="txn").inc()
        return None


# --------------------------------------------------------------------- #
# Client-side transaction context
# --------------------------------------------------------------------- #
class TxnContext:
    """One snapshot-pinned read transaction.

    Pass it to :meth:`~gelly_streaming_tpu.serving.client.RpcClient`
    submit/ask calls (``txn=ctx``): the client rides the context's
    vector on every REQ frame and observes every OK answer back into
    it, so the FIRST answer from each shard pins that shard and every
    later read is answered at the pinned snapshot or raises
    :class:`TxnSnapshotExpired`. The vector is captured from ordinary
    reply stamps — beginning a transaction costs no extra round trip.

    ``deadline_s`` is the transaction's ONE total budget (GL008): it is
    pinned to the wall clock here, and every read issued under the
    context spends what REMAINS of it — begin, reads, retries, and
    expiry sweeps share the single clock."""

    def __init__(self, *, deadline_s: Optional[float] = None):
        self.id = os.urandom(6).hex()
        self._vec: Dict[int, Tuple[int, str]] = {}
        self._lock = threading.Lock()
        self._deadline = (
            None if deadline_s is None
            else time.monotonic() + float(deadline_s)
        )
        get_registry().counter("txn.begin").inc()
        note_txn(self.id)

    def remaining_s(self) -> Optional[float]:
        """What is left of the transaction's one deadline budget (None
        when unbounded); never negative."""
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    def observe(self, answer) -> None:
        """Pin from one OK answer's reply stamp: the first answer seen
        from a shard pins ``(version, boot)`` for it; later answers
        from an already-pinned shard are ignored (they are either the
        pinned snapshot's own stamps or the reason an expiry raised)."""
        shard = int(getattr(answer, "shard", -1))
        boot = str(getattr(answer, "boot", ""))
        version = int(getattr(answer, "version", 0))
        if version <= 0 or not boot:
            # a v1 peer's unstamped answer pins nothing, and neither
            # does a router-merged cross-shard answer (shard=-1,
            # boot="", version=summed) — pins are per-shard lineage
            # facts; the MERGED classes pin through the vector the
            # per-shard answers already built
            return
        with self._lock:
            if shard not in self._vec:
                self._vec[shard] = (version, boot)
                note_txn(self.id)

    def vector(self) -> Dict[int, Tuple[int, str]]:
        """A copy of the pinned ``{shard: (version, boot)}`` vector."""
        with self._lock:
            return dict(self._vec)

    def pin_for(self, shard: int) -> Optional[Tuple[int, str]]:
        with self._lock:
            return self._vec.get(int(shard))

    @property
    def pinned(self) -> bool:
        with self._lock:
            return bool(self._vec)

    def wire_doc(self) -> dict:
        """The REQ ``txn`` field for this context's current vector."""
        return encode_txn(self.id, vec=self.vector())


# --------------------------------------------------------------------- #
# Active-transaction tracker (the /healthz "active" gauge)
# --------------------------------------------------------------------- #
class ActiveTxns:
    """Recently-seen transaction ids, TTL-pruned: the health surface's
    per-replica active-transaction count. Bounded both ways (cap +
    TTL) — a tracker must never become the leak it exists to report."""

    TTL_S = 30.0
    CAP = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._seen: "OrderedDict[str, float]" = OrderedDict()

    def note(self, txn_id: str) -> None:
        if not txn_id:
            return
        now = time.monotonic()
        with self._lock:
            self._seen[txn_id] = now
            self._seen.move_to_end(txn_id)
            while len(self._seen) > self.CAP:
                self._seen.popitem(last=False)

    def count(self) -> int:
        cutoff = time.monotonic() - self.TTL_S
        with self._lock:
            stale = [k for k, ts in self._seen.items() if ts < cutoff]
            for k in stale:
                del self._seen[k]
            return len(self._seen)


_ACTIVE = ActiveTxns()


def note_txn(txn_id: str) -> None:
    """Record one transaction sighting in the process-wide tracker
    (called at begin client-side and per pinned REQ server-side)."""
    _ACTIVE.note(txn_id)


def active_txn_count() -> int:
    """Transactions seen within the tracker TTL — the health gauge."""
    return _ACTIVE.count()
