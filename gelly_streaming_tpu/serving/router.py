"""Sharded serving: multi-shard query fan-out with scatter-gather merge.

Until now every replica answered from ONE whole snapshot: a keyspace
bigger than one host's memory was unservable and query throughput was
capped by a single serving worker. This module is the routing tier in
front of N shard servers:

- **One partition rule.** The vertex-id space is partitioned by
  :func:`~gelly_streaming_tpu.core.ingest.vertex_owner` — derived from
  ``shard_of``, the SAME endpoint hash the sharded-ingest wire uses —
  and each shard ingests the edges incident to the vertices it owns
  (:func:`~gelly_streaming_tpu.core.ingest.partition_edges_by_vertex`:
  every edge reaches the owner of each endpoint, so per-vertex answers
  are owner-complete and every edge lives in at least one shard).
- **Scatter-gather fan-out.** :class:`ShardRouter` drains concurrent
  submissions in sweeps (the serving worker's coalescing discipline),
  splits each sweep's degree/rank queries into per-owner sub-batches,
  fans them to the owning shards in parallel over the existing GSRP
  wire (one :class:`~.client.RpcClient` per shard — idempotent batch
  ids, reconnect-and-resubmit, per-shard failover all inherited), and
  merges the partial answer lists back into submission order. Each
  query spends ONE deadline end-to-end: the budget is pinned at
  admission and every shard call ships only what REMAINS.
- **Cross-shard union for CC.** Connectivity queries cannot be answered
  by any single shard (a component may span shards through boundary
  vertices), so the router pulls each shard's forest summary
  (:class:`~.query.SummaryPullQuery` — raw-id ``(vertex, root)``
  columns) and merges them with the group-fold union step
  (:func:`~gelly_streaming_tpu.summaries.forest.fold_edges_host`): the
  union of per-shard spanning forests has exactly the components of the
  union of per-shard edge sets, so ``connected``/``component size``
  answers are byte-identical to a single host folding the whole
  stream. Pulls are per shard snapshot VERSION (lazy, cached), not per
  query.
- **Hot-key answer cache.** A bounded LRU keyed on
  ``(query class, vertex key)`` and STAMPED with the shard snapshot
  versions the answer was computed from. Reply frames carry each
  shard's snapshot version; a version bump observed in any reply
  lazily invalidates stale entries at their next lookup (counted).
  Power-law traffic — millions of users hammering a small hot set —
  short-circuits the fan-out entirely on the hit path.
  ``cache_ttl_s`` optionally bounds hit age for deployments whose
  traffic could go 100% hot (no misses means no version observations).

Observability: ``router.cache_hits`` / ``router.cache_misses`` /
``router.cache_invalidations``, ``router.fanouts``, ``router.pulls`` /
``router.pull_errors{shard}``, ``router.stale_merges``, and — with
tracing on — one ``serving.router.fanout`` span per traced wire batch,
parented under the client's batch root, with every shard sub-batch's
spans parented under IT: one trace joins client, router, and every
shard that answered.

``python -m gelly_streaming_tpu.serving.router --router '<json cfg>'``
runs the router as a standalone binary (an :class:`~.rpc.RpcServer`
front end over the fan-out), the shape the sharded bench deploys.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from collections import OrderedDict, deque
from concurrent.futures import Future, InvalidStateError
from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..core.ingest import vertex_owner, vertex_owner_epoch
from ..obs import trace as _trace
from ..obs.registry import get_registry
from .client import RpcClient
from .query import (
    Answer,
    ComponentSizeQuery,
    ConnectedQuery,
    DegreeQuery,
    MalformedPull,
    Query,
    RankQuery,
    SummaryPullQuery,
    decode_pull_doc,
)
from .server import Overloaded
from .txn import TxnSnapshotExpired

#: hot-key LRU capacity default (answers, not bytes: each entry is one
#: Answer + a version stamp)
DEFAULT_CACHE_CAP = 8192

#: pinned merged-forest LRU (ISSUE 20): one carried cross-shard forest
#: per distinct transaction pin vector — transactions are short-lived,
#: so a handful of concurrently-pinned vectors covers the working set
PINNED_MERGED_CAP = 4

#: fallback wall bound for a pinned CC gather when every requester is
#: deadline-less — the worker must never block forever on a dead shard
PINNED_PULL_TIMEOUT_S = 30.0

#: query classes the router serves (fan-out or merged-forest path)
ROUTED_CLASSES = (
    ConnectedQuery, ComponentSizeQuery, DegreeQuery, RankQuery,
)

#: wire bytes per pulled (vertex, root) row — two packed int64 columns
PULL_ROW_BYTES = 16

#: how many delta refreshes the selective-invalidation history spans; a
#: cache entry stamped further back than the ring reaches invalidates
#: the old blanket way instead of revalidating
DELTA_HIST = 64


def decode_pull(doc: dict) -> dict:
    """Decode a :meth:`~.query.QueryEngine.summary_pull` answer value
    (see :func:`~.query.decode_pull_doc` for the decoded shape). Raises
    :class:`~.query.MalformedPull` (a ``ValueError``) on a malformed
    doc — a torn summary must never silently merge as empty — and
    counts the rejection under ``router.pull_malformed{kind}`` so a
    misbehaving shard's failure CLASS (geometry vs base64 vs missing
    keys...) is visible, not just a generic pull error."""
    try:
        return decode_pull_doc(doc)
    except MalformedPull as e:
        get_registry().counter("router.pull_malformed", kind=e.kind).inc()
        raise


class _Entry:
    """One admitted query riding the router's pending queue. ``txn``
    is the decoded transaction dict (``{"id", "pin", "vec"}``) the
    entry rides under, None outside a transaction; ``pin`` is the
    ``(version, boot)`` the fan-out resolved for the entry's routed
    shard (split-ancestry walk included), None for unpinned."""

    __slots__ = ("q", "f", "t0", "dl", "ctx", "grp", "key", "done",
                 "txn", "pin")

    def __init__(self, q, f, t0, dl, ctx, txn=None):
        self.q = q
        self.f = f
        self.t0 = t0
        self.dl = dl
        self.ctx = ctx
        self.grp = None
        self.key = None
        self.done = False
        self.txn = txn
        self.pin = None


class _Group:
    """Per-(traced wire batch, sweep) fan-out accounting: the
    ``serving.router.fanout`` span is emitted when the LAST entry of
    the group settles, so its duration covers the whole scatter-gather
    including the slowest shard."""

    __slots__ = ("ctx", "sid", "t0", "left", "hits", "misses",
                 "shards", "lock")

    def __init__(self, ctx, sid: int, t0: float, left: int):
        self.ctx = ctx
        self.sid = sid
        self.t0 = t0
        self.left = left
        self.hits = 0
        self.misses = 0
        self.shards: set = set()
        self.lock = threading.Lock()

    def done_one(self) -> bool:
        with self.lock:
            self.left -= 1
            return self.left == 0


class _CacheEntry:
    """``owner`` is the key's owning shard for owner-routed classes
    (so validity checks one version slot without re-hashing), None for
    router-merged classes (validity checks the whole vector).
    ``roots`` (merged-CC entries only) records the RAW root ids the
    answer depended on — the selective-invalidation key: a delta
    refresh whose touched-component set misses every root PROVES the
    cached answer still holds at the new version vector."""

    __slots__ = ("ans", "vers", "ts", "owner", "roots")

    def __init__(self, ans: Answer, vers: tuple, ts: float,
                 owner: Optional[int], roots: Optional[frozenset] = None):
        self.ans = ans
        self.vers = vers
        self.ts = ts
        self.owner = owner
        self.roots = roots


class _MergedCC:
    """The router's carried cross-shard merged forest.

    Built by a full rebuild
    (:func:`~gelly_streaming_tpu.summaries.forest.merge_forest_tables_host`
    over the per-shard tables) and then kept CURRENT by
    :func:`~gelly_streaming_tpu.summaries.forest.apply_forest_delta_host`
    over delta-pull rows — O(changed) per refresh. Dense ids are the
    sorted position in ``uniq`` (the raw-id union at rebuild time);
    raw ids first seen in a later delta append PAST the base (``extra``
    maps them, ``raw_of`` inverts) with amortized-doubling growth, so
    between rebuilds nothing is re-sorted. ``lab`` stays min-rooted
    (``lab[v] <= v`` — sorted raw order preserves the invariant) but
    not necessarily flat between rebuilds: readers chase roots.
    All access is under the router's ``_mlock``."""

    __slots__ = ("uniq", "extra", "lab", "sizes", "raw_of", "n",
                 "meta", "stamp")

    def __init__(self, uniq: np.ndarray, lab: np.ndarray,
                 sizes: np.ndarray, meta: tuple, stamp: tuple):
        self.uniq = uniq
        self.extra: dict = {}
        self.lab = np.asarray(lab, np.int64)
        self.sizes = np.asarray(sizes, np.int64)
        self.raw_of = np.asarray(uniq, np.int64).copy()
        self.n = len(uniq)
        self.meta = meta
        self.stamp = stamp

    def lookup(self, raw: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """(dense index, found mask); consults the post-rebuild extras
        for ids the sorted base predates."""
        i, f = ShardRouter._lookup(self.uniq, raw)
        if self.extra:
            for j in np.nonzero(~f)[0].tolist():
                d = self.extra.get(int(raw[j]))
                if d is not None:
                    i[j] = d
                    f[j] = True
        return i, f

    def ensure_ids(self, raw: np.ndarray) -> np.ndarray:
        """Dense ids for ``raw``, allocating self-rooted singleton slots
        for ids never seen before (a delta's brand-new vertices)."""
        i, f = self.lookup(raw)
        for j in np.nonzero(~f)[0].tolist():
            rid = int(raw[j])
            d = self.extra.get(rid)
            if d is None:
                d = self.n
                self._grow(d + 1)
                self.lab[d] = d
                self.sizes[d] = 1
                self.raw_of[d] = rid
                self.extra[rid] = d
                self.n = d + 1
            i[j] = d
        return i

    def roots(self, idx: np.ndarray) -> np.ndarray:
        """Batch root chase (the table may be non-flat between full
        rebuilds; chains stay short via the delta path's halving)."""
        r = self.lab[idx]
        while True:
            nxt = self.lab[r]
            if np.array_equal(nxt, r):
                return r
            r = nxt

    def _grow(self, need: int) -> None:
        cap = len(self.lab)
        if need <= cap:
            return
        new = max(need, 2 * cap, 8)
        lab2 = np.arange(new, dtype=np.int64)
        lab2[:cap] = self.lab
        sizes2 = np.ones(new, np.int64)
        sizes2[:cap] = self.sizes
        raw2 = np.full(new, -1, np.int64)
        raw2[:cap] = self.raw_of
        self.lab, self.sizes, self.raw_of = lab2, sizes2, raw2


class ShardRouter:
    """Scatter-gather query router over N shard serving replicas.

    ``shard_addrs`` is one address LIST per shard (give each shard's
    primary AND standby; the per-shard :class:`~.client.RpcClient`
    cycles them, so each shard fails over independently without the
    router noticing beyond a latency blip). The router has the same
    ``submit``/``ask`` surface as a ``StreamServer`` — put it behind an
    :class:`~.rpc.RpcServer` and clients cannot tell it from a single
    replica.

    Merge semantics per query class (the contract README documents):

    - ``DegreeQuery`` / ``RankQuery``: routed to the key's OWNER shard,
      whose partial is the whole answer (the delivery rule hands every
      incident edge to the owner); the router's merge re-interleaves
      per-shard sub-batch answers into submission order. Rank is exact
      only as far as the shard's local summary is (an edge-subset
      PageRank is the shard's declared partial).
    - ``ConnectedQuery`` / ``ComponentSizeQuery``: answered at the
      router from the merged cross-shard forest (see module docstring);
      ``window`` is the MINIMUM shard window merged (the conservative
      progress claim), ``watermark`` the sum, ``staleness`` the max,
      ``version`` the sum of shard versions (monotone under any bump).

    A cache hit re-serves the answer computed at its stamped snapshot
    versions; the invalidation contract bounds how stale a hit can be:
    any reply frame observing a newer shard version invalidates the
    entry at its next lookup, and ``cache_ttl_s`` (optional) bounds the
    window in which NO reply was observed at all.
    """

    def __init__(
        self,
        shard_addrs: Sequence,
        *,
        max_pending: int = 1 << 14,
        cache: bool = True,
        cache_cap: int = DEFAULT_CACHE_CAP,
        cache_ttl_s: Optional[float] = None,
        client_factory=None,
        seed: int = 0,
        autotune: bool = False,
        target_wait_s: Optional[float] = None,
        delta: bool = True,
        reshard=None,
    ):
        if not shard_addrs:
            raise ValueError("at least one shard address is required")
        factory = client_factory or (
            lambda addrs, i: RpcClient(addrs, seed=seed + i)
        )
        self._factory = factory
        self._clients: List[RpcClient] = [
            factory(a if isinstance(a, (list, tuple)) and not (
                isinstance(a, tuple) and len(a) == 2
                and isinstance(a[1], int)
            ) else [a], i)
            for i, a in enumerate(shard_addrs)
        ]
        self.nshards = len(self._clients)
        #: elastic resharding (ISSUE 19): the BOOT shard count is the
        #: hash base forever — splits compose on top of it
        #: (``core.ingest.vertex_owner_epoch``), so adopting a split
        #: never moves keys that did not split. ``reshard`` is the
        #: coordination store (dir/transport) split plans are read
        #: from; adoption triggers off reply-frame epoch stamps.
        self._hash_shards = len(self._clients)
        self._reshard = reshard
        self._splits: list = []   # adopted plan dicts, epoch order
        self._epoch = 0           # == len(self._splits)
        self.max_pending = int(max_pending)
        #: load-aware admission (ISSUE 15): same contract as
        #: ``StreamServer(autotune=True)`` — the router's drain sweep
        #: taps its oldest queue wait vs the tightest deadline budget,
        #: and the tuner moves ``max_pending`` inside the configured
        #: ceiling with hysteresis + bounded steps (the router has no
        #: class shedding, so only the admission limit moves)
        self.admission = None
        if autotune:
            from ..control import AdmissionTuner

            self.admission = AdmissionTuner(
                max_pending=self.max_pending,
                target_wait_s=target_wait_s,
            )
        self.cache_enabled = bool(cache)
        self.cache_cap = int(cache_cap)
        self.cache_ttl_s = cache_ttl_s
        self._cache: "OrderedDict[tuple, _CacheEntry]" = OrderedDict()
        self._lock = threading.Lock()       # pending/admission/cache
        self._pending: deque = deque()
        self._inflight = 0
        self._wake = threading.Event()
        self._closing = False
        #: pull protocol v2 (ISSUE 17): send ``since_version`` once a
        #: baseline exists, apply delta replies incrementally, and
        #: retain provably-untouched cache entries across refreshes;
        #: False pins the v1 full-re-pull behavior (the bench baseline)
        self.delta = bool(delta)
        # merged cross-shard CC state (all under _mlock)
        self._mlock = threading.Lock()
        self._vers = [0] * self.nshards       # newest observed version
        self._pulled_vers = [-1] * self.nshards
        self._pairs: list = [None] * self.nshards   # (u_raw, r_raw)
        self._rows: list = [None] * self.nshards    # raw -> root carry
        self._pull_meta: list = [None] * self.nshards  # (win, wm, stale)
        self._pulls: dict = {}                # shard -> in-flight pull
        self._pull_err: list = [None] * self.nshards
        self._cc_waiting: list = []           # jobs parked on pulls
        self._merged: Optional[_MergedCC] = None
        # delta rows accepted since the last merged refresh, and
        # whether any full reply forces the next refresh to rebuild
        self._delta_pending: list = []        # (u_raw, r_raw) batches
        self._full_pending = False
        # (from_stamp, to_stamp, touched raw roots) per delta refresh —
        # the chain a stale cache entry revalidates against
        self._delta_hist: deque = deque(maxlen=DELTA_HIST)
        # pinned merged forests (ISSUE 20): one carried cross-shard
        # forest per transaction pin vector, LRU-bounded (under _mlock)
        self._pinned_merged: "OrderedDict[tuple, _MergedCC]" = \
            OrderedDict()
        # hot-path instruments resolved once (a cache hit should cost
        # a dict probe + a counter bump, not two registry lookups)
        reg = get_registry()
        self._c_hits = reg.counter("router.cache_hits")
        self._c_misses = reg.counter("router.cache_misses")
        self._c_inval = reg.counter("router.cache_invalidations")
        self._c_retained = reg.counter("router.cache_retained")
        self._worker = threading.Thread(
            target=self._run, name="shard-router", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------------ #
    # Submission surface (StreamServer.submit contract)
    # ------------------------------------------------------------------ #
    def submit(
        self,
        query: Query,
        *,
        deadline_s: Optional[float] = None,
        ctx=None,
        txn=None,
    ) -> "Future[Answer]":
        """Admit one query; resolves to a merged :class:`Answer`.
        Raises :class:`~.server.Overloaded` at the admission limit and
        ``TypeError`` for classes the router cannot merge. The deadline
        is a TOTAL budget pinned here: cache lookup, fan-out, shard
        retries, and merge all spend the one clock. ``txn`` (ISSUE 20)
        is the decoded transaction dict whose ``vec`` pins per-shard
        reads — owner-routed classes are answered at the pinned
        shard snapshot, CC classes from a pinned merged forest."""
        if not isinstance(query, ROUTED_CLASSES):
            raise TypeError(
                f"ShardRouter routes "
                f"{[c.__name__ for c in ROUTED_CLASSES]}, not "
                f"{type(query).__name__}"
            )
        t0 = time.perf_counter()
        dl = None if deadline_s is None else t0 + float(deadline_s)
        if ctx is None and _trace.on():
            ctx = _trace.current_context()
        e = _Entry(query, Future(), t0, dl, ctx, txn=txn)
        with self._lock:
            if self._closing:
                raise RuntimeError("router is closed")
            admitted = len(self._pending) + self._inflight
            if admitted >= self.max_pending:
                get_registry().counter("router.rejected").inc()
                raise Overloaded(
                    f"{admitted} queries in flight at the router "
                    f"(max_pending={self.max_pending})"
                )
            self._pending.append(e)
        self._wake.set()
        return e.f

    def submit_many(
        self,
        queries,
        *,
        deadline_s: Optional[float] = None,
        ctx=None,
        txn=None,
    ) -> list:
        """Admit a whole wire batch under ONE lock acquisition (the
        RPC front end's fast path; all-or-nothing admission, like
        ``StreamServer.submit_many``)."""
        for q in queries:
            if not isinstance(q, ROUTED_CLASSES):
                raise TypeError(
                    f"ShardRouter routes "
                    f"{[c.__name__ for c in ROUTED_CLASSES]}, not "
                    f"{type(q).__name__}"
                )
        t0 = time.perf_counter()
        dl = None if deadline_s is None else t0 + float(deadline_s)
        if ctx is None and _trace.on():
            ctx = _trace.current_context()
        entries = [
            _Entry(q, Future(), t0, dl, ctx, txn=txn) for q in queries
        ]
        with self._lock:
            if self._closing:
                raise RuntimeError("router is closed")
            admitted = len(self._pending) + self._inflight
            if admitted + len(queries) > self.max_pending:
                get_registry().counter("router.rejected").inc()
                raise Overloaded(
                    f"{admitted} queries in flight at the router "
                    f"(max_pending={self.max_pending})"
                )
            self._pending.extend(entries)
        self._wake.set()
        return [e.f for e in entries]

    def ask(self, query: Query, timeout: Optional[float] = None,
            deadline_s: Optional[float] = None) -> Answer:
        return self.submit(query, deadline_s=deadline_s).result(timeout)

    def ask_batch(
        self,
        queries: Sequence[Query],
        *,
        deadline_s: Optional[float] = None,
        timeout: Optional[float] = None,
    ) -> List[Answer]:
        futures = [
            self.submit(q, deadline_s=deadline_s) for q in queries
        ]
        # one budget across the whole wait (GL008)
        deadline = None if timeout is None \
            else time.monotonic() + float(timeout)
        return [
            f.result(
                None if deadline is None
                else max(0.0, deadline - time.monotonic())
            )
            for f in futures
        ]

    def pending(self) -> int:
        with self._lock:
            return len(self._pending) + self._inflight

    def health(self) -> dict:
        with self._lock:
            cache_n = len(self._cache)
            pending = len(self._pending) + self._inflight
        return {
            "shards": self.nshards,
            "epoch": self._epoch,
            "pending": pending,
            "cache_entries": cache_n,
            "shard_versions": list(self._vers),
            "ok": self._worker.is_alive(),
        }

    def stats_snapshot(self) -> dict:
        """Router counters as a plain dict (cache hit/miss/invalidation
        and full-vs-delta refresh evidence the bench commits)."""
        reg = get_registry()

        def _count(name: str, **labels) -> float:
            return float(sum(
                i.value for l, i in reg.find(name)
                if all(l.get(k) == v for k, v in labels.items())
            ))

        return {
            "pending": self.pending(),
            "epoch": self._epoch,
            "shards": self.nshards,
            "reshard_adopts":
                int(_count("reshard.adopt", site="router")),
            "cache_hits": int(_count("router.cache_hits")),
            "cache_misses": int(_count("router.cache_misses")),
            "cache_invalidations":
                int(_count("router.cache_invalidations")),
            "cache_retained": int(_count("router.cache_retained")),
            "fanouts": int(_count("router.fanouts")),
            "pulls": int(_count("router.pulls")),
            "pull_errors": int(_count("router.pull_errors")),
            "pull_malformed": int(_count("router.pull_malformed")),
            "stale_merges": int(_count("router.stale_merges")),
            "rejected": int(_count("router.rejected")),
            # protocol v2 evidence: reply-frame mix, pulled volume, and
            # the router-side merge-refresh cost split by kind
            "delta_pulls": int(_count("router.delta_pulls")),
            "delta_rows": int(_count("router.delta_rows")),
            "full_fallbacks": int(_count("router.full_fallbacks")),
            "pull_bytes_full":
                int(_count("router.pull_bytes", kind="full")),
            "pull_bytes_delta":
                int(_count("router.pull_bytes", kind="delta")),
            "merges_full": int(_count("router.merges", kind="full")),
            "merges_delta": int(_count("router.merges", kind="delta")),
            "merge_s_full": _count("router.merge_s", kind="full"),
            "merge_s_delta": _count("router.merge_s", kind="delta"),
        }

    # ------------------------------------------------------------------ #
    # Worker (drain-and-coalesce, like the serving worker)
    # ------------------------------------------------------------------ #
    def _run(self) -> None:
        while True:
            with self._lock:
                batch = list(self._pending)
                self._pending.clear()
                self._inflight += len(batch)
                closing = self._closing
            if batch:
                try:
                    self._sweep(batch)
                except BaseException as e:
                    # the router worker must survive any sweep error —
                    # a dead worker hangs every future forever
                    get_registry().counter(
                        "router.swallowed", site="sweep"
                    ).inc()
                    for e_ in batch:
                        self._settle(e_, exc=e)
                continue
            if closing:
                return
            self._wake.wait(0.05)
            self._wake.clear()

    def _sweep(self, batch: List[_Entry]) -> None:
        if self._reshard is not None:
            self._maybe_adopt_epoch()
        reg = get_registry()
        now = time.perf_counter()
        t_sweep = now
        live: List[_Entry] = []
        groups: dict = {}
        tracing = _trace.on()
        for e in batch:
            if e.dl is not None and now > e.dl:
                self._expire(e)
                continue
            e.key = self._cache_key(e.q)
            if tracing and e.ctx is not None:
                g = groups.get(id(e.ctx))
                if g is None:
                    g = _Group(e.ctx, _trace.next_sid(), t_sweep, 0)
                    groups[id(e.ctx)] = g
                g.left += 1
                e.grp = g
            live.append(e)
        if not live:
            return
        if self.admission is not None:
            # admission tap (once per sweep): oldest queue wait — the
            # batch drains in submission order — vs the sweep's
            # tightest deadline budget
            if self.admission.tap_entries(
                t_sweep - live[0].t0, ((e.t0, e.dl) for e in live)
            ):
                with self._lock:
                    self.max_pending = self.admission.max_pending
        # ---- cache pass (counters aggregated per sweep: a hot sweep
        # must cost probes, not one event emission per query) --------- #
        misses: List[_Entry] = []
        n_hits = 0
        for e in live:
            hit = None
            if self.cache_enabled:
                vec = None if e.txn is None else e.txn.get("vec")
                if vec:
                    # pinned lookup: the cache is consulted with a
                    # VERSION COMPARE against the pin, not bypassed —
                    # a hit re-serves the answer only when it was
                    # computed at exactly the pinned snapshot
                    pin = None
                    if isinstance(e.q, (DegreeQuery, RankQuery)):
                        s = int(vertex_owner_epoch(
                            np.asarray([e.q.v], np.int64),
                            self._hash_shards, self._splits,
                        )[0])
                        _rs, pin = self._pin_route(vec, s)
                    if pin is not None:
                        hit = self._cache_get(e.key, pin=pin)
                else:
                    hit = self._cache_get(e.key)
            if hit is not None:
                if e.grp is not None:
                    e.grp.hits += 1
                n_hits += 1
                self._settle(e, ans=hit)
            else:
                if e.grp is not None:
                    e.grp.misses += 1
                misses.append(e)
        if n_hits:
            self._c_hits.inc(n_hits)
        if not misses:
            return
        if self.cache_enabled:
            self._c_misses.inc(len(misses))
        reg.counter("router.fanouts").inc()
        # ---- split by path ------------------------------------------- #
        dr: List[_Entry] = []      # owner fan-out classes
        cc: List[_Entry] = []      # merged-forest classes (fresh)
        ccp: List[_Entry] = []     # merged-forest classes, PINNED
        for e in misses:
            if isinstance(e.q, (DegreeQuery, RankQuery)):
                dr.append(e)
            elif e.txn is not None and e.txn.get("vec"):
                ccp.append(e)
            else:
                cc.append(e)
        if dr:
            self._fan_out(dr)
        if cc:
            self._route_cc(cc)
        if ccp:
            self._route_cc_pinned(ccp)

    # ------------------------------------------------------------------ #
    # Elastic resharding: epoch adoption (worker thread only)
    # ------------------------------------------------------------------ #
    def _maybe_adopt_epoch(self) -> None:
        """Adopt newly actionable split plans once any shard's reply
        frames stamp an epoch ahead of ours.

        Runs on the router worker (the only thread that reads
        ``_clients`` by index for fan-out), so appending a child
        client is race-free for routing; the merged-CC arrays grow
        under ``_mlock`` where every other reader holds it. A stamp
        ahead of the store's ACTIONABLE prefix just retries next sweep
        (the child's address commit is what we are waiting on).
        Adoption never rolls back — splits are monotone history."""
        observed = max(c.epoch_observed for c in self._clients)
        if observed <= self._epoch:
            return
        from .reshard import actionable_plans

        try:
            plans = actionable_plans(self._reshard)
        except Exception:
            # a flaky store read must not take the sweep down; the
            # reply frames keep stamping, the next sweep retries
            get_registry().counter(
                "router.swallowed", site="reshard_read").inc()
            return
        reg = get_registry()
        for p in plans[self._epoch:]:
            if int(p["child"]) != len(self._clients):
                # a plan whose child index does not extend the client
                # list would mis-route every moved key; refuse it (and
                # everything after — plans compose in order)
                reg.counter(
                    "router.swallowed", site="reshard_geometry").inc()
                return
            cl = self._factory([p["addr"]], len(self._clients))
            with self._mlock:
                self._clients.append(cl)
                self._vers.append(0)
                self._pulled_vers.append(-1)
                self._pairs.append(None)
                self._rows.append(None)
                self._pull_meta.append(None)
                self._pull_err.append(None)
                self._splits.append(
                    {k: int(p[k])
                     for k in ("epoch", "parent", "child", "salt")})
                self.nshards = len(self._clients)
                self._epoch = len(self._splits)
                # the merged forest must now cover the child's pull
                # before answering: drop the merge so the next CC
                # query refreshes against ALL shards including the
                # child (its first pull is a full, since=-1)
                self._merged = None
            reg.counter(
                "reshard.adopt", epoch=str(p["epoch"]), site="router",
            ).inc()

    # ------------------------------------------------------------------ #
    # Degree / rank: owner fan-out
    # ------------------------------------------------------------------ #
    def _pin_route(self, vec: dict, shard: int):
        """``(route_shard, pin)`` for an owner-routed key under a
        transaction vector. A pin on the owner itself routes there; an
        unpinned CHILD of a live split walks the ancestry child→parent
        looking for a pinned ancestor — a parent-version pin predates
        the split, and the parent's snapshot (a superset table) is the
        only replica that HOLDS it, so the pinned read routes to the
        ancestor shard. No pin anywhere on the chain: unpinned."""
        pin = vec.get(shard)
        if pin is not None:
            return shard, pin
        cur = shard
        for p in reversed(self._splits):
            if p["child"] == cur:
                cur = p["parent"]
                pin = vec.get(cur)
                if pin is not None:
                    return cur, pin
        return shard, None

    def _fan_out(self, entries: List[_Entry]) -> None:
        # ownership = boot hash + adopted split generations: the hash
        # base NEVER changes (self._hash_shards), splits move only the
        # split-off half of the split shard's keys (ISSUE 19)
        owners = vertex_owner_epoch(
            np.asarray([e.q.v for e in entries], np.int64),
            self._hash_shards, self._splits,
        )
        # sub-batch per (shard, trace group, has-deadline, pin):
        # untraced entries coalesce per shard; traced ones split per
        # group so every shard batch stays on exactly one trace;
        # deadline-less entries ride their own sub-batch so they
        # neither STRIP the wire deadline from bounded peers (which
        # would let a wedged shard hang them past their budget) nor
        # inherit one; pinned entries (ISSUE 20) sub-batch per pin so
        # one wire txn field speaks for the whole sub-batch
        subs: dict = {}
        for e, s in zip(entries, owners.tolist()):
            vec = None if e.txn is None else e.txn.get("vec")
            if vec:
                s, e.pin = self._pin_route(vec, s)
            subs.setdefault(
                (s, id(e.grp) if e.grp else None, e.dl is None,
                 e.pin),
                []).append(e)
        for (s, _gk, dl_free, pin), es in subs.items():
            grp = es[0].grp
            if grp is not None:
                grp.shards.add(s)
            now = time.perf_counter()
            remaining = None
            if not dl_free:
                # the LOOSEST member deadline bounds the wire call; each
                # entry still re-checks its own budget at settle
                remaining = max(
                    0.001, max(e.dl for e in es) - now)
            ctx2 = None
            if grp is not None:
                ctx2 = _trace.TraceContext(
                    trace_id=grp.ctx.trace_id, parent_sid=grp.sid
                )
            txn_doc = None
            if pin is not None:
                # the per-owner wire form: ONE pin the shard must
                # honor or expire honestly (serving/txn.py codec)
                txn_doc = {
                    "id": es[0].txn.get("id", ""),
                    "pin": [int(pin[0]), str(pin[1])],
                }
            try:
                futs = self._clients[s].submit_batch(
                    [e.q for e in es], deadline_s=remaining, ctx=ctx2,
                    txn=txn_doc,
                )
            except BaseException as exc:
                # a synchronously-failing shard client (closed mid-
                # sweep): the error reaches the callers, but it must
                # ALSO leave fan-out evidence — an uncounted shard
                # failure would make a partial outage invisible
                get_registry().counter(
                    "router.shard_errors", shard=str(s)
                ).inc()
                for e in es:
                    self._settle(e, exc=exc)
                continue
            for e, f in zip(es, futs):
                f.add_done_callback(partial(self._shard_done, e, s))

    def _shard_done(self, e: _Entry, shard: int, fut) -> None:
        """Shard answer callback (the shard client's io thread): settle
        ONE entry — per-entry settling keeps a slow shard from holding
        up answers that already arrived from faster shards."""
        exc = fut.exception()
        if exc is not None:
            if not isinstance(exc, TxnSnapshotExpired):
                # a typed pin expiry is the transaction's honest
                # outcome (already counted at its raise/detect site),
                # not a shard failure
                get_registry().counter(
                    "router.shard_errors", shard=str(shard)
                ).inc()
            self._settle(e, exc=exc)
            return
        ans = fut.result()
        if ans.shard < 0:
            # stamp the routed shard so the client's TxnContext pins
            # (and its monotonic floor tracks) per shard, even when
            # the replica did not know its own index
            ans = dataclasses.replace(ans, shard=shard)
        if e.pin is not None:
            # a pinned answer is deliberately OLD: it must neither
            # seed the hot-key cache (a fresh lookup would re-serve
            # the pinned past) nor drive _observe_version (its low
            # version would read as a shard restart and reset the
            # router's high-water adoption state)
            self._settle(e, ans=ans)
            return
        self._observe_version(shard, ans.version)
        if self.cache_enabled:
            self._cache_put(e.key, ans, (int(ans.version),),
                            owner=shard)
        self._settle(e, ans=ans)

    # ------------------------------------------------------------------ #
    # Connected / component size: merged cross-shard forest
    # ------------------------------------------------------------------ #
    def _route_cc(self, entries: List[_Entry]) -> None:
        to_pull: list = []
        ready = False
        with self._mlock:
            stale = [
                s for s in range(self.nshards)
                if self._pulled_vers[s] < max(1, self._vers[s])
            ]
            if not stale and self._merged is not None:
                ready = True
            else:
                self._cc_waiting.append(entries)
                for s in stale:
                    if s not in self._pulls:
                        # protocol v2: once a baseline table is carried
                        # for the shard, ask for only the rows changed
                        # since it; -1 (v1 shape) pulls the full table
                        since = (
                            self._pulled_vers[s]
                            if self.delta and self._pulled_vers[s] >= 0
                            and self._rows[s] is not None else -1
                        )
                        self._pulls[s] = {"since": since, "t0": 0.0,
                                          "grp": None}
                        to_pull.append((s, since))
        if ready:
            self._answer_cc(entries)
            return
        # fire pulls OUTSIDE the lock (socket sends must never run
        # under router state locks)
        now = time.perf_counter()
        dls = [e.dl for e in entries if e.dl is not None]
        # bound the pull by the LOOSEST bounded requester: a
        # deadline-less co-swept entry must not make the pull (and the
        # bounded entries parked on it) unexpirable against a wedged
        # shard. Entries without a deadline accept the pull's outcome
        # either way — a failed pull fails them visibly, and the next
        # CC miss re-triggers a fresh pull.
        remaining = max(0.001, max(dls) - now) if dls else None
        # pulls serve EVERY parked group; attribute their spans to the
        # first TRACED entry's group (a shared refresh has one causal
        # home, and an untraced head entry must not orphan the join)
        grp = next((e.grp for e in entries if e.grp is not None), None)
        for s, since in to_pull:
            get_registry().counter("router.pulls").inc()
            ctx2 = None
            if grp is not None:
                grp.shards.add(s)
                ctx2 = _trace.TraceContext(
                    trace_id=grp.ctx.trace_id, parent_sid=grp.sid
                )
            # the reply callback reads this to attribute the pull span
            # and detect full-reply fallbacks (assignment is atomic;
            # the placeholder above already holds the pull slot)
            self._pulls[s] = {"since": since,
                              "t0": time.perf_counter(), "grp": grp}
            try:
                fut = self._clients[s].submit(
                    SummaryPullQuery(since_version=since),
                    deadline_s=remaining, ctx=ctx2,
                )
            except BaseException as exc:
                self._pull_done(s, _FailedFuture(exc))
                continue
            fut.add_done_callback(partial(self._pull_done, s))

    def _pull_done(self, shard: int, fut) -> None:
        jobs: list = []
        reg = get_registry()
        span = None   # (grp, t0, kind, rows, since)
        never: list = []
        with self._mlock:
            info = self._pulls.pop(shard, None) or {}
            since = int(info.get("since", -1))
            exc = fut.exception()
            if exc is None:
                try:
                    ans = fut.result()
                    dec = decode_pull(ans.value)
                    v = int(ans.version)
                    if dec["kind"] == "delta":
                        if (self._rows[shard] is None
                                or dec["base"] !=
                                self._pulled_vers[shard]):
                            # a delta against a baseline this router no
                            # longer holds (restart adoption raced the
                            # reply) cannot be applied
                            raise MalformedPull(
                                "base",
                                f"delta pull base {dec['base']} does "
                                f"not match the carried baseline "
                                f"{self._pulled_vers[shard]}",
                            )
                        reg.counter("router.delta_pulls").inc()
                        reg.counter("router.delta_rows").inc(dec["n"])
                        reg.counter("router.pull_bytes",
                                    kind="delta").inc(
                            PULL_ROW_BYTES * dec["n"])
                        # the delta lists EVERY row whose root changed,
                        # so a plain update keeps the carried table
                        # exact (not merely approximate)
                        self._rows[shard].update(
                            zip(dec["u"].tolist(), dec["r"].tolist()))
                        self._delta_pending.append((dec["u"], dec["r"]))
                    else:
                        reg.counter("router.pull_bytes",
                                    kind="full").inc(
                            PULL_ROW_BYTES * dec["n"])
                        if since >= 0:
                            # we asked for a delta and got the whole
                            # table: an honest degrade (stale ring, no
                            # chain, restarted store) or a v1 peer
                            # that never read the field — either way
                            # the baseline resets to this full table
                            reg.counter(
                                "router.full_fallbacks",
                                reason=dec["why"] or "peer_full",
                            ).inc()
                        self._pairs[shard] = (dec["u"], dec["r"])
                        if self.delta:
                            self._rows[shard] = dict(
                                zip(dec["u"].tolist(),
                                    dec["r"].tolist()))
                        self._full_pending = True
                    self._pulled_vers[shard] = v
                    self._pull_meta[shard] = (
                        int(ans.window), int(ans.watermark),
                        int(ans.staleness), int(ans.event_ts),
                    )
                    self._pull_err[shard] = None
                    cur = self._vers[shard]
                    if v > cur:
                        self._vers[shard] = v
                    elif v + self.VERSION_RESTART_SLACK < cur:
                        # the pull itself met a restarted sequence
                        # (promoted standby): adopt it — pulled_vers
                        # already records the new sequence's version
                        reg.counter(
                            "router.shard_restarts", shard=str(shard)
                        ).inc()
                        self._vers[shard] = v
                    if info.get("grp") is not None:
                        span = (info["grp"], float(info.get("t0", 0.0)),
                                dec["kind"], int(dec["n"]), since)
                except (ValueError, KeyError, TypeError) as e:
                    exc = e
            if exc is not None:
                reg.counter(
                    "router.pull_errors", shard=str(shard)
                ).inc()
                self._pull_err[shard] = exc
                if self._shard_cols(shard) is not None:
                    # a previous pull exists: the merge proceeds on the
                    # stale summary (bounded-staleness availability)
                    reg.counter("router.stale_merges").inc()
            pending_more = bool(self._pulls)
            if not pending_more:
                never = [
                    s for s in range(self.nshards)
                    if self._shard_cols(s) is None
                ]
                if not never:
                    t0m = time.perf_counter()
                    if (self.delta and self._merged is not None
                            and not self._full_pending):
                        self._apply_deltas_locked()
                        kind = "delta"
                    else:
                        self._rebuild_merged_locked()
                        kind = "full"
                    reg.counter("router.merges", kind=kind).inc()
                    reg.counter("router.merge_s", kind=kind).inc(
                        time.perf_counter() - t0m)
                jobs = self._cc_waiting
                self._cc_waiting = []
        if span is not None:
            grp, t0, kind, rows, since = span
            _trace.record_span(
                "serving.router.pull",
                time.perf_counter() - t0,
                trace_id=grp.ctx.trace_id,
                parent=grp.sid,
                sid=_trace.next_sid(),
                attrs={"shard": shard, "kind": kind, "rows": rows,
                       "since": since},
            )
        if pending_more:
            return  # later pulls complete the rendezvous
        if never:
            # a shard that never delivered ANY summary cannot be merged
            # around: exactness over availability at boot — fail these
            # entries with the shard's own error
            err = next(
                (self._pull_err[s] for s in never
                 if self._pull_err[s] is not None),
                RuntimeError(f"shards {never} never delivered a "
                             "summary pull"),
            )
            for entries in jobs:
                for e in entries:
                    self._settle(e, exc=err)
            return
        for entries in jobs:
            self._answer_cc(entries)

    def _shard_cols(self, s: int):
        """This shard's current (raw, root) columns — the delta-carried
        row table when present (always current: full replies replace
        it, delta replies patch it exactly), else the last full pull's
        columns; None when the shard never delivered."""
        d = self._rows[s]
        if d is not None:
            u = np.fromiter(d.keys(), np.int64, len(d))
            r = np.fromiter(d.values(), np.int64, len(d))
            return u, r
        return self._pairs[s]

    def _meta_locked(self) -> tuple:
        """Merged answer meta from the newest per-shard pulls (caller
        holds ``_mlock``): MIN window (conservative progress), summed
        watermark, MAX staleness, summed versions, MIN event-time
        watermark (the cross-shard merge rule
        :func:`gelly_streaming_tpu.eventtime.watermark.merge_watermarks`
        applies: a merged answer is only as current as its
        laggiest shard; shards without event time (-1) are left out,
        -1 when none carries it)."""
        metas = [m for m in self._pull_meta if m is not None]
        stamped = [
            m[3] for m in metas if len(m) > 3 and m[3] >= 0
        ]
        return (
            min(m[0] for m in metas) if metas else -1,
            sum(m[1] for m in metas),
            max(m[2] for m in metas) if metas else 0,
            sum(max(0, v) for v in self._pulled_vers),
            min(stamped) if stamped else -1,
        )

    def _rebuild_merged_locked(self) -> None:
        """Rebuild the merged forest from the carried per-shard tables.
        Caller holds ``_mlock``. Each shard's raw-id pairs densify into
        a forest table over the UNION id space (sorted raw order
        preserves the min-rooted invariant), and one
        :func:`~gelly_streaming_tpu.summaries.forest.merge_forest_tables_host`
        call — THE cross-shard union step — merges them all. Resets the
        delta bookkeeping: pending rows are already folded into the
        carried tables, and the selective-invalidation history cannot
        chain across a rebuild."""
        from ..summaries.forest import merge_forest_tables_host

        cols = [self._shard_cols(s) for s in range(self.nshards)]
        us = [c[0] for c in cols]
        uniq = np.unique(np.concatenate(us)) if us else \
            np.zeros(0, np.int64)
        n = len(uniq)
        tables = []
        for u, r in cols:
            t = np.arange(n, dtype=np.int64)
            t[np.searchsorted(uniq, u)] = np.searchsorted(uniq, r)
            tables.append(t)
        lab = merge_forest_tables_host(tables)
        sizes = np.bincount(lab, minlength=n) if n else \
            np.zeros(0, np.int64)
        self._merged = _MergedCC(
            uniq, lab, sizes, self._meta_locked(),
            tuple(self._pulled_vers),
        )
        self._delta_pending = []
        self._delta_hist.clear()
        self._full_pending = False

    def _apply_deltas_locked(self) -> None:
        """Fold the delta rows accepted since the last refresh into the
        carried merged forest — O(changed rows), the refresh cost the
        delta protocol buys — and record which components they touched
        so provably-untouched cache entries survive the version bump.
        Caller holds ``_mlock``; requires ``self._merged``."""
        from ..summaries.forest import apply_forest_delta_host

        m = self._merged
        from_stamp = m.stamp
        touched: set = set()
        for u, r in self._delta_pending:
            if not len(u):
                continue
            iu = m.ensure_ids(u)
            ir = m.ensure_ids(r)
            t = apply_forest_delta_host(m.lab, m.sizes, iu, ir)
            if len(t):
                touched.update(m.raw_of[t].tolist())
        self._delta_pending = []
        m.meta = self._meta_locked()
        stamp = tuple(self._pulled_vers)
        if stamp != from_stamp:
            self._delta_hist.append(
                (from_stamp, stamp, frozenset(touched)))
        m.stamp = stamp

    def _answer_cc(self, entries: List[_Entry]) -> None:
        qs = [e.q for e in entries]
        conn_idx = [i for i, q in enumerate(qs)
                    if isinstance(q, ConnectedQuery)]
        size_idx = [i for i, q in enumerate(qs)
                    if isinstance(q, ComponentSizeQuery)]
        vals: dict = {}
        roots_of: dict = {}
        # compute under _mlock: the carried forest mutates IN PLACE on
        # delta refreshes (unlike the old swap-a-tuple rebuild), so
        # reads must not interleave with an apply
        with self._mlock:
            m = self._merged
            meta, stamp = m.meta, m.stamp
            if conn_idx:
                us = np.asarray([qs[i].u for i in conn_idx], np.int64)
                vs = np.asarray([qs[i].v for i in conn_idx], np.int64)
                iu, fu = m.lookup(us)
                iv, fv = m.lookup(vs)
                ok = fu & fv
                ru = m.roots(np.where(fu, iu, 0))
                rv = m.roots(np.where(fv, iv, 0))
                # an unseen vertex is its own singleton — connected
                # only to itself (the single-host engine's semantics)
                got = np.where(ok, ru == rv, us == vs)
                rud, rvd = m.raw_of[ru], m.raw_of[rv]
                for k, i in enumerate(conn_idx):
                    vals[i] = bool(got[k])
                    # the RAW roots this answer depends on; an unseen
                    # endpoint's own id stands in (if it ever appears
                    # and merges, it shows up in a touched set)
                    roots_of[i] = frozenset((
                        int(rud[k]) if fu[k] else int(us[k]),
                        int(rvd[k]) if fv[k] else int(vs[k]),
                    ))
            if size_idx:
                vs = np.asarray([qs[i].v for i in size_idx], np.int64)
                iv, fv = m.lookup(vs)
                rv = m.roots(np.where(fv, iv, 0))
                got = np.where(fv, m.sizes[rv], 0)
                rvd = m.raw_of[rv]
                for k, i in enumerate(size_idx):
                    vals[i] = int(got[k])
                    roots_of[i] = frozenset(
                        (int(rvd[k]) if fv[k] else int(vs[k]),))
        window, watermark, staleness, version, event_ts = meta
        for i, e in enumerate(entries):
            ans = Answer(
                value=vals[i], window=window, watermark=watermark,
                staleness=staleness, version=version, event_ts=event_ts,
            )
            if self.cache_enabled:
                self._cache_put(e.key, ans, stamp,
                                roots=roots_of.get(i))
            self._settle(e, ans=ans)

    # ------------------------------------------------------------------ #
    # Connected / component size under a transaction vector (ISSUE 20)
    # ------------------------------------------------------------------ #
    def _route_cc_pinned(self, entries: List[_Entry]) -> None:
        """Merged-forest classes pinned by a transaction vector.

        The shared carried forest (:meth:`_route_cc`) is always-fresh
        by design, so pinned requests build their OWN merged forest
        from per-shard pulls issued AT the pinned versions (the pin
        rides the pull as the per-shard wire form), kept in a small
        LRU keyed by the vector — a repeated read inside one
        transaction reuses the same forest object and is byte-identical
        by construction. Shards the vector does not pin are pulled
        fresh ONCE and baked into that forest (partial pins stay
        self-consistent across repeats while the LRU holds the entry —
        the documented best-effort residual). Any shard that cannot
        serve its pin fails the whole group with the shard's own typed
        :class:`~.txn.TxnSnapshotExpired` — never a fresher merge."""
        groups: "OrderedDict[tuple, List[_Entry]]" = OrderedDict()
        for e in entries:
            vec = e.txn.get("vec") or {}
            key = tuple(sorted(
                (int(s), int(p[0]), str(p[1])) for s, p in vec.items()
            ))
            groups.setdefault(key, []).append(e)
        for _key, es in groups.items():
            vec = es[0].txn.get("vec") or {}
            now = time.perf_counter()
            dls = [e.dl for e in es if e.dl is not None]
            remaining = max(0.001, max(dls) - now) if dls else None
            try:
                m = self._pinned_forest(
                    vec, es[0].txn.get("id", ""), remaining)
            except BaseException as exc:
                if not isinstance(exc, TxnSnapshotExpired):
                    get_registry().counter(
                        "router.pinned_pull_errors").inc()
                for e in es:
                    self._settle(e, exc=exc)
                continue
            self._answer_cc_pinned(es, m)

    def _pinned_forest(self, vec: dict, txn_id: str,
                       remaining: Optional[float]) -> _MergedCC:
        """The merged forest at one transaction vector (LRU-cached,
        cap ``PINNED_MERGED_CAP``). Pulls run SYNCHRONOUSLY on the
        router worker bounded by the requesters' deadlines (else
        ``PINNED_PULL_TIMEOUT_S``) — the client io threads complete
        the futures, so the wait cannot deadlock; a pinned refresh
        deliberately does not share the fresh path's rendezvous
        machinery (its state is per-vector, not per-router)."""
        from ..summaries.forest import merge_forest_tables_host

        key = tuple(sorted(
            (int(s), int(p[0]), str(p[1])) for s, p in vec.items()
        ))
        with self._mlock:
            m = self._pinned_merged.get(key)
            if m is not None:
                self._pinned_merged.move_to_end(key)
                return m
        reg = get_registry()
        # target shards: every current shard EXCEPT a split child
        # whose pinned ancestor is being pulled — a parent-version
        # pin predates the split, so the parent's pinned table is a
        # superset of the rows the child held at that version
        targets: List[tuple] = []
        for s in range(self.nshards):
            rs, _pin = self._pin_route(vec, s)
            if rs != s:
                continue
            targets.append((s, vec.get(s)))
        if remaining is None:
            remaining = PINNED_PULL_TIMEOUT_S
        futs: List[tuple] = []
        for s, pin in targets:
            since, base = -1, None
            if pin is not None and self.delta:
                with self._mlock:
                    pulled = self._pulled_vers[s]
                    if (0 <= pulled < int(pin[0])
                            and self._rows[s] is not None):
                        # the fresh path's carried baseline PRECEDES
                        # the pin: ask for only the rows changed since
                        # it (the shard's ring-backed delta chain
                        # serves historical ``since`` — the PR 17
                        # residual this closes); copy the rows NOW,
                        # under the lock, before the fresh path can
                        # advance them past the baseline we claim
                        since = pulled
                        base = dict(self._rows[s])
            tdoc = None
            if pin is not None:
                tdoc = {"id": str(txn_id),
                        "pin": [int(pin[0]), str(pin[1])]}
            reg.counter("router.pinned_pulls").inc()
            try:
                fut = self._clients[s].submit(
                    SummaryPullQuery(since_version=since),
                    deadline_s=remaining, txn=tdoc,
                )
            except BaseException as exc:
                # deferred, not swallowed: the gather below re-raises
                # it for the whole group (counted here so a dead
                # client still leaves wire-side evidence)
                reg.counter("router.swallowed",
                            site="pinned_pull_submit").inc()
                fut = _FailedFuture(exc)
            futs.append((s, pin, since, base, fut))
        cols: List[tuple] = []
        metas: List[tuple] = []
        vers_sum = 0
        deadline = time.perf_counter() + remaining
        for s, pin, since, base, fut in futs:
            ans = fut.result(max(0.001, deadline - time.perf_counter()))
            dec = decode_pull(ans.value)
            if dec["kind"] == "delta":
                if base is None or dec["base"] != since:
                    raise MalformedPull(
                        "base",
                        f"pinned delta pull base {dec['base']} does "
                        f"not match the carried baseline {since}",
                    )
                rows = base
                rows.update(
                    zip(dec["u"].tolist(), dec["r"].tolist()))
            else:
                rows = dict(
                    zip(dec["u"].tolist(), dec["r"].tolist()))
            u = np.fromiter(rows.keys(), np.int64, len(rows))
            r = np.fromiter(rows.values(), np.int64, len(rows))
            cols.append((u, r))
            metas.append((int(ans.window), int(ans.watermark),
                          int(ans.staleness), int(ans.event_ts)))
            vers_sum += int(pin[0]) if pin is not None \
                else max(0, int(ans.version))
        uniq = np.unique(np.concatenate([c[0] for c in cols])) \
            if cols else np.zeros(0, np.int64)
        n = len(uniq)
        tables = []
        for u, r in cols:
            t = np.arange(n, dtype=np.int64)
            t[np.searchsorted(uniq, u)] = np.searchsorted(uniq, r)
            tables.append(t)
        lab = merge_forest_tables_host(tables)
        sizes = np.bincount(lab, minlength=n) if n else \
            np.zeros(0, np.int64)
        stamped = [m[3] for m in metas if m[3] >= 0]
        meta = (
            min(m[0] for m in metas) if metas else -1,
            sum(m[1] for m in metas),
            max(m[2] for m in metas) if metas else 0,
            vers_sum,
            min(stamped) if stamped else -1,
        )
        m = _MergedCC(uniq, lab, sizes, meta, key)
        with self._mlock:
            self._pinned_merged[key] = m
            self._pinned_merged.move_to_end(key)
            while len(self._pinned_merged) > PINNED_MERGED_CAP:
                self._pinned_merged.popitem(last=False)
        reg.counter("router.pinned_merges").inc()
        return m

    def _answer_cc_pinned(self, entries: List[_Entry],
                          m: _MergedCC) -> None:
        """Answer merged-forest entries from one PINNED forest — the
        :meth:`_answer_cc` lookup semantics, minus the cache (the
        pinned-forest LRU is the reuse path; the router cache serves
        fresh readers) and minus ``_mlock`` (a pinned forest is
        immutable once built — deltas never apply to it)."""
        window, watermark, staleness, version, event_ts = m.meta
        for e in entries:
            q = e.q
            if isinstance(q, ConnectedQuery):
                iu, fu = m.lookup(np.asarray([q.u], np.int64))
                iv, fv = m.lookup(np.asarray([q.v], np.int64))
                if fu[0] and fv[0]:
                    val: object = bool(
                        m.roots(iu)[0] == m.roots(iv)[0])
                else:
                    val = bool(int(q.u) == int(q.v))
            else:
                iv, fv = m.lookup(np.asarray([q.v], np.int64))
                val = int(m.sizes[m.roots(iv)[0]]) if fv[0] else 0
            self._settle(e, ans=Answer(
                value=val, window=window, watermark=watermark,
                staleness=staleness, version=version,
                event_ts=event_ts,
            ))

    @staticmethod
    def _lookup(uniq: np.ndarray, raw: np.ndarray
                ) -> Tuple[np.ndarray, np.ndarray]:
        """(dense index, found mask) of raw ids in the merged id table;
        missing ids index slot 0 with found=False."""
        if len(uniq) == 0:
            z = np.zeros(len(raw), np.int64)
            return z, np.zeros(len(raw), bool)
        i = np.searchsorted(uniq, raw)
        i = np.minimum(i, len(uniq) - 1)
        return i, uniq[i] == raw

    # ------------------------------------------------------------------ #
    # Cache
    # ------------------------------------------------------------------ #
    @staticmethod
    def _cache_key(q: Query) -> tuple:
        if isinstance(q, ConnectedQuery):
            u, v = int(q.u), int(q.v)
            # connectivity is symmetric; one entry serves both orders
            return ("C", min(u, v), max(u, v))
        tag = {DegreeQuery: "D", RankQuery: "R",
               ComponentSizeQuery: "S"}[type(q)]
        return (tag, int(q.v))

    def _cache_get(self, key: tuple,
                   pin: Optional[tuple] = None) -> Optional[Answer]:
        with self._lock:
            entry = self._cache.get(key)
            if entry is None:
                return None
            if pin is not None:
                # pinned lookup (ISSUE 20): serve the entry ONLY when
                # it was computed at exactly the pinned snapshot — an
                # exact (version, boot) compare, never the freshness
                # rules (a pinned hit is deliberately old and must not
                # be invalidated for it; a mismatch is a plain miss,
                # the fan-out answers at the pin)
                if (entry.owner is not None
                        and (entry.ans.version, entry.ans.boot)
                        == (int(pin[0]), str(pin[1]))):
                    self._cache.move_to_end(key)
                    return entry.ans
                return None
            if self.cache_ttl_s is not None and \
                    time.monotonic() - entry.ts > self.cache_ttl_s:
                del self._cache[key]
                self._c_inval.inc()
                return None
        expected = (
            (self._vers[entry.owner],) if entry.owner is not None
            else tuple(self._vers)
        )
        if entry.vers != expected:
            if (entry.owner is None and entry.roots is not None
                    and self.delta and self._revalidate(entry)):
                # the delta history proves every component this answer
                # depends on was untouched by the intervening refreshes
                self._c_retained.inc()
            else:
                # a reply frame observed a newer shard version than
                # this answer was computed from: lazily invalidate
                # (counted) — the next miss re-fans-out / re-pulls at
                # the new version
                with self._lock:
                    self._cache.pop(key, None)
                self._c_inval.inc()
                return None
        with self._lock:
            if key in self._cache:
                self._cache.move_to_end(key)
        return entry.ans

    def _revalidate(self, entry: _CacheEntry) -> bool:
        """Selective invalidation: walk the delta-refresh history from
        the entry's stamp to the carried forest's current stamp. If no
        hop's touched-component set intersects the entry's roots, the
        answer provably still holds — re-stamp it and keep it."""
        with self._mlock:
            m = self._merged
            if m is None or tuple(self._vers) != m.stamp:
                return False   # a refresh is in flight; stay lazy
            v = entry.vers
            hops = 0
            while v != m.stamp:
                nxt = None
                for h in self._delta_hist:
                    if h[0] == v:
                        nxt = h
                        break
                if nxt is None or entry.roots & nxt[2]:
                    return False
                v = nxt[1]
                hops += 1
                if hops > len(self._delta_hist):
                    return False   # defensive: broken chain
            entry.vers = m.stamp
            return True

    def _cache_put(self, key: tuple, ans: Answer, vers: tuple,
                   owner: Optional[int] = None,
                   roots: Optional[frozenset] = None) -> None:
        with self._lock:
            self._cache[key] = _CacheEntry(
                ans, vers, time.monotonic(), owner, roots
            )
            self._cache.move_to_end(key)
            while len(self._cache) > self.cache_cap:
                self._cache.popitem(last=False)

    #: how far BELOW the observed high-water a reply's version may sit
    #: before it reads as a RESTARTED sequence rather than ordinary
    #: answer skew (prefer_ready serves up to READY_LOOKBACK=3 windows
    #: behind head; sweeps add a little more)
    VERSION_RESTART_SLACK = 8

    def _observe_version(self, shard: int, version: int) -> None:
        version = int(version)
        if not version or version == self._vers[shard]:
            return
        with self._mlock:
            cur = self._vers[shard]
            if version > cur:
                self._vers[shard] = version
            elif version + self.VERSION_RESTART_SLACK < cur:
                # a version sequence far below this shard's observed
                # high-water: a promoted standby publishes from a FRESH
                # store whose counter restarts at 1, so monotone
                # ratcheting would pin the old primary's answers in the
                # cache forever. Adopt the new sequence: the version
                # vector changes, so every entry stamped against the
                # old sequence lazily invalidates, and the merged CC
                # forest re-pulls at the new shard's state.
                get_registry().counter(
                    "router.shard_restarts", shard=str(shard)
                ).inc()
                self._vers[shard] = version
                self._pulled_vers[shard] = -1

    # ------------------------------------------------------------------ #
    # Settling
    # ------------------------------------------------------------------ #
    def _expire(self, e: _Entry) -> None:
        from ..resilience.errors import DeadlineExceeded

        get_registry().counter("serving.deadline_expired").inc()
        self._set_exc(e.f, DeadlineExceeded(
            f"{type(e.q).__name__} unanswered after its "
            f"{(e.dl - e.t0):.3f}s deadline"
        ))
        self._finish(e)

    def _settle(self, e: _Entry, ans: Optional[Answer] = None,
                exc: Optional[BaseException] = None) -> None:
        if ans is not None:
            now = time.perf_counter()
            if e.dl is not None and now > e.dl:
                # answered late: honor the deadline over a stale answer
                self._expire(e)
                return
            self._set_res(e.f, ans)
        else:
            self._set_exc(e.f, exc)
        self._finish(e)

    def _finish(self, e: _Entry) -> None:
        with self._lock:
            if e.done:
                return  # the sweep guard may re-settle an entry a
                # callback already answered; account it exactly once
            e.done = True
            self._inflight -= 1
        g = e.grp
        if g is not None and g.done_one():
            _trace.record_span(
                "serving.router.fanout",
                time.perf_counter() - g.t0,
                trace_id=g.ctx.trace_id,
                parent=g.ctx.parent_sid,
                sid=g.sid,
                attrs={
                    "n": g.hits + g.misses,
                    "hits": g.hits,
                    "misses": g.misses,
                    "shards": len(g.shards),
                },
            )

    @staticmethod
    def _set_res(f: Future, ans: Answer) -> None:
        if not f.done():
            try:
                f.set_result(ans)
            except InvalidStateError:
                get_registry().counter(
                    "router.swallowed", site="settle_race"
                ).inc()

    @staticmethod
    def _set_exc(f: Future, exc: BaseException) -> None:
        if not f.done():
            try:
                f.set_exception(exc)
            except InvalidStateError:
                get_registry().counter(
                    "router.swallowed", site="settle_race"
                ).inc()

    # ------------------------------------------------------------------ #
    def close(self, timeout: float = 30.0) -> None:
        """Stop the worker, fail leftovers, close every shard client.
        One budget across all the joins/closes (GL008)."""
        with self._lock:
            if self._closing:
                return
            self._closing = True
        deadline = time.monotonic() + float(timeout)
        self._wake.set()
        self._worker.join(max(0.0, deadline - time.monotonic()))
        with self._lock:
            leftovers = list(self._pending)
            self._pending.clear()
        err = RuntimeError("router closed with the query pending")
        for e in leftovers:
            self._set_exc(e.f, err)
        for c in self._clients:
            c.close()


class _FailedFuture:
    """Minimal already-failed future (submit raised synchronously)."""

    __slots__ = ("_exc",)

    def __init__(self, exc: BaseException):
        self._exc = exc

    def exception(self):
        return self._exc

    def result(self, timeout: Optional[float] = None):
        raise self._exc


# --------------------------------------------------------------------- #
# Shard demo servable (real CC + degrees over a partitioned stream)
# --------------------------------------------------------------------- #
def demo_shard_edges(n_vertices: int, n_edges: int, seed: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """The sharded bench/test stream: deterministic uniform edges, the
    SAME columns in every process that passes the same arguments — the
    property the cross-process oracle identity rests on."""
    rng = np.random.default_rng(seed)
    src = rng.integers(0, n_vertices, n_edges, dtype=np.int64)
    dst = rng.integers(0, n_vertices, n_edges, dtype=np.int64)
    return src, dst


def shard_demo_payloads(
    *,
    n_vertices: int,
    n_edges: int,
    seed: int = 7,
    window: int = 1024,
    shard: int = 0,
    nshards: int = 1,
    pace_s: float = 0.0,
    churn_bumps: int = 0,
    churn_edges: int = 0,
    churn_seed: int = 1000,
    churn_pace_s: float = 0.0,
    churn_gate: Optional[str] = None,
):
    """One shard's servable: fold the edges this shard OWNS
    (:func:`~gelly_streaming_tpu.core.ingest.partition_edges_by_vertex`)
    into a live min-rooted CC forest + degree table, one snapshot per
    count window. ``nshards=1`` is the single-host oracle — the same
    code folding the WHOLE stream, which is what the identity tests and
    the bench baseline serve from.

    After the main stream, ``churn_bumps`` extra versions each fold
    this shard's slice of ``churn_edges`` global edges drawn from
    ``churn_seed`` — a low-rate live-ingest tail the delta-pull churn
    cell measures against. The k-th bump folds global slice
    ``[k*churn_edges, (k+1)*churn_edges)``, so a driver can rebuild the
    identical stream for an oracle check. ``churn_gate`` (a path) holds
    the churn tail until the file EXISTS: the measuring driver touches
    it once its routers are up, so the paced bumps overlap live query
    traffic instead of racing the routers' boot (bounded wait — a
    driver that never touches the gate releases the tail after 120s
    rather than wedging the shard)."""
    from ..datasets import IdentityDict
    from ..core.ingest import partition_edges_by_vertex
    from ..summaries.forest import fold_edges_host

    src, dst = demo_shard_edges(n_vertices, n_edges, seed)
    s, d, _v = partition_edges_by_vertex(src, dst, None, nshards)[shard]
    vd = IdentityDict(n_vertices)
    vd.observe(n_vertices - 1)  # full-keyspace parity (see summary_pull)
    lab = np.arange(n_vertices, dtype=np.int32)
    deg = np.zeros(n_vertices, np.int64)
    done = 0
    for a in range(0, max(1, len(s)), window):
        b = min(a + window, len(s))
        if b > a:
            lab = fold_edges_host(lab, s[a:b], d[a:b])
            deg += np.bincount(s[a:b], minlength=n_vertices)
            deg += np.bincount(d[a:b], minlength=n_vertices)
            done += b - a
        yield {"labels": lab, "deg": deg.copy(), "vdict": vd}, done
        if pace_s:
            time.sleep(pace_s)
    if churn_bumps and churn_edges:
        if churn_gate:
            gate_dl = time.monotonic() + 120.0
            while (not os.path.exists(churn_gate)
                   and time.monotonic() < gate_dl):
                time.sleep(0.02)
        csrc, cdst = demo_shard_edges(
            n_vertices, churn_bumps * churn_edges, churn_seed)
        for k in range(churn_bumps):
            a, b = k * churn_edges, (k + 1) * churn_edges
            cs, cd, _cv = partition_edges_by_vertex(
                csrc[a:b], cdst[a:b], None, nshards)[shard]
            if len(cs):
                lab = fold_edges_host(lab, cs, cd)
                deg += np.bincount(cs, minlength=n_vertices)
                deg += np.bincount(cd, minlength=n_vertices)
                done += len(cs)
            yield {"labels": lab, "deg": deg.copy(), "vdict": vd}, done
            if churn_pace_s:
                time.sleep(churn_pace_s)


# --------------------------------------------------------------------- #
# Router binary (subprocess entry, mirrors rpc.replica_main)
# --------------------------------------------------------------------- #
def router_main(cfg: dict) -> None:
    """The router as a real process. ``cfg`` keys: ``shards`` (one
    address list per shard), ``portfile``, optional ``events`` (ShardSink
    path + ``shard`` label), ``cache``/``cache_cap``/``cache_ttl_s``,
    ``delta`` (pull protocol v2 on/off), ``run_s``, ``meta``.

    ISSUE 19 keys: ``autotune``/``target_wait_s`` (load-aware
    admission), ``reshard`` (split-plan store dir — live ownership
    epoch adoption; the router's own reply frames re-stamp the adopted
    epoch, so clients of a router FLEET converge too)."""
    import json
    import signal

    from ..obs import trace as obs_trace
    from ..obs.cluster import ShardSink
    from .rpc import RpcServer

    sink = None
    if cfg.get("events"):
        sink = ShardSink(cfg["events"], shard=cfg.get("shard"))
        get_registry().add_sink(sink)
        obs_trace.add_sink(sink)
        obs_trace.enable(registry_spans=False)
    kw = {}
    if cfg.get("autotune"):
        kw["autotune"] = True
        if cfg.get("target_wait_s") is not None:
            kw["target_wait_s"] = float(cfg["target_wait_s"])
    if cfg.get("reshard"):
        kw["reshard"] = cfg["reshard"]
    router = ShardRouter(
        cfg["shards"],
        cache=bool(cfg.get("cache", True)),
        cache_cap=int(cfg.get("cache_cap", DEFAULT_CACHE_CAP)),
        cache_ttl_s=cfg.get("cache_ttl_s"),
        max_pending=int(cfg.get("max_pending", 1 << 14)),
        delta=bool(cfg.get("delta", True)),
        **kw,
    )
    rpc = RpcServer(router, epoch=lambda: router._epoch,
                    txn_narrow=False).start()
    if cfg.get("portfile"):
        from ..resilience import integrity

        tmp = cfg["portfile"] + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(rpc.port))
        integrity.replace_atomic(tmp, cfg["portfile"])
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    deadline = time.monotonic() + float(cfg.get("run_s", 600.0))
    while not stop.is_set() and time.monotonic() < deadline:
        stop.wait(0.05)
    meta = dict(router.stats_snapshot(), port=rpc.port)
    rpc.close()
    router.close()
    if cfg.get("meta"):
        with open(cfg["meta"], "w") as f:
            json.dump(meta, f)
    if sink is not None:
        sink.close()
        get_registry().remove_sink(sink)


def spawn_router(cfg: dict):
    """Launch the router binary detached, logging next to its portfile
    (same discipline as :func:`~.rpc.spawn_replica`)."""
    import json
    import os
    import subprocess
    import sys as _sys

    from .rpc import REPO_ROOT

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    log_path = (cfg.get("portfile") or "router") + ".log"
    code = (
        "import sys, json; "
        f"sys.path.insert(0, {REPO_ROOT!r}); "
        "from gelly_streaming_tpu.serving import router; "
        "router.router_main(json.loads(sys.argv[1]))"
    )
    logf = open(log_path, "wb")
    try:
        p = subprocess.Popen(
            [_sys.executable, "-c", code, json.dumps(cfg)],
            stdout=logf, stderr=subprocess.STDOUT, env=env,
        )
    finally:
        logf.close()  # the child holds its own dup of the fd
    p.log_path = log_path
    return p


if __name__ == "__main__":
    import json
    import sys

    if "--router" in sys.argv:
        router_main(json.loads(
            sys.argv[sys.argv.index("--router") + 1]
        ))
        sys.exit(0)
    print(
        "usage: python -m gelly_streaming_tpu.serving.router "
        "--router '<json cfg>'",
        file=sys.stderr,
    )
    sys.exit(2)
