"""Multi-host coordinated barriers + cluster-level supervised recovery.

The reference scales out on Flink's cluster runtime, whose fault
tolerance is asynchronous barrier snapshotting (Carbone et al. 2015, the
Chandy-Lamport refinement): barriers flow through every parallel subtask
at the same stream position, each subtask snapshots its shard, and the
checkpoint coordinator declares the checkpoint complete only once EVERY
subtask has acknowledged — restore then uses exactly one complete
checkpoint, never a mix. This module is that protocol for the repo's
multi-controller SPMD layout (``parallel/multihost.py``), built on the
cluster transport fabric (``gelly_streaming_tpu/fabric``) instead of an
RPC coordinator — every epoch artifact moves through a
:class:`~gelly_streaming_tpu.fabric.Transport` (a bare directory path
coerces to the shared-dir backend, byte-identical to the historical
layout; a socket transport points the same protocol at the exchange
daemon):

- :class:`CoordinatedCheckpoint` (an
  :class:`~gelly_streaming_tpu.aggregate.autockpt.AutoCheckpoint`
  subclass) aligns barriers across processes at the same
  superbatch-aligned window ordinal — every process runs the same
  ``every`` x granularity cadence, so barrier ordinals agree with no
  messages. Each process commits its SHARD's CRC-framed barrier
  (``e<ordinal>.p<pid>.ckpt``) plus a tiny rendezvous record
  (``e<ordinal>.p<pid>.json``: epoch, window ordinal, process id, shard
  container CRC) — the record commit is atomic and per-shard, so the
  commit path never blocks on peers (the "asynchronous" in asynchronous
  barrier snapshotting).
- :func:`select_epoch` is the restore-side coordinator analog: scan the
  rendezvous records, pick the NEWEST epoch for which every one of the
  ``num_processes`` shards has a valid artifact (record readable, shard
  file present, size + CRC matching), and fall back coherently past
  torn or incomplete epochs. Every process runs the same pure scan over
  the same directory, so all restarting processes agree on the epoch
  without talking — and a mixed-epoch restore (shard A from epoch 6,
  shard B from epoch 4) is impossible by construction: the selected
  epoch number IS the restore input for every shard.
- :class:`ClusterSupervisor` is the process-level restart strategy (the
  JobManager's "restart the whole job" policy): it spawns one worker
  process per shard, and when ANY worker dies it terminates the rest
  and relaunches all of them — each relaunched worker re-selects the
  same agreed epoch, restores its shard, and replays with the
  deduplication the in-process
  :class:`~gelly_streaming_tpu.resilience.supervisor.Supervisor`
  already provides.

Every coordination event is visible in the obs registry:
``resilience.coord_commits``, ``resilience.epoch_incomplete`` /
``resilience.epoch_torn`` (epochs skipped during selection),
``resilience.epoch_fallbacks`` (selection passed over a newer damaged
epoch), the ``resilience.epoch_selected`` gauge, and
``resilience.cluster_restarts{reason=...}`` on the supervisor side.
"""

from __future__ import annotations

import json
import os
import re
import time
import zlib
from typing import Callable, List, Optional, Tuple

from ..aggregate.autockpt import AutoCheckpoint
from ..fabric import as_transport
from ..obs.registry import get_registry
from . import integrity as _integrity
from .errors import RestartBudgetExceeded
from .retry import exp_backoff, jittered

#: shard barrier / rendezvous tag name shapes
_SHARD_RE = re.compile(r"^e(\d{8})\.p(\d+)\.json$")


def _shard_tag(epoch: int, pid: int) -> str:
    return f"e{epoch:08d}.p{pid}"


def list_epochs(target) -> List[int]:
    """Every epoch ordinal with at least one rendezvous record in the
    store, ascending. ``target`` is a
    :class:`~gelly_streaming_tpu.fabric.Transport` or a shared
    directory path."""
    names = as_transport(target).list()
    return sorted({
        int(m.group(1)) for m in map(_SHARD_RE.match, names) if m
    })


def read_rendezvous(target, epoch: int, pid: int) -> Optional[dict]:
    """One shard's rendezvous record for ``epoch`` (None when missing or
    unreadable — the caller treats both as an incomplete epoch)."""
    data = as_transport(target).get(_shard_tag(epoch, pid) + ".json")
    if data is None:
        return None
    try:
        return json.loads(data.decode())
    except (ValueError, UnicodeDecodeError):
        return None


def _shard_valid(target, epoch: int, pid: int,
                 rec: dict, num_processes: int,
                 cache: Optional[dict] = None) -> Tuple[bool, str]:
    """Validate one shard's artifact against its rendezvous record:
    geometry (nprocs, epoch == windows_done), artifact presence, size,
    and container CRC. Returns (ok, reason).

    ``cache`` (keyed by locator + store version + the record's promised
    crc/size) memoizes the full-content CRC pass: barriers are
    write-once, so an unchanged artifact version keeps its verdict and
    the per-commit GC / per-restore selection scans do NOT re-read
    every container in the store — the same no-re-read discipline the
    PR-4 hardening applied to the barrier span."""
    if rec.get("nprocs") != num_processes:
        return False, (
            f"rendezvous nprocs={rec.get('nprocs')} != {num_processes}"
        )
    if rec.get("epoch") != epoch or rec.get("windows_done") != epoch:
        # a record whose ordinal disagrees with its epoch slot would
        # stitch shards from different stream positions into one
        # "checkpoint" — exactly the mixed-epoch restore this protocol
        # exists to forbid
        return False, (
            f"rendezvous ordinal {rec.get('windows_done')} disagrees "
            f"with epoch {epoch}"
        )
    tr = as_transport(target)
    tag = _shard_tag(epoch, pid) + ".ckpt"
    st = tr.stat(tag)
    if st is None:
        return False, "shard artifact unreadable: missing"
    if st.size != rec.get("size"):
        return False, (
            f"shard artifact is {st.size} bytes, record promised "
            f"{rec.get('size')}"
        )
    key = (tr.describe(tag), st.version, st.size,
           rec.get("crc"), rec.get("size"))
    if cache is not None and key in cache:
        return cache[key]
    data = tr.get(tag)
    if data is None:
        return False, "shard artifact unreadable: missing"
    if len(data) != rec.get("size"):
        return False, (
            f"shard artifact is {len(data)} bytes, record promised "
            f"{rec.get('size')}"
        )
    if (zlib.crc32(data) & 0xFFFFFFFF) != rec.get("crc"):
        result = (False, "shard container checksum mismatch")
    else:
        result = (True, "")
    if cache is not None:
        cache[key] = result
    return result


def select_epoch(
    target,
    num_processes: int,
    *,
    max_epoch: Optional[int] = None,
    record: bool = True,
    cache: Optional[dict] = None,
) -> Optional[int]:
    """The newest epoch for which EVERY shard has a valid artifact.

    This is the restore-side rendezvous: epochs are scanned newest-first
    and an epoch is selected only when all ``num_processes`` rendezvous
    records exist, agree on the geometry and ordinal, and their shard
    files validate (presence, size, container CRC). Anything less —  a
    process died before committing its shard (incomplete), a shard file
    was torn or bit-rotted (torn) — skips the WHOLE epoch, never a
    subset of its shards, so a restore can never mix epochs. Returns
    None when no complete epoch exists (restart from scratch; correct
    under the at-least-once emission contract).

    The scan is a pure function of the store contents, so every
    restarting process computes the same answer with no coordinator.
    ``record=True`` mirrors each skip into the obs registry
    (``resilience.epoch_incomplete`` / ``resilience.epoch_torn``) and
    counts a ``resilience.epoch_fallbacks`` when the selected epoch is
    not the newest in the store.
    """
    reg = get_registry()
    tr = as_transport(target)
    epochs = [
        e for e in reversed(list_epochs(tr))
        if max_epoch is None or e <= max_epoch
    ]
    for i, epoch in enumerate(epochs):
        missing = []
        torn = []
        for pid in range(num_processes):
            rec = read_rendezvous(tr, epoch, pid)
            if rec is None:
                missing.append(pid)
                continue
            ok, reason = _shard_valid(
                tr, epoch, pid, rec, num_processes, cache=cache
            )
            if not ok:
                torn.append((pid, reason))
        if not missing and not torn:
            if record and i > 0:
                reg.counter("resilience.epoch_fallbacks").inc()
            if record:
                reg.gauge("resilience.epoch_selected").set(epoch)
            return epoch
        if record:
            if torn:
                reg.counter("resilience.epoch_torn").inc()
                for pid, reason in torn:
                    _integrity.record_rejection(
                        tr.describe(_shard_tag(epoch, pid) + ".ckpt"),
                        f"epoch {epoch}: {reason}",
                    )
            else:
                reg.counter("resilience.epoch_incomplete").inc()
    return None


class CoordinatedCheckpoint(AutoCheckpoint):
    """Per-shard barriers aligned across processes, restored by epoch
    rendezvous.

    Every process of the multi-host run constructs one of these over the
    SAME shared ``directory`` with its own ``process_id``; the barrier
    cadence (``every`` x the work's superbatch granularity) is identical
    everywhere, so all processes commit at the same window ordinals —
    the epoch. Committing is per-shard and never waits on peers; restore
    (:meth:`windows_done` / :meth:`run`) selects the newest COMPLETE
    epoch via :func:`select_epoch` and loads only this process's shard
    of it.

    ``keep`` bounds how many of this process's own committed epochs stay
    in the store (each process garbage-collects only its own shard
    artifacts, so a slow peer can never have an epoch deleted out from
    under it by a fast one before the fast one has committed ``keep``
    newer epochs).

    ``transport`` selects the store the epoch artifacts move through —
    any store-backed :class:`~gelly_streaming_tpu.fabric.Transport`
    (None keeps the historical behavior: the shared-dir backend over
    ``directory``, byte-identical layout). ``directory`` stays required
    either way: the inherited single-process machinery keeps its local
    scratch path there.
    """

    def __init__(
        self,
        directory: str,
        *,
        process_id: int,
        num_processes: int,
        every=8,
        keep: int = 3,
        transport=None,
    ):
        if every == "auto":
            # the whole rendezvous protocol rests on every process
            # committing at the SAME ordinals with no messages; a
            # per-process tuner would derive different cadences from
            # each host's own timing noise, after which no epoch is
            # ever complete again — fail loudly instead
            raise ValueError(
                'every="auto" cannot be used with coordinated barriers: '
                "the cadence must be identical on every process for "
                "epochs to align. Pick a fixed `every` (tune it "
                "single-host first if needed) and configure the same "
                "value everywhere."
            )
        os.makedirs(directory, exist_ok=True)
        self.dir = directory
        self.process_id = int(process_id)
        self.num_processes = int(num_processes)
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id {process_id} outside 0..{num_processes - 1}"
            )
        #: the one cluster-exchange handle every epoch artifact moves
        #: through — rendezvous records, shard containers, GC, and the
        #: cadence elections (no code below this seam touches the
        #: shared directory directly)
        self.transport = as_transport(
            directory if transport is None else transport,
            process_id=self.process_id,
            num_processes=self.num_processes,
        )
        #: the epoch the last load selected (None before any load / when
        #: no complete epoch exists) — the number every process agrees on
        self.epoch: Optional[int] = None
        #: memoizes full-content CRC verdicts per file version (barriers
        #: are write-once) so the per-commit GC scan and the restore
        #: selection never re-read an already-verified container
        self._valid_cache: dict = {}
        super().__init__(
            os.path.join(directory, f"shard.p{self.process_id}"),
            every=every, keep=keep,
        )

    def run(self, make_stream, work):
        """``superbatch="auto"`` historically raised here: each process
        learning its own K re-tiles its groups from its host's OWN
        timing noise, barrier-eligible window ordinals diverge, and no
        epoch ever completes. The transport's agreement primitive
        dissolves the conflict — the workload's controller is wrapped
        in :class:`~gelly_streaming_tpu.fabric.ElectedK`, which elects
        ONE process's learned K per epoch through
        :meth:`~gelly_streaming_tpu.fabric.Transport.elect`, so every
        process tiles with the same agreed K and the barriers align by
        construction (see ``fabric/agreement.py`` for why the election
        runs on the packer's call schedule, not the commit clock)."""
        if getattr(work, "superbatch_auto", False):
            self._wire_cadence_agreement(work)
        return super().run(make_stream, work)

    def _wire_cadence_agreement(self, work) -> None:
        """Wrap the workload's local K learner in the agreed-K adapter,
        anchored at THIS attempt's restore epoch. Re-wiring happens on
        every ``run()`` call: a supervisor restart restores from a new
        epoch, and the adapter's election schedule must restart from
        that ordinal (its tags are absolute, so it re-reads the winners
        the pre-failure run persisted)."""
        from ..fabric import ElectedK

        plane = getattr(work, "control", None)
        if plane is None:
            from ..control import default_plane

            plane = default_plane(1)
            work.control = plane
        inner = getattr(plane, "autok", None)
        if inner is None:
            inner = plane  # a bare controller standing in for the plane
        if isinstance(inner, ElectedK):
            inner = inner.inner  # re-anchor, never stack wrappers
        elected = ElectedK(
            inner, self.transport, every=self.every,
            done=self.windows_done(),
        )
        if getattr(plane, "autok", None) is not None:
            plane.autok = elected
        else:
            work.control = elected

    # -- commit side ---------------------------------------------------- #
    def _commit(self, payload: dict) -> str:
        """Commit this shard's barrier for epoch ``windows_done``: the
        CRC-framed container lands first (an atomic transport put),
        then the rendezvous record naming it — the record is the
        shard's commit point, so a kill between the two puts leaves an
        invisible container, never a record pointing at nothing. Peers
        are not consulted: epoch completeness is decided at restore
        time."""
        import pickle

        epoch = payload["windows_done"]
        tag = _shard_tag(epoch, self.process_id)
        data = _integrity.wrap_checksummed(pickle.dumps(payload))
        self.transport.put(tag + ".ckpt", data, overwrite=True)
        rec = {
            "epoch": epoch,
            "windows_done": epoch,
            "process": self.process_id,
            "nprocs": self.num_processes,
            "crc": zlib.crc32(data) & 0xFFFFFFFF,
            "size": len(data),
        }
        self.transport.put(  # shard commit point
            tag + ".json", json.dumps(rec).encode(), overwrite=True
        )
        get_registry().counter("resilience.coord_commits").inc()
        self._gc(epoch)
        return self.transport.describe(tag + ".ckpt")

    def _gc(self, committed_epoch: int) -> None:
        """Drop this process's shard files for epochs older than the
        ``keep``-th newest COMPLETE-AND-VALID epoch. Restorability is
        the deletion gate, not this process's own history: a fast shard
        that trimmed by its own epoch count alone would delete its half
        of the only epochs a slow peer has fully committed — leaving
        the cluster with NO complete epoch to restore from — and
        counting rendezvous records alone would let torn or bit-rotted
        epochs (which :func:`select_epoch` will SKIP at restore)
        advance the floor over the last genuinely loadable ones, the
        same rotate-over-the-good-fallback failure the single-process
        ``_rotate`` was hardened against. Validation is the same
        presence+size+CRC check selection uses; the epoch set on disk
        is bounded (~keep plus stragglers), so the extra pass is cheap.
        With fewer than ``keep`` valid epochs on disk nothing is
        deleted. Unlinks touch OWN files only; peers collect theirs, so
        a torn epoch can only be produced by damage, never by a cleanup
        race."""

        def _restorable(e: int) -> bool:
            for pid in range(self.num_processes):
                rec = read_rendezvous(self.transport, e, pid)
                if rec is None:
                    return False
                ok, _ = _shard_valid(
                    self.transport, e, pid, rec, self.num_processes,
                    cache=self._valid_cache,
                )
                if not ok:
                    return False
            return True

        complete = [
            e for e in list_epochs(self.transport) if _restorable(e)
        ]
        if len(complete) < self.keep:
            return
        floor = complete[-self.keep]
        for e in list_epochs(self.transport):
            if e >= floor:
                continue
            tag = _shard_tag(e, self.process_id)
            for suffix in (".json", ".ckpt"):
                self.transport.delete(tag + suffix)

    def discard(self) -> None:
        """Fresh start for THIS PROCESS's shard: remove its epoch
        barriers and rendezvous records (plus the inherited
        single-process path artifacts) and drop the caches. Peers'
        shards are never touched — each process owns only its own
        artifacts, the same ownership rule :meth:`_gc` follows."""
        for e in list_epochs(self.transport):
            tag = _shard_tag(e, self.process_id)
            for suffix in (".json", ".ckpt"):
                self.transport.delete(tag + suffix)
        self._valid_cache.clear()
        super().discard()

    # -- restore side --------------------------------------------------- #
    def _load(self) -> Optional[dict]:
        """Epoch rendezvous + own-shard read. If the selected epoch's
        own shard fails to unpickle despite a matching container CRC
        (damage between validation and read), the epoch is excluded and
        selection falls back — the fallback is re-selected over the
        whole directory, so it stays an ALL-shards-valid epoch.

        The NO-EPOCH result caches like a found one (base-class
        contract): peers commit concurrently, so two scans in one
        attempt can genuinely disagree — every read between
        ``invalidate()`` calls must return the same answer or the
        supervisor's replay ordinals desynchronize from the restore."""
        if self._cache_valid:
            return self._cache
        ceiling: Optional[int] = None
        while True:
            epoch = select_epoch(
                self.transport, self.num_processes, max_epoch=ceiling,
                cache=self._valid_cache,
            )
            self.epoch = epoch
            if epoch is None:
                self._cache = None
                self._cache_valid = True
                return None
            tag = _shard_tag(epoch, self.process_id) + ".ckpt"
            payload = None
            data = self.transport.get(tag)
            if data is not None:
                st = self.transport.stat(tag)
                origin = self.transport.describe(tag)
                key = (origin, st.version if st else 0, len(data))
                payload = self._barrier_payload(data, origin, key)
            if payload is not None:
                self._cache = payload
                self._cache_valid = True
                return payload
            get_registry().counter("resilience.epoch_torn").inc()
            ceiling = epoch - 1


class ClusterError(RuntimeError):
    """A cluster worker failed in a way the restart policy does not
    cover (unexpected exit code); carries the worker's stderr tail."""


class ClusterSupervisor:
    """Restart-all process supervision over one worker per shard.

    The Flink restart strategy at process granularity: ``spawn(pid,
    attempt)`` launches worker ``pid`` (a ``subprocess.Popen``); when
    any worker exits with a code in ``restart_codes`` (or is killed by
    a signal), the remaining workers are terminated and ALL are
    relaunched — each re-runs the epoch rendezvous and restores from
    the agreed epoch, so the cluster never runs with shards on
    different epochs. Exits outside ``restart_codes`` raise
    :class:`ClusterError` immediately (a deterministic worker bug must
    not burn the restart budget).

    ``before_restart(attempt)`` runs between teardown and relaunch (the
    chaos harness injects its torn-epoch corruption there). Restarts
    are counted as ``resilience.cluster_restarts{reason=...}`` and
    bounded by ``max_restarts`` (then
    :class:`~.errors.RestartBudgetExceeded`), with the supervisor's
    bounded-exponential backoff rule between attempts.

    ``flight_dir`` names the directory the workers' flight recorders
    dump into (each worker installs its own
    :class:`~gelly_streaming_tpu.obs.flight.FlightRecorder`; a
    ``FaultPlan`` kill or an in-worker supervisor restart commits the
    ring there). Every worker death's newly-appeared dumps are
    collected into the failure report: the ``flight_dumps`` list of
    :meth:`run`'s result, and the :class:`ClusterError` message for
    non-restartable deaths — the restart carries its black box.
    """

    def __init__(
        self,
        spawn: Callable,
        num_processes: int,
        *,
        max_restarts: int = 4,
        restart_codes: Tuple[int, ...] = (),
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        jitter: float = 0.0,
        seed: int = 0,
        poll_s: float = 0.02,
        terminate_grace_s: float = 5.0,
        before_restart: Optional[Callable[[int], None]] = None,
        flight_dir: Optional[str] = None,
    ):
        self._spawn = spawn
        self.num_processes = int(num_processes)
        self.max_restarts = int(max_restarts)
        self.restart_codes = set(restart_codes)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.poll_s = float(poll_s)
        self.terminate_grace_s = float(terminate_grace_s)
        self._before_restart = before_restart
        self.flight_dir = flight_dir
        #: restarts performed by the most recent :meth:`run`
        self.restarts = 0
        #: (pid, exit_code) of every worker death that triggered a
        #: restart, in order — the sweep's evidence of WHO was killed
        self.worker_exits: List[Tuple[int, int]] = []
        #: flight-recorder dump paths collected across the run, in
        #: discovery order (newest deaths last)
        self.flight_dumps: List[str] = []

    def _collect_flight_dumps(self) -> List[str]:
        """Newly-appeared dumps in ``flight_dir`` since the last
        collection (the per-death sweep of the workers' black boxes)."""
        if self.flight_dir is None:
            return []
        from ..obs import flight as _flight

        fresh = [
            p for p in _flight.find_dumps(self.flight_dir)
            if p not in self.flight_dumps
        ]
        self.flight_dumps.extend(fresh)
        return fresh

    @staticmethod
    def _describe_dumps(paths: List[str]) -> str:
        """One line per dump for a failure report: path, reason, ring
        size — readable without opening the files."""
        from ..obs import flight as _flight

        out = []
        for p in paths:
            try:
                doc = _flight.read_dump(p)
                out.append(
                    f"{p} (reason={doc.get('reason')}, "
                    f"{doc.get('n_events')} events)"
                )
            except Exception:
                out.append(f"{p} (unreadable)")
        return "; ".join(out)

    def _teardown(self, procs: list) -> None:
        for p in procs:
            if p is not None and p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.terminate_grace_s
        for p in procs:
            if p is None:
                continue
            while p.poll() is None and time.monotonic() < deadline:
                time.sleep(self.poll_s)
            if p.poll() is None:
                p.kill()
                p.wait()

    def run(self) -> dict:
        """Drive the cluster to an all-zero exit; returns
        ``{"restarts": n, "worker_exits": [(pid, rc), ...],
        "flight_dumps": [path, ...]}``."""
        reg = get_registry()
        self.restarts = 0
        self.worker_exits = []
        self.flight_dumps = []
        attempt = 0
        while True:
            procs = [
                self._spawn(pid, attempt)
                for pid in range(self.num_processes)
            ]
            failed: Optional[Tuple[int, int]] = None
            live = set(range(self.num_processes))
            while live and failed is None:
                for pid in sorted(live):
                    rc = procs[pid].poll()
                    if rc is None:
                        continue
                    live.discard(pid)
                    if rc != 0:
                        failed = (pid, rc)
                        break
                if live and failed is None:
                    time.sleep(self.poll_s)
            if failed is None:
                self._collect_flight_dumps()
                return {
                    "restarts": self.restarts,
                    "worker_exits": list(self.worker_exits),
                    "flight_dumps": list(self.flight_dumps),
                }
            pid, rc = failed
            self.worker_exits.append((pid, rc))
            # a signal death (negative rc) is environmental; a listed
            # code is an expected injected kill; anything else is a
            # worker bug and restarting would loop on it
            transient = rc < 0 or rc in self.restart_codes
            self._teardown(procs)
            # the dead worker's black box: collected AFTER teardown so
            # a dump committed in its dying instants is on disk
            fresh_dumps = self._collect_flight_dumps()
            if not transient:
                # spawners that pipe stderr expose it on the Popen;
                # spawners that redirect to a log file (the in-repo
                # chaos spawner — pipes could deadlock a terminated
                # worker) advertise the path as ``proc.log_path``
                err = b""
                if procs[pid].stderr is not None:
                    try:
                        err = procs[pid].stderr.read() or b""
                    except Exception:
                        # diagnostics collection on an already-failed
                        # worker: the ClusterError below still raises,
                        # just without a tail — record the gap
                        get_registry().counter(
                            "resilience.swallowed",
                            site="worker_stderr_read",
                        ).inc()
                elif getattr(procs[pid], "log_path", None):
                    try:
                        with open(procs[pid].log_path, "rb") as f:
                            err = f.read()
                    except OSError:
                        pass
                if isinstance(err, str):
                    err = err.encode()
                raise ClusterError(
                    f"worker {pid} exited rc={rc} (not a restartable "
                    f"code): {err[-2000:].decode(errors='replace')}"
                    + (f"\nflight dumps: "
                       f"{self._describe_dumps(fresh_dumps)}"
                       if fresh_dumps else "")
                )
            if self.restarts >= self.max_restarts:
                raise RestartBudgetExceeded(
                    f"{self.restarts} cluster restarts exhausted "
                    f"(worker {pid} rc={rc})"
                    + (f"; flight dumps: "
                       f"{self._describe_dumps(self.flight_dumps)}"
                       if self.flight_dumps else "")
                )
            self.restarts += 1
            reg.counter(
                "resilience.cluster_restarts",
                reason="kill" if rc in self.restart_codes else "signal",
            ).inc()
            delay = jittered(
                exp_backoff(
                    self.restarts - 1, self.backoff_base_s,
                    self.backoff_max_s,
                ),
                self.jitter, self.seed, self.restarts - 1,
            )
            if delay > 0:
                time.sleep(delay)
            if self._before_restart is not None:
                self._before_restart(self.restarts)
            attempt += 1
