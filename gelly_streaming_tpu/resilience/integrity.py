"""Checkpoint integrity: content checksums + atomic commit helpers.

Flink's recovery guarantee rests on checkpoints that are either fully
committed or invisible (asynchronous barrier snapshotting — Carbone et
al.); the repo's numpy/pickle snapshots must earn the same property on a
plain filesystem. Two primitives provide it:

- **Atomic commit**: every artifact is written to a temp name in the
  same directory and moved into place with ``os.replace`` — a kill at
  any byte leaves either the previous committed file or none, never a
  half-written one under the live name. Multi-file checkpoints order
  their replaces so ONE file is the commit point (``save_pytree``
  commits on the ``.json`` sidecar; the ``.npz`` alone is not a
  checkpoint).
- **Content checksums**: CRC32 over the payload bytes, validated at
  load. Catches the failure atomic rename cannot: bit rot, a partial
  copy from another host, or a deliberately corrupted file (the chaos
  harness's flip-byte fault). Rejection raises
  :class:`~gelly_streaming_tpu.resilience.errors.CheckpointCorrupt`
  and is RECORDED — every rejected artifact increments
  ``resilience.ckpt_rejected`` in the obs registry, so "zero torn loads"
  is a checkable property of a run's event log, not a hope.

The checksummed single-file container (:func:`wrap_checksummed` /
:func:`unwrap_checksummed`) frames arbitrary payload bytes as
``magic | crc32 | length | payload``; files without the magic are passed
through untouched so pre-resilience checkpoints keep loading.
"""

from __future__ import annotations

import os
import struct
import warnings
import zlib

from ..obs.registry import get_registry
from .errors import CheckpointCorrupt

#: container magic for checksummed single-file artifacts (version 1)
MAGIC = b"GSCKPT1\n"

_HEADER = struct.Struct("<II")  # crc32, payload length


def arrays_crc32(arrays) -> int:
    """CRC32 over the raw bytes of numpy arrays, in iteration order.

    This is the pytree checkpoint's CONTENT checksum: computed from the
    in-memory arrays at save time (no re-read of the just-written file
    inside the barrier's serialize span) and from the loaded arrays at
    restore time (which are materialized anyway) — one sequential pass
    either way, never a second trip through a multi-GB ``.npz``.
    """
    import numpy as np

    crc = 0
    for a in arrays:
        a = np.ascontiguousarray(a)
        crc = zlib.crc32(memoryview(a).cast("B"), crc)
    return crc & 0xFFFFFFFF


def replace_atomic(tmp: str, path: str) -> None:
    """Alias for ``os.replace`` kept here so commit points read as what
    they are at call sites (``integrity.replace_atomic(tmp, json_path)``
    is the barrier commit)."""
    os.replace(tmp, path)


def wrap_checksummed(payload: bytes) -> bytes:
    """Frame payload bytes as ``MAGIC | crc32 | length | payload``."""
    return MAGIC + _HEADER.pack(zlib.crc32(payload) & 0xFFFFFFFF,
                                len(payload)) + payload


def unwrap_checksummed(data: bytes, *, origin: str = "checkpoint") -> bytes:
    """Validate and strip the checksummed container.

    Data not starting with :data:`MAGIC` is returned unchanged (legacy
    artifact — rename-atomicity is its only guarantee, as before).
    A present-but-wrong frame (truncated payload, checksum mismatch)
    raises :class:`CheckpointCorrupt`.
    """
    if not data.startswith(MAGIC):
        return data
    head_end = len(MAGIC) + _HEADER.size
    if len(data) < head_end:
        raise CheckpointCorrupt(f"{origin}: truncated container header")
    crc, length = _HEADER.unpack(data[len(MAGIC):head_end])
    payload = data[head_end:]
    if len(payload) != length:
        raise CheckpointCorrupt(
            f"{origin}: payload is {len(payload)} bytes, header promised "
            f"{length} (truncated or over-written file)"
        )
    if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
        raise CheckpointCorrupt(f"{origin}: payload checksum mismatch")
    return payload


def record_rejection(path: str, reason: str) -> None:
    """One rejected checkpoint artifact: bump the obs counter (the chaos
    harness's evidence stream) and warn — rejection is a recovery event
    an operator should see, not a silent branch."""
    get_registry().counter("resilience.ckpt_rejected").inc()
    warnings.warn(
        f"rejected checkpoint artifact {path}: {reason}",
        RuntimeWarning,
        stacklevel=2,
    )
