"""Bounded exponential backoff + the serving tier's retry policy.

One shared delay rule (:func:`exp_backoff`) feeds every retry loop in
the resilience layer — supervisor restarts, socket reconnects, and
client-side :class:`RetryPolicy` for ``Overloaded`` serving rejections —
so the backoff shape is tested once and read the same everywhere:
``min(max_s, base_s * 2**attempt)``, plus multiplicative jitter where a
thundering herd is possible.

Jitter is DETERMINISTIC per ``(seed, attempt)``: chaos runs must replay
identically, so nothing here reads global randomness or the clock.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional


def exp_backoff(attempt: int, base_s: float, max_s: float) -> float:
    """Capped exponential delay for the ``attempt``-th retry (0-based)."""
    return min(float(max_s), float(base_s) * (2.0 ** int(attempt)))


def jittered(delay_s: float, jitter: float, seed: int, attempt: int) -> float:
    """Multiply ``delay_s`` by ``1 + jitter * u`` with ``u`` drawn
    deterministically from ``(seed, attempt)`` — spread without losing
    replayability (int-tuple hashes are not salted across processes)."""
    if jitter <= 0:
        return delay_s
    u = random.Random(hash((int(seed), int(attempt)))).random()
    return delay_s * (1.0 + float(jitter) * u)


@dataclass(frozen=True)
class RetryPolicy:
    """Client-side retry budget for :class:`~gelly_streaming_tpu.serving.server.Overloaded`.

    ``attempts`` is the number of RETRIES after the first try; each
    waits ``exp_backoff(i, base_s, max_s)`` (jittered) before re-asking
    admission. Shed rejections (:class:`~gelly_streaming_tpu.serving.server.Shed`)
    are never retried — shedding exists to LOSE that traffic, and a
    retrying client would defeat it.
    """

    attempts: int = 3
    base_s: float = 0.01
    max_s: float = 0.5
    jitter: float = 0.5
    seed: int = 0

    def delay_s(self, attempt: int) -> Optional[float]:
        """Delay before retry ``attempt`` (0-based), or None when the
        budget is spent."""
        if attempt >= self.attempts:
            return None
        return jittered(
            exp_backoff(attempt, self.base_s, self.max_s),
            self.jitter, self.seed, attempt,
        )

    def delay_before(
        self, attempt: int, remaining_s: Optional[float] = None
    ) -> Optional[float]:
        """:meth:`delay_s` clamped to a remaining deadline budget.

        The RPC client retries ``Overloaded`` wire rejections under a
        per-query deadline; sleeping past the budget would turn a
        would-be answer into a guaranteed ``DeadlineExceeded``, so the
        delay is capped at ``remaining_s`` and a spent budget returns
        None (give up NOW, fail the deadline cleanly) — same contract
        shape as :meth:`delay_s`."""
        d = self.delay_s(attempt)
        if d is None or remaining_s is None:
            return d
        if remaining_s <= 0:
            return None
        return min(d, float(remaining_s))
