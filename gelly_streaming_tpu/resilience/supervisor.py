"""Supervised recovery: restart a checkpointed pipeline until it finishes.

Flink's runtime pairs its checkpoint coordinator with a RESTART
strategy — a failed job restores the last barrier and replays, with the
restart budget and backoff as first-class configuration. The repo's
:class:`~gelly_streaming_tpu.aggregate.autockpt.AutoCheckpoint` covers
the barrier/restore half; this module adds the supervision half:

- :meth:`Supervisor.run` drives ``AutoCheckpoint.run(make_stream, work)``
  and, on failure, CLASSIFIES the exception (transient environment
  fault vs. poison window vs. fatal), restores from the newest valid
  barrier, and retries under bounded exponential backoff with
  deterministic jitter.
- Replayed windows that were already emitted before the failure are
  DEDUPLICATED: the consumer sees each window ordinal exactly once per
  process, in order. (Replay is value-identical by the checkpoint
  contract — the chaos harness asserts it — so suppression loses
  nothing; across a real process kill the at-least-once contract of the
  module doc in ``autockpt.py`` still applies.)
- A window that keeps failing across ``poison_limit`` consecutive
  restores is declared :class:`~.errors.PoisonWindowError` instead of
  burning the whole restart budget on data that will never fold.

Recovery telemetry flows into the obs registry:
``resilience.restarts{kind=...}``, ``resilience.deduped_windows``,
``resilience.backoff_s``, ``resilience.poison_windows``, and a
``resilience.recovery_seconds`` histogram (failure to first
post-restart emission — the number the chaos bench distributes).

Pass ``work`` as a ZERO-ARG FACTORY when possible: a freshly built
workload plus barrier restore is guaranteed clean, whereas reusing one
object relies on its ``restore_state``/``load_state_dict`` fully
overwriting mid-window wreckage (true for the repo's aggregations, but
the factory needs no such audit). A non-callable ``work`` is deep-copied
once up front so a failure BEFORE the first barrier can still restart
from pristine state.
"""

from __future__ import annotations

import copy
import time
from typing import Any, Callable, Iterator, Optional

from ..obs import flight as _flight
from ..obs.registry import get_registry
from .errors import (
    InjectedFault,
    PoisonWindowError,
    RestartBudgetExceeded,
    StallError,
    TransientSourceError,
)
from .retry import exp_backoff, jittered


class Supervisor:
    """Run a checkpointed workload to completion through failures.

    Parameters
    ----------
    checkpoint:
        An :class:`~gelly_streaming_tpu.aggregate.autockpt.AutoCheckpoint`
        or a path (one is constructed with default cadence).
    max_restarts:
        Total restart budget across the run; exceeding it raises
        :class:`~.errors.RestartBudgetExceeded` chaining the last error.
    poison_limit:
        Consecutive failures AT THE SAME window ordinal (for
        window-classified errors) before
        :class:`~.errors.PoisonWindowError` is raised.
    backoff_base_s / backoff_max_s / jitter / seed:
        Bounded exponential backoff between restarts, deterministic in
        ``seed`` (see :mod:`~gelly_streaming_tpu.resilience.retry`).
    classify:
        Optional ``exc -> "transient" | "window" | "fatal"`` override.
    sleep:
        Injection point for tests (defaults to ``time.sleep``).
    """

    #: never caught: the process is coming down or the consumer closed us
    FATAL = (KeyboardInterrupt, SystemExit, GeneratorExit, MemoryError)

    #: environment faults: restart is expected to succeed, so repeated
    #: hits at one ordinal spend restart budget, not poison count
    TRANSIENT = (
        TransientSourceError,
        StallError,
        InjectedFault,
        ConnectionError,
        TimeoutError,
    )

    def __init__(
        self,
        checkpoint,
        *,
        max_restarts: int = 8,
        poison_limit: int = 3,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        jitter: float = 0.5,
        seed: int = 0,
        classify: Optional[Callable[[BaseException], str]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if isinstance(checkpoint, str):
            from ..aggregate.autockpt import AutoCheckpoint

            checkpoint = AutoCheckpoint(checkpoint)
        self.ckpt = checkpoint
        self.max_restarts = int(max_restarts)
        self.poison_limit = int(poison_limit)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self._classify = classify or self.default_classify
        self._sleep = sleep
        #: restarts performed by the most recent :meth:`run`
        self.restarts = 0

    # ------------------------------------------------------------------ #
    @classmethod
    def default_classify(cls, e: BaseException) -> str:
        if isinstance(e, cls.FATAL):
            return "fatal"
        if isinstance(e, cls.TRANSIENT):
            return "transient"
        return "window"

    # ------------------------------------------------------------------ #
    def run(self, make_stream: Callable, work) -> Iterator[Any]:
        """Yield the workload's per-window emissions exactly as an
        uninterrupted ``AutoCheckpoint.run`` would, surviving restarts.

        ``make_stream(vdict)`` must rebuild the stream over the SAME
        source each attempt (the ``AutoCheckpoint.run`` contract);
        ``work`` is a workload/aggregation or a zero-arg factory for
        one (preferred — see module doc).
        """
        factory = work if callable(work) else None
        pristine = None if factory is not None else copy.deepcopy(work)
        current = factory() if factory is not None else work
        reg = get_registry()
        self.restarts = 0
        emitted = 0          # next ordinal the consumer has NOT seen
        fail_ordinal = None  # poison tracking
        fail_count = 0
        t_fail = None        # set at failure, cleared on first emission
        while True:
            # re-scan the disk on every attempt: between a failure and
            # its restart the barrier set may have changed under us (a
            # coordinated peer committed or tore an epoch, the chaos
            # harness corrupted the head) — restarting from a cached
            # pre-failure payload could silently resurrect damage or,
            # in the multi-host layout, restore a different epoch than
            # the peers agree on
            inv = getattr(self.ckpt, "invalidate", None)
            if inv is not None:
                inv()
            done = self.ckpt.windows_done()
            ordinal = done
            try:
                for em in self.ckpt.run(make_stream, current):
                    if ordinal >= emitted:
                        if t_fail is not None:
                            reg.histogram(
                                "resilience.recovery_seconds"
                            ).observe(time.perf_counter() - t_fail)
                            t_fail = None
                        yield em
                        emitted = ordinal + 1
                    else:
                        # replayed pre-failure window: value-identical
                        # by the checkpoint contract, suppressed so the
                        # consumer sees each ordinal once
                        reg.counter("resilience.deduped_windows").inc()
                    ordinal += 1
                return
            except self.FATAL:
                raise
            except BaseException as e:
                kind = self._classify(e)
                # every failure commits the black box BEFORE any
                # restart decision: the ring holds the events that led
                # here, and the dump path rides the failure report
                # (PoisonWindowError / RestartBudgetExceeded) so a
                # post-mortem starts from telemetry, not from grep
                dump_path = _flight.dump_installed(
                    f"supervisor:{kind}",
                    ordinal=ordinal,
                    error=repr(e)[:200],
                )
                if dump_path is not None:
                    reg.counter("resilience.flight_dumps").inc()
                if kind == "fatal":
                    raise
                # poison counting tracks WINDOW-classified failures
                # only: transient flaps at the same ordinal (a source
                # down across several restarts) spend restart budget,
                # never poison count — mixing them would condemn a
                # window for its environment's sins
                if kind == "window":
                    if ordinal == fail_ordinal:
                        fail_count += 1
                    else:
                        fail_ordinal, fail_count = ordinal, 1
                    if fail_count >= self.poison_limit:
                        reg.counter("resilience.poison_windows").inc()
                        raise PoisonWindowError(ordinal, fail_count) from e
                if self.restarts >= self.max_restarts:
                    raise RestartBudgetExceeded(
                        f"{self.restarts} restarts exhausted at window "
                        f"{ordinal} ({kind}: {e!r})"
                        + (f"; flight dump: {dump_path}"
                           if dump_path else "")
                    ) from e
                attempt = self.restarts
                self.restarts += 1
                reg.counter("resilience.restarts", kind=kind).inc()
                delay = jittered(
                    exp_backoff(
                        attempt, self.backoff_base_s, self.backoff_max_s
                    ),
                    self.jitter, self.seed, attempt,
                )
                reg.counter("resilience.backoff_s").inc(delay)
                t_fail = time.perf_counter()
                if delay > 0:
                    self._sleep(delay)
                current = self._fresh_work(factory, pristine, current)

    # ------------------------------------------------------------------ #
    def _fresh_work(self, factory, pristine, current):
        if factory is not None:
            return factory()
        # decide from the disk's CURRENT barrier state, not the attempt's
        # cached payload: if every barrier was destroyed between the
        # failure and this restart, the next attempt restores nothing —
        # reusing the mutated object then would run mid-window wreckage
        # as if it were pristine state
        inv = getattr(self.ckpt, "invalidate", None)
        if inv is not None:
            inv()
        if self.ckpt.windows_done() > 0:
            # the barrier restore inside AutoCheckpoint.run overwrites
            # the carried state wholesale (restore_state /
            # load_state_dict), so the mutated object is safe to reuse
            return current
        return copy.deepcopy(pristine)
