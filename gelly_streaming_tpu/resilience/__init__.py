"""Resilience layer: checkpoint integrity, supervised recovery, chaos.

The reference inherits fault tolerance wholesale from Flink — barrier
snapshots (Carbone et al., lightweight asynchronous snapshots) plus a
restart strategy — and never has to prove it; the runtime does. This
repo's checkpoint surface (``aggregate/checkpoint.py`` +
``AutoCheckpoint``) reproduced the snapshots but, before this layer,
nothing guaranteed they SURVIVE real failures: a kill between the two
files of a pytree checkpoint left a torn pair, a socket source died
permanently on its first disconnect, and an ``Overloaded`` serving
rejection had no retry or shed story. This package closes that gap in
three parts, in the MillWheel spirit that recovery is a tested property:

- :mod:`integrity` — content checksums + atomic multi-file commit for
  checkpoint artifacts; every rejected artifact is visible as
  ``resilience.ckpt_rejected`` in the obs registry.
- :mod:`supervisor` (+ :mod:`retry`, :mod:`errors`) — restart a
  checkpointed pipeline from the newest valid barrier under bounded
  exponential backoff, classify failures (transient / poison window /
  fatal), and deduplicate replayed emissions; the bounded-backoff rule
  is shared with socket reconnect and the serving tier's client
  ``RetryPolicy``.
- :mod:`faults` + :mod:`chaos` — a seeded deterministic
  :class:`FaultPlan` behind test-only hook points (pipeline, sources,
  checkpoints, serving worker) and the kill-at-every-window sweep
  (``bench.py --chaos``) that asserts oracle-identical recovery.
- :mod:`coordinated` — the DISTRIBUTED half (ISSUE 5): per-shard epoch
  barriers aligned across processes with a restore-side rendezvous
  (newest epoch valid across ALL shards; mixed-epoch restores
  impossible by construction) and :class:`ClusterSupervisor`
  restart-all process supervision; the multi-process chaos sweep
  (``bench.py --chaos --multiprocess``) kills one worker of N at every
  window ordinal and demands oracle-identical recovery with
  byte-identical vertex dictionaries. Serving-side failover lives in
  :mod:`gelly_streaming_tpu.serving.failover`.

Resilience telemetry rides the PR-3 obs registry:
``resilience.restarts{kind=...}``, ``resilience.ckpt_rejected``,
``resilience.recovery_seconds``, ``resilience.deduped_windows``,
``resilience.fault_injected{site=...}``, ``pipeline.producer_leaked``,
``pipeline.stalls``, ``source.malformed_lines``, ``source.reconnects``,
``serving.shed{cls=...}``, ``serving.retries``,
``serving.deadline_expired``, ``serving.worker_stalls``.
"""

from . import faults
from .coordinated import (
    ClusterError,
    ClusterSupervisor,
    CoordinatedCheckpoint,
    select_epoch,
)
from .errors import (
    CheckpointCorrupt,
    DeadlineExceeded,
    InjectedFault,
    PoisonWindowError,
    RestartBudgetExceeded,
    SimulatedCrash,
    StallError,
    TransientSourceError,
)
from .faults import FaultPlan
from .retry import RetryPolicy, exp_backoff, jittered
from .supervisor import Supervisor

__all__ = [
    "CheckpointCorrupt",
    "ClusterError",
    "ClusterSupervisor",
    "CoordinatedCheckpoint",
    "DeadlineExceeded",
    "FaultPlan",
    "InjectedFault",
    "PoisonWindowError",
    "RestartBudgetExceeded",
    "RetryPolicy",
    "SimulatedCrash",
    "StallError",
    "Supervisor",
    "TransientSourceError",
    "exp_backoff",
    "faults",
    "jittered",
    "select_epoch",
]
