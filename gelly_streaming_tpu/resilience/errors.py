"""Resilience-layer exception taxonomy.

One small module so every other layer (checkpointing, pipeline, sources,
serving, supervision) can share failure types without import cycles:
nothing here imports anything from the repo.

The taxonomy mirrors what the supervisor classifies
(:mod:`~gelly_streaming_tpu.resilience.supervisor`):

- **transient** — the environment hiccupped (source disconnect, stalled
  prefetch, injected crash); restarting from the last barrier is
  expected to succeed. :class:`TransientSourceError`, :class:`StallError`,
  :class:`InjectedFault`.
- **poison** — the same window keeps failing across restarts: the DATA
  (or a bug it tickles) is at fault, and retrying forever would loop.
  :class:`PoisonWindowError`.
- **fatal** — the process must not continue (interpreter shutdown,
  memory exhaustion) or the recovery budget is spent
  (:class:`RestartBudgetExceeded`).

:class:`CheckpointCorrupt` marks an artifact that failed integrity
validation (checksum, leaf count, structure) — raised at LOAD time so a
torn snapshot can never be silently restored into live state.
"""

from __future__ import annotations


class CheckpointCorrupt(ValueError):
    """A checkpoint artifact failed integrity validation (truncated file,
    checksum mismatch, leaf count disagreeing with its sidecar). Subclass
    of ``ValueError`` so pre-existing ``load_pytree`` rejection handling
    keeps working."""


class TransientSourceError(ConnectionError):
    """A live source gave up after its own bounded reconnect budget; the
    supervisor may restart the whole pipeline (which re-builds the
    source) with backoff."""


class StallError(RuntimeError):
    """A watchdog fired: a pipeline stage stopped making progress (the
    prefetch queue stayed empty past ``stall_timeout_s`` with the
    producer still alive, i.e. wedged rather than slow)."""


class PoisonWindowError(RuntimeError):
    """The same window ordinal failed ``poison_limit`` consecutive
    recovery attempts — the failure deterministically follows the data,
    so restarting again would loop forever. Carries ``ordinal``; the
    triggering exception chains via ``__cause__``."""

    def __init__(self, ordinal: int, attempts: int):
        super().__init__(
            f"window {ordinal} failed {attempts} consecutive recovery "
            "attempts; classifying as poison (not restarting again)"
        )
        self.ordinal = int(ordinal)
        self.attempts = int(attempts)


class RestartBudgetExceeded(RuntimeError):
    """The supervisor's ``max_restarts`` budget is spent; the last
    failure chains via ``__cause__``."""


class InjectedFault(RuntimeError):
    """Base class for failures raised by the deterministic fault plan
    (:mod:`~gelly_streaming_tpu.resilience.faults`). Test-only traffic;
    classified as transient by the default supervisor policy."""


class SimulatedCrash(InjectedFault):
    """An in-process stand-in for a process kill: raised by the fault
    plan's kill point so a single test process can exercise the
    crash/restore loop without forking."""


class DeadlineExceeded(TimeoutError):
    """A served query's per-query deadline expired before the worker
    answered it (the query was admitted, then shed at answer time)."""
