"""Deterministic fault injection: a seeded plan behind test-only hooks.

Production stream engines treat recovery as a TESTED property, not a
code path that exists (MillWheel's idempotent-replay guarantee was
proven by killing workers, not by reading the code). This module is the
repo's kill switchboard: a :class:`FaultPlan` describes exactly which
fault fires where — kill after window k, corrupt the barrier committed
at window b, disconnect the socket at record n, drop/duplicate/swap
specific source records, stall a consumer — and hook points threaded
through ``core/pipeline.py``, ``core/sources.py``,
``aggregate/autockpt.py`` and ``serving/server.py`` consult it.

Everything is deterministic: faults fire on exact indices (window
ordinal, record ordinal, barrier watermark), and the only randomness —
the corruption byte offset — derives from the plan's ``seed``. Running
the same plan twice produces byte-identical failure sequences, which is
what lets the chaos sweep (``bench.py --chaos``) assert ORACLE-IDENTICAL
recovery at every kill point instead of "it didn't crash".

Hook-point cost when disarmed is one module-attribute check
(``faults.active()`` is ``_PLAN is not None``); no plan object, index
arithmetic, or registry lookup happens on production runs.

Usage::

    plan = FaultPlan(kill_at_window=5)
    with faults.injected(plan):
        ...            # SimulatedCrash fires after window 5, once

Every fired fault increments ``resilience.fault_injected{site=...}`` in
the obs registry so a chaos run's event log records what was done to it
alongside how it recovered.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Iterator, Optional, Tuple

from ..obs.registry import get_registry
from .errors import SimulatedCrash


@dataclass
class FaultPlan:
    """One deterministic failure schedule. All indices are 0-based
    ordinals counted at the hook site (window ordinal for kills, record
    ordinal for source faults, ``windows_done`` for barrier corruption).

    ``kill_at_window`` fires at the ``kill_site`` hook ONLY (default
    ``chaos.window``, the harness drive loop; ``pipeline.item`` kills
    at a prefetch-item ordinal instead — note that under superbatching
    those are GROUP indices, not window indices, so the two sites count
    different things and a kill must name the one it means):
    :class:`SimulatedCrash` when ``kill_exit_code`` is None (the
    in-process crash the supervisor recovers from), else ``os._exit``
    (the real process kill the chaos workers use). Kills are ONE-SHOT:
    after restart the replayed ordinal passes the hook again, and
    re-firing would turn every kill test into a poison-window loop.
    """

    seed: int = 0
    # -- kill / stall (pipeline sites) --------------------------------- #
    kill_at_window: Optional[int] = None
    kill_site: str = "chaos.window"
    kill_exit_code: Optional[int] = None
    stall_site: Optional[str] = None       # e.g. "serving.worker"
    stall_at_index: int = 0
    stall_s: float = 0.0
    # -- rpc wire faults (frame ordinals on the socket path) ------------ #
    rpc_disconnect_at_frame: Optional[int] = None
    rpc_truncate_at_frame: Optional[int] = None
    # -- source perturbation (record ordinals) ------------------------- #
    disconnect_at_record: Optional[int] = None
    drop_records: Tuple[int, ...] = ()
    duplicate_records: Tuple[int, ...] = ()
    swap_records: Tuple[int, ...] = ()     # swap record i with record i+1
    # -- event-time skew (ISSUE 18): jitter record i's timestamp field
    # by a deterministic bounded offset in [-skew_ts_s, +skew_ts_s],
    # derived from (seed, i) — the out-of-order-ARRIVAL analog of
    # swap_records, testing watermark/lateness handling instead of
    # delivery order. ``skew_ts_field`` indexes the ts inside the
    # record tuple (-1 = last element, the ``(s, d, v, ts)`` shape)
    skew_records: Tuple[int, ...] = ()
    skew_ts_s: int = 0
    skew_ts_field: int = -1
    # -- checkpoint corruption ----------------------------------------- #
    corrupt_at_barrier: Optional[int] = None
    corrupt_mode: str = "flip"             # "flip" | "truncate"
    # -- one-shot bookkeeping (mutable run state) ----------------------- #
    _fired: set = field(default_factory=set, repr=False)

    def perturbs_records(self) -> bool:
        return bool(
            self.drop_records or self.duplicate_records
            or self.swap_records or self.skew_records
        )

    # ------------------------------------------------------------------ #
    def _once(self, key) -> bool:
        if key in self._fired:
            return False
        self._fired.add(key)
        return True

    def _count(self, site: str) -> None:
        get_registry().counter(
            "resilience.fault_injected", site=site
        ).inc()

    def fire(self, site: str, *, index: Optional[int] = None,
             path: Optional[str] = None) -> None:
        """Consult the plan at one hook point; may sleep, raise, corrupt
        a file, or kill the process. No-op for sites/indices the plan
        does not name."""
        if (
            self.stall_site == site
            and (index or 0) == self.stall_at_index
            and self.stall_s > 0
            and self._once(("stall", site, index))
        ):
            self._count(site)
            time.sleep(self.stall_s)
        if site == self.kill_site:
            if (
                self.kill_at_window is not None
                and index == self.kill_at_window
                and self._once(("kill", self.kill_at_window))
            ):
                self._count(site)
                # the black box goes down WITH the plane: an os._exit
                # kill gives no later hook, so the installed flight
                # recorder (if any) commits its ring right here —
                # the fault_injected count above is the last event in it
                from ..obs import flight as _flight

                _flight.dump_installed(
                    f"fault_kill:{site}", index=index,
                )
                if self.kill_exit_code is not None:
                    os._exit(self.kill_exit_code)
                raise SimulatedCrash(
                    f"injected kill after window {index} ({site})"
                )
        elif site == "rpc.frame":
            # the serving RPC read paths (server handler + client
            # reader) consult this after every complete frame: a
            # mid-stream disconnect is the wire analog of
            # disconnect_at_record, counted at the same one-shot
            # discipline (frame ordinals are per-connection)
            if (
                self.rpc_disconnect_at_frame is not None
                and index == self.rpc_disconnect_at_frame
                and self._once(("rpc_disconnect", index))
            ):
                self._count(site)
                raise ConnectionResetError(
                    f"injected disconnect at frame {index}"
                )
        elif site == "source.record":
            if (
                self.disconnect_at_record is not None
                and index == self.disconnect_at_record
                and self._once(("disconnect", index))
            ):
                self._count(site)
                raise ConnectionResetError(
                    f"injected disconnect at record {index}"
                )
        elif site == "checkpoint.committed":
            if (
                self.corrupt_at_barrier is not None
                and index == self.corrupt_at_barrier
                and path is not None
                and self._once(("corrupt", index))
            ):
                self._count(site)
                corrupt_file(path, self.corrupt_mode, seed=self.seed)

    # ------------------------------------------------------------------ #
    def truncate_frame(self, index: Optional[int]) -> bool:
        """True when the RPC send path should commit only HALF of frame
        ``index`` and drop the connection — the torn-write shape on the
        wire (the socket analog of ``corrupt_mode="truncate"``).
        One-shot, counted as site ``rpc.send``; a pure query, so the
        send path stays in charge of its own socket teardown."""
        if (
            self.rpc_truncate_at_frame is not None
            and index == self.rpc_truncate_at_frame
            and self._once(("rpc_truncate", index))
        ):
            self._count("rpc.send")
            return True
        return False

    # ------------------------------------------------------------------ #
    def perturb_records(self, records: Iterator) -> Iterator:
        """Apply drop/duplicate/swap faults to a record iterator.

        Indices count REAL records only; ``None`` idle ticks pass
        through unindexed (they are time, not data). ``swap_records``
        holds record ``i`` back and emits ``i+1`` first — a bounded,
        deterministic reorder (the shape out-of-order delivery actually
        takes at a window boundary). ``skew_records`` jitters record
        ``i``'s timestamp field by a seed-derived bounded offset —
        event-time disorder without reordering delivery.
        """
        drop = set(self.drop_records)
        dup = set(self.duplicate_records)
        swap = set(self.swap_records)
        skew = set(self.skew_records)
        held = None  # (index, record) awaiting its swap partner
        i = 0
        for rec in records:
            if rec is None:
                yield rec
                continue
            idx = i
            i += 1
            if idx in drop:
                self._count("source.perturb")
                continue
            if idx in skew:
                self._count("source.perturb")
                rec = self._skewed(rec, idx)
            if held is not None:
                yield rec
                if idx in dup:
                    yield rec
                yield held[1]
                if held[0] in dup:
                    yield held[1]
                held = None
                continue
            if idx in swap:
                self._count("source.perturb")
                held = (idx, rec)
                continue
            yield rec
            if idx in dup:
                self._count("source.perturb")
                yield rec
        if held is not None:  # swap partner never arrived: emit late
            yield held[1]

    def _skewed(self, rec: tuple, idx: int):
        """Record ``idx`` with its timestamp field jittered by a
        DETERMINISTIC bounded offset in ``[-skew_ts_s, +skew_ts_s]``
        derived from ``(seed, idx)`` — same plan, same jitter, every
        run (the seeded-chaos rule). Records too short to carry the
        field pass through untouched (a ts-less stream has no event
        time to skew)."""
        f = self.skew_ts_field
        pos = f if f >= 0 else len(rec) + f
        if self.skew_ts_s <= 0 or not (0 <= pos < len(rec)):
            return rec
        span = 2 * self.skew_ts_s + 1
        # splitmix-style integer mix of (seed, idx): cheap, stateless,
        # and identical across processes — no RNG object to carry
        h = (idx * 0x9E3779B97F4A7C15 + self.seed * 0xC2B2AE3D27D4EB4F)
        h ^= h >> 31
        offset = (h % span) - self.skew_ts_s
        out = list(rec)
        out[pos] = int(out[pos]) + offset
        return tuple(out)


def corrupt_file(path: str, mode: str = "flip", *, seed: int = 0) -> None:
    """Deterministically damage a committed artifact in place.

    ``flip`` XORs one byte at an offset derived from ``seed`` (second
    half of the file, so the payload — not just the container header —
    is what the checksum must catch); ``truncate`` keeps the first half
    (the torn-write shape). Used by the fault plan and directly by
    tests/the chaos sweep.
    """
    size = os.path.getsize(path)
    if size == 0:
        return
    if mode == "truncate":
        with open(path, "rb+") as f:
            f.truncate(max(1, size // 2))
        return
    if mode != "flip":
        raise ValueError(f"corrupt mode must be flip/truncate, got {mode!r}")
    offset = size // 2 + (seed % max(1, size - size // 2))
    offset = min(offset, size - 1)
    with open(path, "rb+") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


# --------------------------------------------------------------------- #
# Global installation (the hook points' single cheap check)
# --------------------------------------------------------------------- #
_PLAN: Optional[FaultPlan] = None
_LOCK = threading.Lock()


def active() -> bool:
    """True when a plan is installed — the one check production hook
    sites pay."""
    return _PLAN is not None


def plan() -> Optional[FaultPlan]:
    return _PLAN


def install(p: Optional[FaultPlan]) -> None:
    global _PLAN
    with _LOCK:
        _PLAN = p


def clear() -> None:
    install(None)


def fire(site: str, *, index: Optional[int] = None,
         path: Optional[str] = None) -> None:
    """Module-level dispatch: forwards to the installed plan, no-op
    otherwise. Hook sites guard with :func:`active` first so the
    common case never enters this function."""
    p = _PLAN
    if p is not None:
        p.fire(site, index=index, path=path)


def rpc_truncate(index: Optional[int]) -> bool:
    """Module-level dispatch for :meth:`FaultPlan.truncate_frame`;
    False when no plan is installed (the production-path answer)."""
    p = _PLAN
    return p is not None and p.truncate_frame(index)


class injected:
    """``with faults.injected(plan): ...`` — install for the block,
    always clear after (a leaked plan would sabotage the next test)."""

    def __init__(self, p: FaultPlan):
        self._p = p

    def __enter__(self) -> FaultPlan:
        install(self._p)
        return self._p

    def __exit__(self, *exc) -> None:
        clear()
