"""Deterministic chaos harness: kill the CC pipeline at every window.

The recovery guarantee this repo claims — a killed process restarts
from the newest valid barrier and finishes with output value-identical
to an uninterrupted run — is only worth stating if something kills the
process at EVERY window and checks. This module is that something.
:func:`run_sweep` is the single-process sweep (ISSUE 4);
:func:`run_mp_sweep` is the DISTRIBUTED half (ISSUE 5): an N-process
cluster with coordinated epoch barriers
(:mod:`~gelly_streaming_tpu.resilience.coordinated`) and the
file-exchange dictionary contract
(:class:`~gelly_streaming_tpu.parallel.multihost.FileExchangeTransport`),
where one worker of N is killed at every window ordinal, the
:class:`~gelly_streaming_tpu.resilience.coordinated.ClusterSupervisor`
restarts the whole cluster from the agreed epoch, and the driver asserts
oracle-identical emissions, byte-identical VertexDicts, and that no
relaunch ever mixed epochs. Single-process mechanics:

- :func:`run_sweep` runs an ORACLE pass of the superbatched CC pipeline
  (fixed seeded corpus, per-window emission digests), then for each
  kill point ``k`` launches a fresh worker process that dies hard
  (``os._exit``) after ``k`` windows, optionally corrupts the committed
  barrier head (flip-byte / truncate — the torn-checkpoint fault), and
  relaunches to completion. Every digest line any worker ever wrote
  must equal the oracle digest at its window ordinal, and together they
  must cover every window — which proves both recovery AND that
  replayed re-emissions are value-identical at every kill point.
- Workers append one flushed JSONL digest line per window BEFORE the
  kill hook fires, so the pre-crash evidence survives ``os._exit``; the
  obs registry's event log (written on clean exits) records every
  ``resilience.ckpt_rejected`` so torn artifacts are visibly rejected,
  never silently loaded.

Everything is seeded and index-driven (:mod:`~gelly_streaming_tpu.resilience.faults`),
so a failing kill point reproduces exactly. ``bench.py --chaos`` wraps
:func:`run_sweep` into the committed ``BENCH_CHAOS_CPU.json`` artifact
(recovery-time distribution + restart counts); the test suite runs a
reduced sweep (``-m chaos_full``) and the in-process fast subset
(``-m chaos_fast``).

Worker entry point (subprocess only)::

    python -m gelly_streaming_tpu.resilience.chaos worker '<json cfg>'
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Optional

#: worker exit code for an injected kill (distinct from real failures)
KILL_RC = 17

#: repo root (the directory holding ``gelly_streaming_tpu``), for
#: subprocess sys.path injection — workers must import this package
#: regardless of the driver's cwd
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: default sweep geometry: small windows + superbatch=2 so barriers,
#: group boundaries, and kill points interleave in every phase
DEFAULTS = dict(
    windows=24, window_edges=256, superbatch=2, every=2, seed=1234
)

#: multi-process sweep geometry: 2 processes (kill-one-of-N at every
#: window ordinal), window_edges divisible by the process count so the
#: interleaved pre-partition tiles windows exactly
MP_DEFAULTS = dict(
    processes=2, windows=12, window_edges=128, superbatch=2, every=2,
    seed=4321,
)


def corpus(seed: int, n_edges: int) -> list:
    """Deterministic edge list with SPARSE raw ids (vertex-dict replay
    must reproduce exact compact-id assignment across restarts — same
    discipline as ``tests/_ckpt_worker.py``)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, 600, size=(n_edges, 2))
    return [(int(a) * 7 + 3, int(b) * 7 + 3, 0.0) for a, b in pairs]


def digest(emission) -> str:
    """Stable fingerprint of one per-window emission (the Components
    string form is canonical: sorted roots, sorted members)."""
    import hashlib

    return hashlib.sha1(str(emission).encode()).hexdigest()[:16]


# --------------------------------------------------------------------- #
# Worker (runs in a subprocess; dies hard at the kill point)
# --------------------------------------------------------------------- #
def _worker_obs(cfg: dict, shard: Optional[int] = None):
    """Shared worker telemetry wiring: a streaming :class:`ShardSink`
    (every event hits disk the moment it is emitted, so the pre-kill
    story survives ``os._exit`` — the in-memory ``JsonlSink`` these
    workers used before lost EVERYTHING on a kill run), tracing on
    (spans + the flight ring's gate), and a flight recorder when the
    driver asked for one (``cfg["flight"]``). Returns the sink."""
    from ..obs import flight as obs_flight
    from ..obs import trace as obs_trace
    from ..obs.cluster import ShardSink
    from ..obs.registry import get_registry

    sink = ShardSink(cfg["events"], shard=shard)
    get_registry().add_sink(sink)
    obs_trace.add_sink(sink)
    obs_trace.enable()
    if cfg.get("flight"):
        obs_flight.install(obs_flight.FlightRecorder(
            cfg["flight"], capacity=128, shard=shard,
        ))
    return sink


def worker_main(cfg: dict) -> None:
    """Drive the supervised CC pipeline once. ``cfg`` keys: ``ckpt``,
    ``digests``, ``events``, ``meta`` (paths), ``kill_after`` (windows
    consumed before ``os._exit(KILL_RC)``; -1 = run to completion),
    optionally ``flight`` (flight-recorder dump base path), plus the
    sweep geometry (``windows``/``window_edges``/``superbatch``
    /``every``/``seed``)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..aggregate.autockpt import AutoCheckpoint
    from ..core.stream import SimpleEdgeStream
    from ..core.window import CountWindow
    from ..library import ConnectedComponents
    from ..obs.registry import get_registry
    from . import faults
    from .supervisor import Supervisor

    raw = corpus(cfg["seed"], cfg["windows"] * cfg["window_edges"])
    sink = _worker_obs(cfg)

    def make_stream(vd):
        return SimpleEdgeStream(
            raw, window=CountWindow(cfg["window_edges"]), vertex_dict=vd
        )

    def make_work():
        return ConnectedComponents(superbatch=cfg["superbatch"])

    ac = AutoCheckpoint(cfg["ckpt"], every=cfg["every"], keep=3)
    resumed_from = ac.windows_done()
    sup = Supervisor(
        ac, backoff_base_s=0.0, jitter=0.0, seed=cfg["seed"]
    )
    kill_after = int(cfg.get("kill_after", -1))
    if kill_after >= 0:
        faults.install(faults.FaultPlan(
            seed=cfg["seed"],
            kill_at_window=kill_after - 1,
            kill_exit_code=KILL_RC,
        ))
    t0 = time.perf_counter()
    first = None
    yielded = 0
    with open(cfg["digests"], "a") as out:
        ordinal = resumed_from
        for comps in sup.run(make_stream, make_work):
            if first is None:
                first = time.perf_counter() - t0
            out.write(json.dumps({"o": ordinal, "d": digest(comps)}) + "\n")
            # flush BEFORE the kill hook: os._exit drops python-level
            # buffers, and the pre-crash digest lines are the evidence
            out.flush()
            if faults.active():
                faults.fire("chaos.window", index=ordinal)
            ordinal += 1
            yielded += 1
    with open(cfg["meta"], "w") as f:
        json.dump({
            "resumed_from": resumed_from,
            "restarts": sup.restarts,
            "yielded": yielded,
            "first_emission_s": first,
            "total_s": time.perf_counter() - t0,
        }, f)
    sink.close()
    get_registry().remove_sink(sink)
    faults.clear()


def _worker_code(entry: str) -> str:
    return (
        "import sys, json; "
        f"sys.path.insert(0, {REPO_ROOT!r}); "
        "from gelly_streaming_tpu.resilience import chaos; "
        f"chaos.{entry}(json.loads(sys.argv[1]))"
    )


def _spawn_worker(cfg: dict, timeout: float = 600.0,
                  entry: str = "worker_main"):
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-c", _worker_code(entry), json.dumps(cfg)],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


# --------------------------------------------------------------------- #
# Multi-process worker (one shard of the coordinated cluster)
# --------------------------------------------------------------------- #
def mp_worker_main(cfg: dict) -> None:
    """One shard of the distributed sweep's cluster. ``cfg`` keys:
    ``root`` (shared directory: ``ckpt/`` epochs + ``exchange/``
    files), ``process``/``processes``, ``digests``/``events``/``meta``
    (per-process paths), ``kill_after`` (windows consumed before
    ``os._exit``; fires only when ``process == victim``), plus the
    sweep geometry. Each process windows its interleaved shard of the
    global corpus (edge ``i`` belongs to process ``i % N`` — the
    pre-partition keyBy analog), agrees on raw->compact ids through a
    persisted exchange transport, and commits coordinated epoch
    barriers.

    ``transport`` selects the exchange backend: ``"shared_dir"``
    (default — files under ``root/exchange``) or ``"socket"`` (GSRP
    frames against the driver's exchange daemon at
    ``exchange_addr``). Epoch barriers stay on the shared directory in
    BOTH modes: the daemon's store is in-memory, and barrier restore
    must survive the daemon host too — the sweep exercises the socket
    path where it is honest to (the per-window id exchange, whose
    replay-safety window is one cluster incarnation)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from ..core.stream import SimpleEdgeStream
    from ..core.vertexdict import VertexDict
    from ..core.window import CountWindow
    from ..library import ConnectedComponents
    from ..obs.registry import get_registry
    from ..parallel.multihost import FileExchangeTransport, dict_exchange_encode
    from . import faults
    from .coordinated import CoordinatedCheckpoint
    from .supervisor import Supervisor

    pid = int(cfg["process"])
    nprocs = int(cfg["processes"])
    windows = int(cfg["windows"])
    we = int(cfg["window_edges"])
    if we % nprocs:
        raise ValueError("window_edges must divide by the process count")
    lw = we // nprocs  # local (per-shard) window size
    raw = corpus(cfg["seed"], windows * we)
    mine = raw[pid::nprocs]
    if cfg.get("transport") == "socket":
        from ..fabric import SocketTransport

        fx = SocketTransport(
            str(cfg["exchange_addr"]), pid, nprocs,
            timeout_s=float(cfg.get("exchange_timeout_s", 60.0)),
        )
    else:
        fx = FileExchangeTransport(
            os.path.join(cfg["root"], "exchange"), pid, nprocs,
            timeout_s=float(cfg.get("exchange_timeout_s", 60.0)),
        )
    sink = _worker_obs(cfg, shard=pid)
    seen_vd = {}  # the live stream's vertex dict (for the final CRC)

    def make_stream(vd):
        vd_eff = vd if vd is not None else VertexDict()
        seen_vd["vd"] = vd_eff

        def gen():
            for w in range(windows):
                chunk = mine[w * lw:(w + 1) * lw]
                src = np.array([e[0] for e in chunk], np.int64)
                dst = np.array([e[1] for e in chunk], np.int64)
                # the union fold is the point; the returned compact
                # columns are re-derived by the windower's own encode
                dict_exchange_encode(
                    None, vd_eff, src, dst, transport=fx, window=w
                )
                yield from chunk

        return SimpleEdgeStream(
            gen(), window=CountWindow(lw), vertex_dict=vd_eff
        )

    def make_work():
        return ConnectedComponents(superbatch=cfg["superbatch"])

    cc = CoordinatedCheckpoint(
        os.path.join(cfg["root"], "ckpt"),
        process_id=pid, num_processes=nprocs,
        every=cfg["every"], keep=3,
    )
    sup = Supervisor(cc, backoff_base_s=0.0, jitter=0.0, seed=cfg["seed"])
    kill_after = int(cfg.get("kill_after", -1))
    if kill_after >= 0 and int(cfg.get("victim", -1)) == pid:
        faults.install(faults.FaultPlan(
            seed=cfg["seed"],
            kill_at_window=kill_after - 1,
            kill_exit_code=KILL_RC,
        ))
    t0 = time.perf_counter()
    first = None
    yielded = 0
    resumed_epoch = None
    with open(cfg["digests"], "a") as out:
        ordinal = None
        for comps in sup.run(make_stream, make_work):
            if first is None:
                first = time.perf_counter() - t0
            if ordinal is None:
                # label base = the epoch the supervisor ACTUALLY
                # restored for the attempt that produced this first
                # emission (read via the attempt's own cached load) —
                # a pre-run scan could disagree with it: the
                # supervisor re-invalidates and rescans, and in that
                # gap a peer's healing commit can complete a newer
                # epoch, or a pre-emission failure can fall back past
                # a torn one; either way a stale base would mislabel
                # every digest line
                resumed_epoch = ordinal = cc.windows_done()
            out.write(json.dumps({"o": ordinal, "d": digest(comps)}) + "\n")
            out.flush()  # pre-crash evidence must survive os._exit
            if faults.active():
                faults.fire("chaos.window", index=ordinal)
            ordinal += 1
            yielded += 1
    if resumed_epoch is None:
        # nothing was emitted: the barrier already covered the whole
        # stream, so the resumed epoch is the (cached) restored one
        resumed_epoch = cc.windows_done()
    import zlib

    vd = seen_vd.get("vd")
    vd_crc = (
        None if vd is None
        else zlib.crc32(np.ascontiguousarray(vd.raw_ids()).tobytes())
        & 0xFFFFFFFF
    )
    with open(cfg["meta"], "w") as f:
        json.dump({
            "process": pid,
            "resumed_epoch": resumed_epoch,
            "restarts": sup.restarts,
            "yielded": yielded,
            "vd_crc": vd_crc,
            "first_emission_s": first,
            "total_s": time.perf_counter() - t0,
        }, f)
    sink.close()
    get_registry().remove_sink(sink)
    faults.clear()


# --------------------------------------------------------------------- #
# Serving failover scenario (one subprocess; events are the evidence)
# --------------------------------------------------------------------- #
def failover_main(cfg: dict) -> None:
    """Kill the primary serving worker mid-stream and prove the standby
    takeover contract: expired in-flight queries fail DeadlineExceeded,
    the rest are re-answered from the standby's newest snapshot, new
    submits keep working, and every failover event lands in the obs
    event log. ``cfg`` keys: ``events``, ``meta``, ``seed``."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from ..datasets import IdentityDict
    from ..obs import flight as obs_flight
    from ..obs.registry import get_registry
    from ..serving import ConnectedQuery, FailoverServer
    from . import faults
    from .errors import DeadlineExceeded

    # same wiring as every other chaos worker: streaming ShardSink
    # (ts-stamped events, kill-proof) + tracing + the flight recorder
    # whose dump the injected worker death must commit
    sink = _worker_obs(cfg)
    V = 32
    vd = IdentityDict(V)
    vd.observe(V - 1)

    def payloads():
        labels = np.arange(V, dtype=np.int32)
        for w in range(200):
            labels = labels.copy()
            labels[: min(V, w + 2)] = 0  # a chain growing one node/window
            yield {"labels": labels, "vdict": vd}, w + 1
            time.sleep(0.005)

    meta = {"promoted": False, "reanswered": 0, "expired": 0, "post": 0}
    # the worker dies on its 6th sweep (~0.3s in): deterministic ordinal,
    # wall timing irrelevant to the assertions below
    with faults.injected(faults.FaultPlan(
        seed=cfg["seed"], kill_site="serving.worker", kill_at_window=5,
    )):
        fs = FailoverServer(
            payloads(), None, monitor_s=None, max_pending=64,
        ).start()
        try:
            fs.store.wait_for(1, timeout=30)
            # admitted BEFORE the death: answered by the primary if it
            # gets there in time, re-answered by the standby otherwise —
            # either way the future must settle with the right value
            f_pre = fs.submit(ConnectedQuery(0, 1))
            deadline = time.monotonic() + 30
            while fs.primary.worker_alive() and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not fs.primary.worker_alive(), "worker never died"
            # admitted while the worker is dead: one already-hopeless
            # deadline, two that the standby must re-answer
            f_exp = fs.primary.submit(ConnectedQuery(0, 1), deadline_s=0.01)
            f_ok = fs.primary.submit(ConnectedQuery(0, 1))
            f_ok2 = fs.primary.submit(ConnectedQuery(0, 1), deadline_s=30.0)
            time.sleep(0.05)  # f_exp's deadline lapses
            fs.promote(reason="worker_death")
            meta["promoted"] = fs.promoted
            try:
                f_exp.result(30)
            except DeadlineExceeded:
                meta["expired"] += 1
            for f in (f_ok, f_ok2):
                if f.result(30).value is True:
                    meta["reanswered"] += 1
            meta["pre"] = bool(f_pre.result(30).value)
            if fs.ask(ConnectedQuery(0, 1), timeout=30).value is True:
                meta["post"] = 1
        finally:
            fs.close()
    reg = get_registry()
    meta["failover_events"] = reg.counter(
        "serving.failover", reason="worker_death"
    ).value
    meta["worker_deaths"] = reg.counter("serving.worker_deaths").value
    meta["promotion_seconds_count"] = reg.histogram(
        "serving.promotion_seconds"
    ).count
    if cfg.get("flight"):
        meta["flight_dumps"] = [
            os.path.basename(p)
            for p in obs_flight.find_dumps(os.path.dirname(cfg["flight"]))
        ]
    with open(cfg["meta"], "w") as f:
        json.dump(meta, f)
    sink.close()
    get_registry().remove_sink(sink)


# --------------------------------------------------------------------- #
# RPC cross-process failover scenario (kill the serving BINARY under
# live multi-connection wire traffic)
# --------------------------------------------------------------------- #
#: the per-stage keys of the attribution table: client_send (submit ->
#: bytes on the wire), the server-side stages in wire order, then
#: client_recv (response frame -> futures settled); client_wait covers
#: retry/resubmit outage spans separately
ATTRIBUTION_STAGES = (
    "client_send", "decode", "admit", "queue_wait", "dispatch",
    "settle", "reply", "client_recv",
)


def trace_attribution(
    root,
    kill_wall: Optional[float] = None,
    back_wall: Optional[float] = None,
) -> dict:
    """Fold a traced RPC run's merged span stream into the per-stage
    attribution table (ISSUE 9).

    Per trace with a completed client root span (``rpc.client.batch``):
    the end-to-end client measurement, the answering replica's
    server-side residence (newest ``rpc.server.batch``) with its stage
    breakdown (decode/admit from its attrs; queue_wait/dispatch/settle
    from the answering sweep's ``serving.query``), the client-side wait
    spans (retry/resubmit), and the attribution COVERAGE — attributed
    time over the client's own e2e, the honesty ratio the bench
    asserts. Traces are bucketed steady vs promotion-window by overlap
    with ``[kill_wall, back_wall]``; a trace counts as KILL-CROSSING
    when its client waited out an outage (resubmit/retry span) and its
    server spans came from at least two distinct shards — the dead
    primary and the promoted standby."""
    from collections import defaultdict

    from ..obs.cluster import iter_shard_events
    from ..obs.registry import nearest_rank

    by_trace: dict = defaultdict(list)
    for e in iter_shard_events(root):
        if e.get("kind") == "span" and e.get("trace"):
            by_trace[e["trace"]].append(e)

    def bucket():
        return {
            "e2e": [], "coverage": [], "client_wait": [],
            "unattributed": [], "stages": defaultdict(list),
        }

    per = {"steady": bucket(), "promotion_window": bucket()}
    crossing = 0
    completed = 0
    example = None
    for tid in sorted(by_trace):
        spans = by_trace[tid]
        roots = [s for s in spans if s["name"] == "rpc.client.batch"]
        if not roots:
            continue  # unanswered (expired) or foreign trace
        completed += 1
        c = roots[-1]
        e2e = float(c["dur_s"])
        end = float(c["ts"])
        start = end - e2e
        promo = (
            kill_wall is not None and back_wall is not None
            and end >= kill_wall and start <= back_wall
        )
        server_batches = sorted(
            (s for s in spans if s["name"] == "rpc.server.batch"),
            key=lambda s: float(s["ts"]),
        )
        sweeps = sorted(
            (s for s in spans if s["name"] == "serving.query"),
            key=lambda s: float(s["ts"]),
        )
        waits = [
            s for s in spans
            if s["name"] in ("rpc.client.retry", "rpc.client.resubmit")
        ]
        server_s = float(server_batches[-1]["dur_s"]) \
            if server_batches else 0.0
        wait_s = sum(float(s["dur_s"]) for s in waits)
        c_at = c.get("attrs") or {}
        send_s = float(c_at.get("send_s", 0.0))
        recv_s = float(c_at.get("recv_s", 0.0))
        # send_s spans submit -> LAST send, so for a retried batch it
        # overlaps the wait spans (which cover send -> resend cycles);
        # take whichever accounts for more, never both
        attributed = server_s + recv_s + max(send_s, wait_s)
        server_shards = {
            s.get("shard") for s in spans
            if s["name"] in ("rpc.decode", "rpc.admit",
                             "rpc.server.batch", "serving.query")
        } - {None}
        if waits and len(server_shards) >= 2:
            crossing += 1
            if example is None:
                example = tid
        b = per["promotion_window" if promo else "steady"]
        b["e2e"].append(e2e)
        b["coverage"].append(attributed / e2e if e2e > 0 else 1.0)
        b["client_wait"].append(wait_s)
        b["unattributed"].append(max(0.0, e2e - attributed))
        b["stages"]["client_send"].append(send_s)
        b["stages"]["client_recv"].append(recv_s)
        if server_batches:
            at = server_batches[-1].get("attrs") or {}
            b["stages"]["decode"].append(float(at.get("decode_s", 0.0)))
            b["stages"]["admit"].append(float(at.get("admit_s", 0.0)))
            b["stages"]["reply"].append(float(at.get("reply_s", 0.0)))
        if sweeps:
            at = sweeps[-1].get("attrs") or {}
            b["stages"]["queue_wait"].append(
                float(at.get("queue_wait_s", 0.0)))
            b["stages"]["dispatch"].append(
                float(at.get("dispatch_s", 0.0)))
            b["stages"]["settle"].append(
                float(at.get("settle_s", 0.0)))

    def summarize(b: dict) -> dict:
        e2e_ms = sorted(v * 1e3 for v in b["e2e"])
        cov = sorted(b["coverage"])

        def mean_ms(xs):
            return round(sum(xs) / len(xs) * 1e3, 3) if xs else None

        return {
            "traces": len(b["e2e"]),
            # None for an empty bucket, like every other field here —
            # a 0.0 p50 would read as "measured zero latency"
            "e2e_ms": {
                "p50": round(nearest_rank(e2e_ms, 50), 3),
                "p99": round(nearest_rank(e2e_ms, 99), 3),
            } if e2e_ms else None,
            "stages_ms": {
                k: mean_ms(b["stages"][k]) for k in ATTRIBUTION_STAGES
            },
            "client_wait_ms": mean_ms(b["client_wait"]),
            "unattributed_ms": mean_ms(b["unattributed"]),
            "unattributed_p50_ms": (
                round(nearest_rank(
                    sorted(v * 1e3 for v in b["unattributed"]), 50), 3)
                if b["unattributed"] else None
            ),
            "coverage_p50": (
                round(nearest_rank(cov, 50), 4) if cov else None
            ),
        }

    return {
        "traces_total": len(by_trace),
        "traces_completed": completed,
        "kill_crossing_traces": crossing,
        "example_kill_crossing_trace": example,
        "steady": summarize(per["steady"]),
        "promotion_window": summarize(per["promotion_window"]),
    }


def run_rpc_scenario(
    root: str,
    *,
    seed: int = MP_DEFAULTS["seed"],
    clients: int = 3,
    batch: int = 8,
    pace_s: float = 0.01,
    kill_at_sweep: int = 120,
    lease_s: float = 0.4,
    deadline_s: float = 30.0,
    post_kill_batches: int = 25,
    vcap: int = 64,
    autotune: bool = False,
    target_wait_s: Optional[float] = None,
    log: Optional[Callable[[str], None]] = None,
    obs_f=None,
) -> dict:
    """The wire-level availability proof (ISSUE 8): a primary + standby
    serving BINARY pair on a shared snapshot directory, a
    multi-connection client load generator sustaining batched query
    traffic, and a ``FaultPlan`` kill (``serving.worker`` site,
    ``os._exit`` with the flight recorder's black box dumped first) of
    the primary mid-run. The standby promotes on heartbeat-lease lapse;
    clients reconnect and resubmit under their original batch ids.

    Asserted: ZERO client-visible query failures — every submitted
    query resolves to an answer or a clean ``DeadlineExceeded`` within
    its own budget — plus the promotion evidence (``serving.failover``
    with ``reason=lease_lapse`` and a ``serving.promotion_seconds``
    observation in the standby's event stream) and the dead primary's
    flight dump. Client-MEASURED batch latency is reported separately
    for steady state and for the promotion window (batches whose life
    overlapped the outage), which is the artifact's headline.

    ISSUE 9 adds the TRACED run: the driver enables tracing and ships
    its client-side spans as shard ``p2``, so the merged OBS log holds
    end-to-end traces — client batch root + retry/resubmit spans joined
    to each replica's decode/admit/dispatch/reply spans by trace id.
    The committed artifact gains a per-stage ATTRIBUTION table (steady
    vs promotion window), and the scenario additionally asserts that at
    least one trace CROSSES the kill (client resubmit spans joined to
    both the dead primary's and the promoted standby's server spans)
    and that per-stage attribution accounts for the client-measured
    end-to-end latency of answered steady-state batches to within 10%.
    """
    import threading

    from ..obs import trace as obs_trace
    from ..obs.cluster import ShardSink, shard_events_path
    from ..obs.registry import get_registry, nearest_rank
    from ..serving.client import RpcClient
    from ..serving.query import ConnectedQuery
    from ..serving.rpc import spawn_replica, wait_portfile
    from .errors import DeadlineExceeded

    say = log or (lambda s: print(s, file=sys.stderr, flush=True))
    os.makedirs(root, exist_ok=True)
    client_sink = None
    shared = os.path.join(root, "shared")
    base = dict(
        dir=shared, lease_s=lease_s, windows=1 << 20, pace_s=0.01,
        vcap=vcap, run_s=600.0, seed=seed,
    )
    if autotune:
        # ISSUE 19 satellite: load-aware admission on both replicas;
        # the promoted standby's meta carries the tuner's trajectory
        base.update(autotune=True, target_wait_s=target_wait_s)
    standby_meta = os.path.join(root, "standby.meta.json")
    primary = spawn_replica(dict(
        base, role="primary", shard=0,
        kill_at_sweep=kill_at_sweep,
        portfile=os.path.join(root, "primary.port"),
        events=shard_events_path(root, 0),
        flight=os.path.join(root, "flight.p0.json"),
    ))
    standby = spawn_replica(dict(
        base, role="standby", shard=1,
        portfile=os.path.join(root, "standby.port"),
        events=shard_events_path(root, 1),
        meta=standby_meta,
    ))
    doc: dict = {
        "config": dict(
            clients=clients, batch=batch, pace_s=pace_s,
            kill_at_sweep=kill_at_sweep, lease_s=lease_s,
            deadline_s=deadline_s, seed=seed, autotune=autotune,
        ),
    }
    try:
        # the driver IS the client process of the trace story: its
        # spans (batch roots, retries, resubmits) and client-side
        # counters ship as shard p2 next to the replicas' p0/p1
        # streams. Attached INSIDE the try so a failed setup releases
        # them in the finally (the PR 7 obs-leak lesson);
        # registry_spans off for the same reason as replica_main — the
        # span events themselves are the committed evidence
        client_sink = ShardSink(shard_events_path(root, 2), shard=2)
        obs_trace.add_sink(client_sink)
        get_registry().add_sink(client_sink)
        obs_trace.enable(registry_spans=False)
        # perf_counter -> wall-clock offset: span events carry wall
        # ts, the driver's kill/recovery stamps are perf_counter — one
        # offset joins the two clocks for promotion-window bucketing
        wall_off = time.time() - time.perf_counter()
        p_port = wait_portfile(os.path.join(root, "primary.port"))
        s_port = wait_portfile(os.path.join(root, "standby.port"))
        addrs = [f"127.0.0.1:{p_port}", f"127.0.0.1:{s_port}"]
        say(f"chaos-rpc: primary :{p_port} (kill@sweep {kill_at_sweep}), "
            f"standby :{s_port}, {clients} client connections x "
            f"{batch}-query batches")

        kill_seen = [None]  # perf_counter stamp of the observed death

        def watch_primary():
            primary.wait()
            kill_seen[0] = time.perf_counter()

        watcher = threading.Thread(target=watch_primary, daemon=True)
        watcher.start()

        # (submit_ts, settle_ts, ok, deadline, error_repr) per batch
        records: list = []
        rec_lock = threading.Lock()
        client_errs: list = []

        def drive(ci: int) -> None:
            # one CONNECTION per driver thread: the multi-connection
            # half of the contract, each with its own reconnect loop
            import numpy as np

            rng = np.random.default_rng(seed + ci)
            cl = RpcClient(addrs, seed=seed + ci)
            try:
                post = 0
                while post < post_kill_batches:
                    qs = [
                        ConnectedQuery(int(a), int(b))
                        for a, b in rng.integers(0, vcap, (batch, 2))
                    ]
                    t0 = time.perf_counter()
                    futs = cl.submit_batch(qs, deadline_s=deadline_s)
                    n_dead = 0
                    err = None
                    for f in futs:
                        try:
                            f.result(deadline_s + 30)
                        except DeadlineExceeded:
                            n_dead += 1
                        except BaseException as e:
                            err = err or repr(e)[:200]
                    t1 = time.perf_counter()
                    with rec_lock:
                        records.append(
                            (t0, t1, err is None, n_dead, err)
                        )
                    if kill_seen[0] is not None and t1 > kill_seen[0]:
                        post += 1
                    if pace_s:
                        time.sleep(pace_s)
            except BaseException as e:
                # a dead load generator would under-report the outage;
                # its failure is the scenario's failure
                client_errs.append(repr(e)[:400])
            finally:
                cl.close()

        threads = [
            threading.Thread(target=drive, args=(i,), daemon=True)
            for i in range(clients)
        ]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        watcher.join(60)
        t_kill = kill_seen[0]
        primary_rc = primary.returncode

        # -- classify batches: steady vs promotion window --------------- #
        answered = sum(1 for r in records if r[2])
        failures = sum(1 for r in records if not r[2])
        deadline_expired = sum(r[3] for r in records)
        t_back = None
        if t_kill is not None:
            settled_after = sorted(
                r[1] for r in records if r[2] and r[1] > t_kill
            )
            t_back = settled_after[0] if settled_after else None
        steady, promo = [], []
        for t0, t1, ok_b, _nd, _e in records:
            if not ok_b:
                continue
            lat = (t1 - t0) * 1000.0
            if (
                t_kill is not None and t_back is not None
                and t1 >= t_kill and t0 <= t_back
            ):
                promo.append(lat)
            else:
                steady.append(lat)
        steady.sort()
        promo.sort()

        # -- autotune trajectory (ISSUE 19 satellite): the drive is
        # over, so the promoted standby can be retired NOW — its exit
        # meta carries the admission tuner's full shed-watermark
        # trajectory (moves + final knobs), and the retune events below
        # are read after its stream is complete ------------------------ #
        if autotune:
            if standby.poll() is None:
                standby.terminate()
                try:
                    standby.wait(20)
                except Exception:
                    _kill_replica(standby)
            try:
                with open(standby_meta) as f:
                    sb_tuner = json.load(f).get("autotune")
            except (OSError, ValueError):
                sb_tuner = None
            doc["autotune"] = {
                "standby": sb_tuner,
                "retunes": [
                    {"shard": f"p{sh}", "ts": e.get("ts"),
                     **(e.get("labels") or {})}
                    for sh in (0, 1)
                    for e in _read_jsonl(shard_events_path(root, sh))
                    if e.get("name") == "control.retune"
                ],
            }

        # -- promotion evidence from the standby's event stream --------- #
        sb_events = _read_jsonl(shard_events_path(root, 1))
        promoted = any(
            e.get("name") == "serving.failover"
            and (e.get("labels") or {}).get("reason") == "lease_lapse"
            for e in sb_events
        )
        promotion_obs = [
            float(e["v"]) for e in sb_events
            if e.get("name") == "serving.promotion_seconds"
            and "v" in e
        ]
        from ..obs import flight as obs_flight

        flight_dumps = [
            os.path.basename(p) for p in obs_flight.find_dumps(root)
        ]

        # -- per-stage trace attribution (ISSUE 9) ---------------------- #
        attribution = trace_attribution(
            root,
            kill_wall=(t_kill + wall_off if t_kill is not None
                       else None),
            back_wall=(t_back + wall_off if t_back is not None
                       else None),
        )
        wire_ex = get_registry().histogram(
            "rpc.client_wire_seconds"
        ).exemplars()
        cov = attribution["steady"]["coverage_p50"]
        # the unattributed residue per trace (thread wakeups + socket
        # syscalls BETWEEN spans) is a host constant, not a fraction of
        # e2e: on a fast box a ~0.35ms OS gap under a ~2ms e2e fails a
        # pure ratio gate while attributing exactly as much as ever —
        # so the 10% ratio check gets an absolute scheduling floor
        unattr = attribution["steady"]["unattributed_p50_ms"]
        traced_ok = (
            attribution["kill_crossing_traces"] >= 1
            and cov is not None and cov <= 1.05
            and (cov >= 0.9
                 or (unattr is not None and unattr <= 0.5))
        )
        ok = (
            not client_errs
            and failures == 0
            and t_kill is not None
            and primary_rc == KILL_RC
            and t_back is not None
            and promoted
            and len(promotion_obs) >= 1
            and len(flight_dumps) >= 1
            and traced_ok
        )
        doc.update(
            ok=ok,
            batches=len(records),
            queries=len(records) * batch,
            queries_answered=answered * batch - deadline_expired,
            failures=failures,
            client_errors=client_errs,
            deadline_expired=deadline_expired,
            primary_rc=primary_rc,
            kill_wall_s=(
                round(t_kill - t_start, 3) if t_kill is not None
                else None
            ),
            outage_s=(
                round(t_back - t_kill, 3)
                if t_kill is not None and t_back is not None else None
            ),
            steady={
                "batches": len(steady),
                "p50_ms": round(nearest_rank(steady, 50), 3),
                "p99_ms": round(nearest_rank(steady, 99), 3),
            },
            promotion_window={
                "batches": len(promo),
                "p50_ms": round(nearest_rank(promo, 50), 3),
                "p99_ms": round(nearest_rank(promo, 99), 3),
                "max_ms": round(promo[-1], 3) if promo else None,
            },
            serving_promotion_seconds=(
                round(promotion_obs[0], 4) if promotion_obs else None
            ),
            promoted=promoted,
            flight_dumps=flight_dumps,
            attribution=attribution,
            wire_p99_exemplar_trace=(
                wire_ex[0][1] if wire_ex else None
            ),
            note=(
                "client-measured batch latency over live wire traffic "
                "across a primary serving-binary kill: zero failures "
                "means every query was answered or cleanly "
                "DeadlineExceeded within its own budget; the promotion "
                "window covers batches whose life overlapped the "
                "outage. attribution breaks answered batches into "
                "per-stage time from the merged trace spans (steady "
                "coverage_p50 is attributed/e2e — asserted within 10% "
                "or within a 0.5ms absolute inter-span scheduling "
                "floor, the OS residue that does not shrink with e2e); "
                "wire_p99_exemplar_trace links the wire-latency "
                "histogram's tail to one renderable trace "
                "(obs.timeline --trace <id> over the OBS log)"
            ),
        )
        if not ok:
            doc["reason"] = (
                f"failures={failures}, client_errs={len(client_errs)}, "
                f"primary_rc={primary_rc}, recovered={t_back is not None}, "
                f"promoted={promoted}, "
                f"crossing={attribution['kill_crossing_traces']}, "
                f"coverage_p50={cov}, unattributed_p50={unattr}, "
                f"promotion_obs={len(promotion_obs)}, "
                f"flight_dumps={len(flight_dumps)}"
            )
        say(f"chaos-rpc: ok={ok} batches={len(records)} "
            f"failures={failures} outage={doc.get('outage_s')}s "
            f"steady_p99={doc['steady']['p99_ms']}ms "
            f"promo_p99={doc['promotion_window']['p99_ms']}ms "
            f"traces={attribution['traces_completed']} "
            f"crossing={attribution['kill_crossing_traces']} "
            f"coverage_p50={cov}")
        return doc
    finally:
        if client_sink is not None:
            obs_trace.disable()
            obs_trace.remove_sink(client_sink)
            get_registry().remove_sink(client_sink)
        for p in (primary, standby):
            if p.poll() is None:
                p.terminate()
                try:
                    p.wait(20)
                except Exception:
                    _kill_replica(p)
        if client_sink is not None:
            client_sink.close()
        _ship_events(obs_f, root, "rpc_failover")


def _kill_replica(p) -> None:
    """Last-resort teardown for a replica that ignored SIGTERM: counted
    so a wedged shutdown is visible in the driver's event stream."""
    from ..obs.registry import get_registry

    get_registry().counter(
        "rpc.swallowed", site="scenario_teardown"
    ).inc()
    p.kill()


# --------------------------------------------------------------------- #
# Sharded serving scenario (ISSUE 12): router fan-out + hot-key cache
# --------------------------------------------------------------------- #
#: sharded scenario geometry. The keyspace (16k vertices) is TWICE the
#: router's default cache capacity, so the hot-key cache holds the
#: Zipfian HEAD, never the whole keyspace — hits are the power-law hot
#: set, tail keys keep fanning out. Load cells drive enough concurrent
#: connections to SATURATE (closed-loop latency-bound numbers would
#: measure scheduling, not capacity).
SHARDED_DEFAULTS = dict(
    n_vertices=1 << 14, n_edges=1 << 15, window=2048, seed=29,
    batch=32, measure_s=4.0, zipf_a=1.5, deadline_s=30.0, lease_s=0.4,
    # churn cell (ISSUE 17): ~1% of the keyspace touched per version
    # bump (each edge touches <= 2 vertices), paced so the routers
    # observe every bump as a separate refresh
    churn_bumps=24, churn_frac=0.01, churn_pace_s=0.15,
)

#: event-shard ids for the non-replica processes of the sharded story
#: (replicas are p0..p<n-1>)
ROUTER_SHARD = 10
CLIENT_SHARD = 11


def _spawn_shard_replicas(cell_dir: str, n: int, *, base_cfg: dict,
                          standby_shards=(), lease_s: float,
                          events: bool = False):
    """Spawn ``n`` shard primaries (each on its own serving directory),
    plus a standby for every shard in ``standby_shards``. Returns
    ``(procs, shard_addrs)`` where ``shard_addrs[k]`` lists the shard's
    primary (and standby) address — the router's per-shard failover
    address list. ``events`` attaches streaming ShardSinks (the
    EVIDENCE cell's shape; measurement-only cells skip them so the
    event stream never rides inside a QPS number)."""
    from ..serving.rpc import spawn_replica, wait_portfile

    procs = []
    from ..obs.cluster import shard_events_path

    for k in range(n):
        sdir = os.path.join(cell_dir, f"s{k}")
        cfg = dict(
            dir=sdir, role="primary", lease_s=lease_s, run_s=600.0,
            shard=k,
            cc_shard=dict(base_cfg, shard=k, nshards=n),
            portfile=os.path.join(cell_dir, f"s{k}.primary.port"),
        )
        if events:
            cfg["events"] = shard_events_path(cell_dir, k)
        procs.append(spawn_replica(cfg))
    for k in standby_shards:
        sdir = os.path.join(cell_dir, f"s{k}")
        cfg = dict(
            dir=sdir, role="standby", lease_s=lease_s, run_s=600.0,
            shard=100 + k,
            portfile=os.path.join(cell_dir, f"s{k}.standby.port"),
        )
        if events:
            cfg["events"] = shard_events_path(cell_dir, 100 + k)
        procs.append(spawn_replica(cfg))
    out = []
    for k in range(n):
        port = wait_portfile(
            os.path.join(cell_dir, f"s{k}.primary.port"))
        entry = [f"127.0.0.1:{port}"]
        if k in standby_shards:
            sport = wait_portfile(
                os.path.join(cell_dir, f"s{k}.standby.port"))
            entry.append(f"127.0.0.1:{sport}")
        out.append(entry)
    return procs, out


def _wait_watermark(addr, want: int, timeout_s: float = 120.0) -> None:
    """Block until the replica's published watermark reaches ``want``
    (its shard stream fully folded) — measurements must not race
    ingest."""
    from ..serving.client import RpcClient
    from ..serving.query import DegreeQuery

    cl = RpcClient([addr] if isinstance(addr, str) else addr)
    try:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            ans = cl.ask(DegreeQuery(0), timeout=30, deadline_s=30)
            if int(ans.watermark) >= want:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"shard at {addr} never reached watermark {want}"
        )
    finally:
        cl.close()


def _median_load(addrs, keys_fn, *, reps: int = 3, **kw):
    """``reps`` independent :func:`_drive_load` passes; returns the
    MEDIAN-qps pass's full dict with every pass's qps recorded. The
    gate-bearing cells use this: on a shared 2-core host a single pass
    swings tens of percent with scheduler luck, and a ratio of two
    single passes from different cells measures that luck, not the
    tier."""
    runs = sorted(
        (_drive_load(addrs, keys_fn, **kw) for _ in range(reps)),
        key=lambda d: d["qps"],
    )
    out = dict(runs[len(runs) // 2])
    out["qps_all"] = [d["qps"] for d in runs]
    # failure accounting must cover EVERY pass, not just the median one
    out["failures"] = sum(d["failures"] for d in runs)
    out["deadline_expired"] = sum(d["deadline_expired"] for d in runs)
    out["errors"] = [e for d in runs for e in d["errors"]]
    return out


def _drive_load(addrs, keys_fn, *, batch: int, duration_s: float,
                deadline_s: float, clients: int = 2, seed: int = 0,
                query_cls=None):
    """Closed-loop load: ``clients`` threads, each its own connection,
    each submitting ``batch``-query frames of ``query_cls`` over keys
    from ``keys_fn(rng, batch)`` until ``duration_s`` elapses. Returns
    aggregate qps + batch-latency percentiles + failure counts."""
    import threading

    import numpy as np

    from ..obs.registry import nearest_rank
    from ..serving.client import RpcClient
    from ..serving.query import DegreeQuery
    from .errors import DeadlineExceeded

    qcls = query_cls or DegreeQuery
    lock = threading.Lock()
    lats: list = []
    counts = [0, 0, 0]  # answered, failures, deadline_expired
    errs: list = []

    def drive(ci: int) -> None:
        rng = np.random.default_rng(seed + 1000 + ci)
        cl = RpcClient(addrs, seed=seed + ci)
        try:
            end = time.monotonic() + duration_s
            while time.monotonic() < end:
                ks = keys_fn(rng, batch)
                qs = [qcls(int(v)) for v in ks]
                t0 = time.perf_counter()
                futs = cl.submit_batch(qs, deadline_s=deadline_s)
                n_ok = n_dead = n_fail = 0
                for f in futs:
                    try:
                        f.result(deadline_s + 30)
                        n_ok += 1
                    except DeadlineExceeded:
                        n_dead += 1
                    except BaseException as e:
                        n_fail += 1
                        if len(errs) < 5:
                            errs.append(repr(e)[:200])
                lat = (time.perf_counter() - t0) * 1000.0
                with lock:
                    lats.append(lat)
                    counts[0] += n_ok
                    counts[1] += n_fail
                    counts[2] += n_dead
        except BaseException as e:
            with lock:
                errs.append(repr(e)[:400])
        finally:
            cl.close()

    threads = [
        threading.Thread(target=drive, args=(i,), daemon=True)
        for i in range(clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(duration_s + 120)
    wall = time.perf_counter() - t0
    lats.sort()
    return {
        "qps": round(counts[0] / wall, 1) if wall else 0.0,
        "batches": len(lats),
        "p50_ms": round(nearest_rank(lats, 50), 3) if lats else None,
        "p99_ms": round(nearest_rank(lats, 99), 3) if lats else None,
        "answered": counts[0],
        "failures": counts[1],
        "deadline_expired": counts[2],
        "errors": errs,
    }


def _teardown(procs) -> None:
    for p in procs:
        if p.poll() is None:
            p.terminate()
            try:
                p.wait(20)
            except Exception:
                _kill_replica(p)


def run_sharded_scenario(
    root: str,
    *,
    n_vertices: int = SHARDED_DEFAULTS["n_vertices"],
    n_edges: int = SHARDED_DEFAULTS["n_edges"],
    window: int = SHARDED_DEFAULTS["window"],
    seed: int = SHARDED_DEFAULTS["seed"],
    batch: int = SHARDED_DEFAULTS["batch"],
    measure_s: float = SHARDED_DEFAULTS["measure_s"],
    zipf_a: float = SHARDED_DEFAULTS["zipf_a"],
    deadline_s: float = SHARDED_DEFAULTS["deadline_s"],
    lease_s: float = SHARDED_DEFAULTS["lease_s"],
    churn_bumps: int = SHARDED_DEFAULTS["churn_bumps"],
    churn_frac: float = SHARDED_DEFAULTS["churn_frac"],
    churn_pace_s: float = SHARDED_DEFAULTS["churn_pace_s"],
    clients: int = 4,
    oracle_checks: int = 512,
    kill_hold_s: float = 1.0,
    post_kill_batches: int = 40,
    log: Optional[Callable[[str], None]] = None,
    obs_f=None,
) -> dict:
    """The sharded-serving proof (ISSUE 12): shard replicas + the
    routing tier as REAL processes on one box, measured end to end.

    Cells (each torn down before the next):

    - **c1** — one shard holding the WHOLE keyspace: the single-replica
      baseline, measured DIRECT (client -> replica, the PR 8 shape)
      under uniform and Zipfian key traffic, plus the router-with-one-
      shard cell of the scaling curve.
    - **c2** — two shards (shard 0 with a standby): the scaling cell,
      Zipfian latency with the hot-key cache OFF vs ON (the headline:
      cache-on aggregate QPS vs the c1 single-replica baseline), the
      cross-shard CC oracle-identity check, one TRACED batch whose
      spans must join client, router, and both shards, and the
      kill-one-shard point — shard 0's primary SIGKILLed under live
      per-owner traffic; the unaffected shard's keys must see ZERO
      failures (and no outage), shard 0's keys fail over to its
      standby with zero failures and a measured blip.
    - **c3** — the delta-pull churn cell (ISSUE 17): the 2-shard
      topology under LIVE INGEST (paced ~1%-touched version bumps),
      one pull-protocol-v2 router vs one full-re-pull baseline router
      on the same stream; the gate is per-refresh pulled bytes AND
      router merge-refresh time both >= 5x below the baseline, with a
      post-churn oracle identity check on both routers.
    - **c4** — four shards: the tail of the scaling curve.

    The box's core count is recorded (``host_cores``): on a 2-core
    host the cache-off fan-out cells are CORE-BOUND (router + shards +
    client share two cores; the honest plateau PR 11 documented for
    ingest applies here identically) — the headline is the cache tier,
    which REDUCES total work per query rather than spreading it.
    """
    import threading

    import numpy as np

    from ..core.ingest import partition_edges_by_vertex, vertex_owner
    from ..obs import trace as obs_trace
    from ..obs.cluster import ShardSink, shard_events_path
    from ..obs.registry import get_registry, nearest_rank
    from ..serving.client import RpcClient
    from ..serving.query import (
        ComponentSizeQuery,
        ConnectedQuery,
        DegreeQuery,
    )
    from ..serving.router import (
        demo_shard_edges,
        spawn_router,
    )
    from ..serving.rpc import wait_portfile
    from ..summaries.forest import fold_edges_host, resolve_flat_host

    say = log or (lambda s: print(s, file=sys.stderr, flush=True))
    os.makedirs(root, exist_ok=True)
    base_cfg = dict(
        n_vertices=n_vertices, n_edges=n_edges, seed=seed,
        window=window,
    )
    # the driver-side oracle: same generator, whole stream, one fold
    src, dst = demo_shard_edges(n_vertices, n_edges, seed)
    olab = fold_edges_host(
        np.arange(n_vertices, dtype=np.int32), src, dst)
    osizes = np.bincount(olab, minlength=n_vertices)
    odeg = (np.bincount(src, minlength=n_vertices)
            + np.bincount(dst, minlength=n_vertices))
    perm = np.random.default_rng(seed + 5).permutation(n_vertices)

    def uniform_keys(rng, k):
        return rng.integers(0, n_vertices, k)

    def zipf_keys(rng, k):
        return perm[(rng.zipf(zipf_a, k) - 1) % n_vertices]

    def shard_watermarks(n: int):
        parts = partition_edges_by_vertex(src, dst, None, n)
        return [len(s) for s, _d, _v in parts]

    doc: dict = {
        "config": dict(
            n_vertices=n_vertices, n_edges=n_edges, window=window,
            seed=seed, batch=batch, measure_s=measure_s,
            zipf_a=zipf_a, clients=clients, lease_s=lease_s,
            host_cores=os.cpu_count(),
        ),
    }

    # `deadline_s` names a PER-BATCH budget: every load-cell batch and
    # every kill-phase batch is an independent query set with its own
    # full budget (the rebind declares that intent — GL008 guards the
    # one-budget-re-spent shape, which the oracle/trace sections use
    # remaining-computations for)
    per_batch_deadline_s = float(deadline_s)

    def spawn_cell_router(cell_dir: str, shard_addrs, *, cache: bool,
                          tag: str, events: bool = False,
                          delta: bool = True):
        cfg = dict(
            shards=shard_addrs, cache=cache, delta=delta,
            portfile=os.path.join(cell_dir, f"router.{tag}.port"),
            meta=os.path.join(cell_dir, f"router.{tag}.meta.json"),
            run_s=600.0,
        )
        if events:
            cfg["events"] = shard_events_path(cell_dir, ROUTER_SHARD)
            cfg["shard"] = ROUTER_SHARD
        p = spawn_router(cfg)
        port = wait_portfile(cfg["portfile"])
        return p, f"127.0.0.1:{port}", cfg["meta"]

    scaling: dict = {}
    try:
        # ---- cell 1: single shard -------------------------------------- #
        c1 = os.path.join(root, "c1")
        os.makedirs(c1, exist_ok=True)
        procs, shard_addrs = _spawn_shard_replicas(
            c1, 1, base_cfg=base_cfg, lease_s=lease_s)
        try:
            _wait_watermark(shard_addrs[0], shard_watermarks(1)[0])
            say("sharded: c1 up (1 shard, whole keyspace)")
            direct_uniform = _drive_load(
                shard_addrs[0], uniform_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed)
            direct_zipf = _median_load(
                shard_addrs[0], zipf_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 1)
            rp, raddr, _meta = spawn_cell_router(
                c1, shard_addrs, cache=False, tag="off")
            routed1 = _drive_load(
                [raddr], uniform_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 2)
            _teardown([rp])
            scaling["s1"] = {"qps": routed1["qps"],
                             "p50_ms": routed1["p50_ms"],
                             "p99_ms": routed1["p99_ms"]}
            doc["single_replica"] = {
                "uniform": direct_uniform, "zipf": direct_zipf,
            }
            say(f"sharded: c1 direct zipf qps={direct_zipf['qps']} "
                f"routed-1shard qps={routed1['qps']}")
        finally:
            _teardown(procs)
            _ship_events(obs_f, c1, "c1")

        # ---- cell 2a: two shards, MEASUREMENT (no event sinks — the
        # QPS/latency cells must not time the evidence stream) --------- #
        c2 = os.path.join(root, "c2")
        os.makedirs(c2, exist_ok=True)
        procs, shard_addrs = _spawn_shard_replicas(
            c2, 2, base_cfg=base_cfg, lease_s=lease_s)
        client_sink = None
        try:
            wm = shard_watermarks(2)
            for k in range(2):
                _wait_watermark(shard_addrs[k][0], wm[k])
            say("sharded: c2 up (2 shards, measurement phase)")
            rp_off, raddr_off, _m = spawn_cell_router(
                c2, shard_addrs, cache=False, tag="off")
            routed2 = _drive_load(
                [raddr_off], uniform_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 3)
            scaling["s2"] = {"qps": routed2["qps"],
                             "p50_ms": routed2["p50_ms"],
                             "p99_ms": routed2["p99_ms"]}
            zipf_off = _median_load(
                [raddr_off], zipf_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 4)
            _teardown([rp_off])

            rp_on, raddr_on, meta_on = spawn_cell_router(
                c2, shard_addrs, cache=True, tag="on")
            # warm the Zipfian HEAD into the cache, then measure
            _drive_load([raddr_on], zipf_keys, batch=batch,
                        duration_s=2.0, deadline_s=per_batch_deadline_s,
                        clients=2, seed=seed + 5)
            zipf_on = _median_load(
                [raddr_on], zipf_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 6)
            # the cache's BEST case, measured for the record: a tiny
            # hot set (64 keys — "millions of users hammering a small
            # hot set"), every batch short-circuiting the fan-out
            hot_keys_arr = perm[:64]

            def hot_keys(rng, k):
                return rng.choice(hot_keys_arr, k)

            hot_on = _median_load(
                [raddr_on], hot_keys, batch=batch,
                duration_s=measure_s / 2, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 7)

            # ---- CC oracle identity through the router ---------------- #
            rng = np.random.default_rng(seed + 9)
            cl = RpcClient([raddr_on], seed=seed + 9)
            cc_bad = 0
            # ONE budget across the three sequential oracle batches
            # (GL008): each forward ships what remains of it
            odl = time.monotonic() + deadline_s

            def oremain() -> float:
                return max(0.5, odl - time.monotonic())

            try:
                us = rng.integers(0, n_vertices, oracle_checks)
                vs = rng.integers(0, n_vertices, oracle_checks)
                futs = cl.submit_batch(
                    [ConnectedQuery(int(a), int(b))
                     for a, b in zip(us, vs)],
                    deadline_s=oremain())
                for a, b, f in zip(us, vs, futs):
                    want = bool(olab[a] == olab[b])
                    if bool(f.result(60).value) is not want:
                        cc_bad += 1
                ks = rng.integers(0, n_vertices, oracle_checks)
                futs = cl.submit_batch(
                    [ComponentSizeQuery(int(v)) for v in ks],
                    deadline_s=oremain())
                for v, f in zip(ks, futs):
                    if int(f.result(60).value) != int(osizes[olab[v]]):
                        cc_bad += 1
                futs = cl.submit_batch(
                    [DegreeQuery(int(v)) for v in ks],
                    deadline_s=oremain())
                for v, f in zip(ks, futs):
                    if int(f.result(60).value) != int(odeg[v]):
                        cc_bad += 1
            finally:
                cl.close()
            doc["oracle"] = {
                "checked": int(3 * oracle_checks),
                "mismatches": int(cc_bad),
            }
            say(f"sharded: oracle checks {3 * oracle_checks}, "
                f"mismatches {cc_bad}")
            _teardown([rp_on])
            try:
                with open(meta_on) as f:
                    doc["router_cache_stats"] = json.load(f)
            except (OSError, ValueError):
                doc["router_cache_stats"] = None
        finally:
            _teardown(procs)

        # ---- cell 2b: two shards, EVIDENCE (event sinks everywhere:
        # same data, same partition — the traced join and the
        # kill-one-shard story, at story rates, not QPS rates). FRESH
        # serving directories: reusing 2a's would hand the new
        # replicas a dead predecessor's lease/mirror state (and the
        # standby would rightly promote over it) ----------------------- #
        c2e = os.path.join(root, "c2e")
        os.makedirs(c2e, exist_ok=True)
        procs, shard_addrs = _spawn_shard_replicas(
            c2e, 2, base_cfg=base_cfg, standby_shards=(0,),
            lease_s=lease_s, events=True)
        try:
            wm = shard_watermarks(2)
            for k in range(2):
                _wait_watermark(shard_addrs[k][0], wm[k])
            say("sharded: c2 evidence phase up (shard 0 has a standby)")
            rp_tr, raddr_tr, _mt = spawn_cell_router(
                c2e, shard_addrs, cache=False, tag="tr", events=True)

            # ---- traced batch: client -> router -> both shards -------- #
            client_sink = ShardSink(
                shard_events_path(c2e, CLIENT_SHARD),
                shard=CLIENT_SHARD)
            obs_trace.add_sink(client_sink)
            get_registry().add_sink(client_sink)
            obs_trace.enable(registry_spans=False)
            owners = vertex_owner(
                np.arange(n_vertices, dtype=np.int64), 2)
            some0 = np.where(owners == 0)[0][:batch // 2]
            some1 = np.where(owners == 1)[0][:batch // 2]
            cl = RpcClient([raddr_tr], seed=seed + 11)
            # one budget across the two traced batches (GL008)
            tdl = time.monotonic() + deadline_s
            try:
                qs = [DegreeQuery(int(v))
                      for v in np.concatenate([some0, some1])]
                for f in cl.submit_batch(
                    qs, deadline_s=max(0.5, tdl - time.monotonic())
                ):
                    f.result(60)
                qs = [ConnectedQuery(int(some0[0]), int(some1[0]))]
                for f in cl.submit_batch(
                    qs, deadline_s=max(0.5, tdl - time.monotonic())
                ):
                    f.result(60)
            finally:
                cl.close()
            obs_trace.disable()
            obs_trace.remove_sink(client_sink)
            get_registry().remove_sink(client_sink)
            client_sink.close()
            client_sink = None
            joined_trace, trace_shards = _find_joined_trace(c2e)
            doc["trace"] = {
                "joined_trace": joined_trace,
                "span_shards": trace_shards,
            }
            say(f"sharded: joined trace {joined_trace} across "
                f"{trace_shards}")

            # ---- kill one shard under live per-owner traffic ---------- #
            keys0 = np.where(owners == 0)[0]
            keys1 = np.where(owners == 1)[0]
            kill_seen = [None]
            kill_records: dict = {"affected": [], "unaffected": []}
            kill_errs: list = []
            kl = threading.Lock()
            stop_kill = threading.Event()
            from .errors import DeadlineExceeded

            def kill_drive(tag: str, keys: np.ndarray, ci: int) -> None:
                rng2 = np.random.default_rng(seed + 20 + ci)
                cl2 = RpcClient([raddr_tr], seed=seed + 20 + ci)
                # each loop batch is an INDEPENDENT query with its own
                # full budget (not one budget re-spent — the rebind is
                # the declared intent, GL008)
                per_batch_s = per_batch_deadline_s
                try:
                    post = 0
                    while post < post_kill_batches and \
                            not stop_kill.is_set():
                        ks = rng2.choice(keys, batch)
                        t0 = time.perf_counter()
                        futs = cl2.submit_batch(
                            [DegreeQuery(int(v)) for v in ks],
                            deadline_s=per_batch_s)
                        fails = 0
                        for f in futs:
                            try:
                                f.result(deadline_s + 30)
                            except DeadlineExceeded:
                                fails += 1
                            except BaseException:
                                fails += 1
                        t1 = time.perf_counter()
                        with kl:
                            kill_records[tag].append((t0, t1, fails))
                        if kill_seen[0] is not None and \
                                t1 > kill_seen[0]:
                            post += 1
                        time.sleep(0.005)
                except BaseException as e:
                    # a DEAD load generator would let the zero-failure
                    # gate pass vacuously (nobody left to observe the
                    # outage): its death is the scenario's failure,
                    # same contract as run_rpc_scenario's client_errs
                    with kl:
                        kill_errs.append(f"{tag}: {e!r:.300}")
                finally:
                    cl2.close()

            threads = [
                threading.Thread(target=kill_drive,
                                 args=("affected", keys0, 0),
                                 daemon=True),
                threading.Thread(target=kill_drive,
                                 args=("unaffected", keys1, 1),
                                 daemon=True),
            ]
            for t in threads:
                t.start()
            time.sleep(kill_hold_s)  # steady traffic before the kill
            procs[0].kill()          # shard 0's PRIMARY, hard
            procs[0].wait(30)
            kill_seen[0] = time.perf_counter()
            for t in threads:
                t.join(300)
            # a driver that never reached its post-kill quota (a stuck
            # failover) is STOPPED here and given a moment to exit;
            # aggregation below must read a quiesced copy, not a list
            # a live thread is still appending to
            stop_kill.set()
            for t in threads:
                t.join(30)
            with kl:
                kill_records = {
                    tag: list(recs)
                    for tag, recs in kill_records.items()
                }
            kill = {"primary_rc": procs[0].returncode}
            for tag in ("affected", "unaffected"):
                recs = kill_records[tag]
                fails = sum(r[2] for r in recs)
                post = [r for r in recs if kill_seen[0] is not None
                        and r[1] > kill_seen[0]]
                lats = sorted(
                    (r[1] - r[0]) * 1000.0 for r in post)
                kill[tag] = {
                    "batches": len(recs),
                    "post_kill_batches": len(post),
                    "failures": int(fails),
                    "post_kill_p99_ms": (
                        round(nearest_rank(lats, 99), 3)
                        if lats else None),
                    "post_kill_max_ms": (
                        round(lats[-1], 3) if lats else None),
                }
            # the standby's promotion evidence (shard 100+0's stream)
            sb_events = _read_jsonl(shard_events_path(c2e, 100))
            kill["promoted"] = any(
                e.get("name") == "serving.failover"
                and (e.get("labels") or {}).get("reason")
                == "lease_lapse"
                for e in sb_events
            )
            kill["driver_errors"] = list(kill_errs)
            doc["shard_kill"] = kill
            say(f"sharded: kill point — affected "
                f"failures={kill['affected']['failures']} "
                f"max={kill['affected']['post_kill_max_ms']}ms, "
                f"unaffected "
                f"failures={kill['unaffected']['failures']} "
                f"p99={kill['unaffected']['post_kill_p99_ms']}ms, "
                f"promoted={kill['promoted']}")

            _teardown([rp_tr])
        finally:
            if client_sink is not None:
                obs_trace.disable()
                obs_trace.remove_sink(client_sink)
                get_registry().remove_sink(client_sink)
                client_sink.close()
            _teardown(procs)
            _ship_events(obs_f, c2e, "c2")

        # ---- cell 3: delta-pull churn (ISSUE 17) ----------------------- #
        # the same 2-shard topology under LIVE INGEST: after the main
        # stream, each shard folds `churn_bumps` paced version bumps of
        # ~churn_frac touched vertices each. Two routers ride the same
        # stream — pull protocol v2 (delta=True) vs the full-re-pull
        # baseline (delta=False) — and the committed evidence is their
        # per-refresh pulled bytes and merge-refresh time, plus a
        # post-churn oracle identity check on BOTH.
        c3 = os.path.join(root, "c3")
        os.makedirs(c3, exist_ok=True)
        # the churn cell rides a 4x-larger keyspace than the load
        # cells: the claim under test is O(changed rows) vs O(forest),
        # and a bigger forest keeps the full-rebuild baseline well
        # clear of the box's scheduling-noise floor (~1-2ms per
        # refresh under cell load), which would otherwise dominate
        # BOTH sides of the ratio and wash the gate out
        churn_nv = 4 * n_vertices
        churn_edges = max(1, int(churn_nv * churn_frac) // 2)
        churn_seed = seed + 40
        # the shards hold their churn tails on this gate file until
        # both routers are up and the drivers are issuing queries —
        # otherwise the paced bumps race the routers' process boot and
        # the delta path has nothing to refresh against
        churn_gate = os.path.join(c3, "churn.go")
        procs, shard_addrs = _spawn_shard_replicas(
            c3, 2,
            base_cfg=dict(
                base_cfg, n_vertices=churn_nv,
                churn_bumps=churn_bumps,
                churn_edges=churn_edges, churn_seed=churn_seed,
                churn_pace_s=churn_pace_s, churn_gate=churn_gate,
            ),
            lease_s=lease_s)
        try:
            src3, dst3 = demo_shard_edges(churn_nv, n_edges, seed)
            parts3 = partition_edges_by_vertex(src3, dst3, None, 2)
            wm = [len(s) for s, _d, _v in parts3]
            for k in range(2):
                _wait_watermark(shard_addrs[k][0], wm[k])
            say(f"sharded: c3 up (2 shards + {churn_bumps} churn bumps "
                f"of {churn_edges} edges)")
            rp_d, raddr_d, meta_d = spawn_cell_router(
                c3, shard_addrs, cache=False, tag="delta")
            rp_f, raddr_f, meta_f = spawn_cell_router(
                c3, shard_addrs, cache=False, tag="full", delta=False)

            # driver-side post-churn oracle: the shards fold global
            # slice [k*churn_edges, (k+1)*churn_edges) at bump k, so
            # folding the WHOLE churn stream on top of the main fold
            # reproduces their final state exactly
            csrc, cdst = demo_shard_edges(
                churn_nv, churn_bumps * churn_edges, churn_seed)
            olab3 = fold_edges_host(
                np.arange(churn_nv, dtype=np.int32), src3, dst3)
            clab = resolve_flat_host(
                fold_edges_host(olab3, csrc, cdst))
            cparts = partition_edges_by_vertex(csrc, cdst, None, 2)
            final_wm = [wm[k] + len(cparts[k][0]) for k in range(2)]
            owners3 = vertex_owner(
                np.arange(churn_nv, dtype=np.int64), 2)
            probe = [int(np.where(owners3 == k)[0][0])
                     for k in range(2)]

            churn_errs: list = []

            def churn_drive(raddr: str, ci: int) -> None:
                # mixed load over live ingest: Connected queries hit
                # the merged forest (each version bump triggers the
                # next refresh), the Degree sprinkle carries fresh
                # per-shard version observations back to the router
                rng3 = np.random.default_rng(seed + 50 + ci)
                cl3 = RpcClient([raddr], seed=seed + 50 + ci)
                try:
                    end = (time.monotonic()
                           + churn_bumps * churn_pace_s + 4.0)
                    while time.monotonic() < end:
                        us3 = rng3.integers(0, churn_nv, batch - 2)
                        vs3 = rng3.integers(0, churn_nv, batch - 2)
                        qs3 = [ConnectedQuery(int(a), int(b))
                               for a, b in zip(us3, vs3)]
                        qs3 += [DegreeQuery(p) for p in probe]
                        for f in cl3.submit_batch(
                                qs3,
                                deadline_s=per_batch_deadline_s):
                            f.result(deadline_s + 30)
                        time.sleep(0.01)
                except BaseException as e:
                    churn_errs.append(f"r{ci}: {e!r:.300}")
                finally:
                    cl3.close()

            cthreads = [
                threading.Thread(target=churn_drive, args=(a, i),
                                 daemon=True)
                for i, a in enumerate((raddr_d, raddr_f))
            ]
            for t in cthreads:
                t.start()
            # both routers are live and under drive: release the
            # shards' churn tails
            with open(churn_gate, "w") as f:
                f.write("go")
            for t in cthreads:
                t.join(churn_bumps * churn_pace_s + 120)

            # converge each router onto the FINAL churned state, then
            # oracle-check its merged answers against the driver fold
            churn_bad = 0
            converged = []
            orng = np.random.default_rng(seed + 60)
            for raddr in (raddr_d, raddr_f):
                cl3 = RpcClient([raddr], seed=seed + 61)
                try:
                    cdl = time.monotonic() + deadline_s

                    def cremain() -> float:
                        return max(0.5, cdl - time.monotonic())

                    done = False
                    while time.monotonic() < cdl and not done:
                        ws = [int(cl3.ask(
                            DegreeQuery(probe[k]), timeout=30,
                            deadline_s=cremain()).watermark)
                            for k in range(2)]
                        ans = cl3.ask(
                            ConnectedQuery(probe[0], probe[1]),
                            timeout=30, deadline_s=cremain())
                        done = (
                            ws[0] >= final_wm[0]
                            and ws[1] >= final_wm[1]
                            and int(ans.watermark) >= sum(final_wm)
                        )
                        if not done:
                            time.sleep(0.05)
                    converged.append(done)
                    us3 = orng.integers(0, churn_nv, oracle_checks)
                    vs3 = orng.integers(0, churn_nv, oracle_checks)
                    futs = cl3.submit_batch(
                        [ConnectedQuery(int(a), int(b))
                         for a, b in zip(us3, vs3)],
                        deadline_s=cremain())
                    for a, b, f in zip(us3, vs3, futs):
                        want = bool(clab[a] == clab[b])
                        if bool(f.result(60).value) is not want:
                            churn_bad += 1
                finally:
                    cl3.close()
            _teardown([rp_d, rp_f])
            try:
                with open(meta_d) as f:
                    md = json.load(f)
                with open(meta_f) as f:
                    mf = json.load(f)
            except (OSError, ValueError):
                md = mf = None
            if md and mf:
                d_ref = max(1, md["merges_delta"])
                f_ref = max(1, mf["merges_full"])
                # per-refresh steady state: the delta router's boot
                # refresh is a full pull by construction and stays in
                # its *_full columns; the ratios compare what each
                # refresh COSTS once the tier is up
                d_bytes = md["pull_bytes_delta"] / d_ref
                f_bytes = mf["pull_bytes_full"] / f_ref
                d_merge = md["merge_s_delta"] / d_ref
                f_merge = mf["merge_s_full"] / f_ref
                bytes_x = f_bytes / max(d_bytes, 1.0)
                merge_x = f_merge / max(d_merge, 1e-6)
                churn_ok = (
                    not churn_errs and churn_bad == 0
                    and all(converged) and len(converged) == 2
                    and md["merges_delta"] >= 3
                    and mf["merges_full"] >= 3
                    and md["pull_malformed"] == 0
                    and mf["pull_malformed"] == 0
                    and bytes_x >= 5.0 and merge_x >= 5.0
                )
            else:
                d_bytes = f_bytes = d_merge = f_merge = None
                bytes_x = merge_x = None
                churn_ok = False
            doc["churn"] = {
                "config": dict(
                    churn_nv=churn_nv, churn_bumps=churn_bumps,
                    churn_edges=churn_edges, churn_frac=churn_frac,
                    churn_pace_s=churn_pace_s, churn_seed=churn_seed,
                ),
                "oracle_checked": int(2 * oracle_checks),
                "oracle_mismatches": int(churn_bad),
                "converged": converged,
                "driver_errors": list(churn_errs),
                "delta_router": md,
                "full_router": mf,
                "delta_bytes_per_refresh": (
                    round(d_bytes, 1) if d_bytes is not None else None),
                "full_bytes_per_refresh": (
                    round(f_bytes, 1) if f_bytes is not None else None),
                "delta_merge_s_per_refresh": (
                    round(d_merge, 6) if d_merge is not None else None),
                "full_merge_s_per_refresh": (
                    round(f_merge, 6) if f_merge is not None else None),
                "bytes_x": (
                    round(bytes_x, 1) if bytes_x is not None else None),
                "merge_x": (
                    round(merge_x, 1) if merge_x is not None else None),
                "churn_ok": churn_ok,
            }
            say(f"sharded: churn — delta {doc['churn']['delta_bytes_per_refresh']}B/refresh "
                f"vs full {doc['churn']['full_bytes_per_refresh']}B "
                f"({doc['churn']['bytes_x']}x), merge "
                f"{doc['churn']['delta_merge_s_per_refresh']}s vs "
                f"{doc['churn']['full_merge_s_per_refresh']}s "
                f"({doc['churn']['merge_x']}x), "
                f"mismatches={churn_bad}, ok={churn_ok}")
        finally:
            _teardown(procs)
            _ship_events(obs_f, c3, "c3")

        # ---- cell 4: scaling tail -------------------------------------- #
        c4 = os.path.join(root, "c4")
        os.makedirs(c4, exist_ok=True)
        procs, shard_addrs = _spawn_shard_replicas(
            c4, 4, base_cfg=base_cfg, lease_s=lease_s)
        try:
            wm = shard_watermarks(4)
            for k in range(4):
                _wait_watermark(shard_addrs[k][0], wm[k])
            rp, raddr, _m = spawn_cell_router(
                c4, shard_addrs, cache=False, tag="off")
            routed4 = _drive_load(
                [raddr], uniform_keys, batch=batch,
                duration_s=measure_s, deadline_s=per_batch_deadline_s,
                clients=clients, seed=seed + 30)
            _teardown([rp])
            scaling["s4"] = {"qps": routed4["qps"],
                             "p50_ms": routed4["p50_ms"],
                             "p99_ms": routed4["p99_ms"]}
        finally:
            _teardown(procs)
            _ship_events(obs_f, c4, "c4")

        # ---- verdict --------------------------------------------------- #
        single_zipf = doc["single_replica"]["zipf"]
        headline_x = (
            zipf_on["qps"] / single_zipf["qps"]
            if single_zipf["qps"] else None
        )
        doc["scaling"] = scaling
        doc["zipf"] = {
            "cache_off": zipf_off, "cache_on": zipf_on,
            "hot_set_cache_on": hot_on,
        }
        # the gate is CORE-AWARE, the PR 11 ingest precedent: the
        # fan-out's aggregate-QPS scaling needs cores for its extra
        # processes (client + router + N shards). On >= 4 cores the
        # Zipfian cache-on tier must beat a single replica >= 1.6x
        # (the acceptance bar). On a 2-core host every cell
        # time-slices the same two cores, so no process layout can
        # win aggregate QPS honestly; the fallback gate is that the
        # tier's HOT-SET path (every batch short-circuited at the
        # router) holds PARITY WITHIN MEASUREMENT NOISE (>= 0.7x a
        # bare replica, median-of-3 cells — single passes on this box
        # swing tens of percent with scheduler luck) — i.e. keyspace
        # partitioning, per-shard failover, and exact cross-shard
        # merges ride along at near-zero hot-path cost — with the
        # full curve recorded as core-bound.
        cores = os.cpu_count() or 1
        core_bound = cores < 4
        hot_x = (
            hot_on["qps"] / single_zipf["qps"]
            if single_zipf["qps"] else None
        )
        if core_bound:
            headline_ok = hot_x is not None and hot_x >= 0.7
            required = "hot_set_vs_single_x >= 0.7 (core-bound parity)"
        else:
            headline_ok = headline_x is not None and headline_x >= 1.6
            required = "vs_single_x >= 1.6"
        doc["headline"] = {
            "qps": zipf_on["qps"],
            "single_replica_qps": single_zipf["qps"],
            "vs_single_x": (
                round(headline_x, 3) if headline_x else None),
            "hot_set_qps": hot_on["qps"],
            "hot_set_vs_single_x": (
                round(hot_x, 3) if hot_x else None),
            "core_bound": core_bound,
            "host_cores": cores,
            "required": required,
            "headline_ok": headline_ok,
        }
        load_cells = (
            direct_uniform, direct_zipf, routed1, routed2,
            zipf_off, zipf_on, hot_on, routed4,
        )
        # driver-thread deaths count as failures: a dead load
        # generator would let every zero-failure gate pass vacuously
        # (the run_rpc_scenario client_errs contract)
        load_fail = sum(
            d["failures"] + d["deadline_expired"] + len(d["errors"])
            for d in load_cells
        )
        ok = (
            load_fail == 0
            and doc["oracle"]["mismatches"] == 0
            and headline_ok
            and zipf_on["p50_ms"] is not None
            and zipf_off["p50_ms"] is not None
            and zipf_on["p50_ms"] < zipf_off["p50_ms"]
            and doc["shard_kill"]["unaffected"]["failures"] == 0
            and doc["shard_kill"]["affected"]["failures"] == 0
            and not doc["shard_kill"]["driver_errors"]
            and doc["shard_kill"]["promoted"]
            and doc["trace"]["joined_trace"] is not None
            and doc["churn"]["churn_ok"]
        )
        doc["ok"] = ok
        doc["note"] = (
            "aggregate QPS and client-measured batch latency through "
            "the sharded routing tier on one box. scaling s1/s2/s4 is "
            "the cache-off fan-out curve — CORE-BOUND past host_cores "
            "(client + router + N shard processes time-slice the same "
            "cores; the honesty precedent is the ingest sweep's "
            "host_cores note), so on a 2-core host the curve records "
            "scheduling, not capacity, and the headline gate falls "
            "back to hot-set parity-within-noise vs a bare replica "
            "(headline.required; gate cells are median-of-3 passes). "
            "The headline compares the 2-shard "
            "tier UNDER ITS PRODUCTION CONFIG (hot-key cache, "
            "Zipfian traffic) against a single replica serving the "
            "same traffic directly; hot_set_qps is the cache's best "
            "case (64-key hot set, every batch short-circuiting the "
            "fan-out at the router). oracle: connected/size/degree "
            "answers vs a single-host fold of the whole stream. "
            "shard_kill: shard 0's primary SIGKILLed under live "
            "per-owner load; its standby promotes on lease lapse; "
            "the unaffected shard's keys see zero failures and no "
            "outage. churn: pull protocol v2 (since_version deltas) "
            "vs the full-re-pull baseline over the same live-ingest "
            "stream — per-refresh pulled bytes and router merge time "
            "must both sit >= 5x below the baseline, with post-churn "
            "oracle identity on both routers."
        )
        if not ok:
            doc["reason"] = (
                f"load_fail={load_fail}, "
                f"oracle_mismatches={doc['oracle']['mismatches']}, "
                f"headline={doc['headline']}, "
                f"cache_p50=({zipf_on['p50_ms']} vs "
                f"{zipf_off['p50_ms']}), "
                f"kill={doc['shard_kill']}, "
                f"trace={doc['trace']}, "
                f"churn={doc['churn']}"
            )
        say(f"sharded: ok={ok} scaling="
            f"{ {k: v['qps'] for k, v in scaling.items()} } "
            f"headline={zipf_on['qps']} "
            f"({doc['headline']['vs_single_x']}x single) "
            f"cache p50 {zipf_on['p50_ms']} vs {zipf_off['p50_ms']}")
        return doc
    finally:
        # per-cell teardown already ran in each cell's own finally; the
        # CALLER owns root's removal (bench keeps it for post-mortems)
        pass


def _find_joined_trace(root: str, *, exclude=None, require=None):
    """The first trace id whose spans include the client's batch root,
    the router's fan-out, and >= 2 distinct SHARD processes — the
    causal join the sharded story promises. Returns
    ``(trace_id or None, {shard: [span names]})`` for the best trace.

    ``exclude`` overrides the non-replica shard labels (the storm runs
    a router FLEET, so its routers sit on two event shards); ``require``
    names specific replica shards the join must cross (the storm's
    both-post-split-shards gate) instead of the any-two default."""
    from collections import defaultdict

    from ..obs.cluster import iter_shard_events

    if exclude is None:
        exclude = (f"p{ROUTER_SHARD}", f"p{CLIENT_SHARD}")
    excluded = set(exclude) | {"?"}
    by_trace: dict = defaultdict(list)
    for e in iter_shard_events(root):
        if e.get("kind") == "span" and e.get("trace"):
            by_trace[e["trace"]].append(e)
    best = (None, {})
    for tid in sorted(by_trace):
        spans = by_trace[tid]
        shards = defaultdict(list)
        for s in spans:
            shards[s.get("shard") or "?"].append(s["name"])
        names = {n for ns in shards.values() for n in ns}
        replica_shards = {
            sh for sh in shards if sh not in excluded
        }
        joined = (
            set(require) <= replica_shards if require is not None
            else len(replica_shards) >= 2
        )
        if (
            "rpc.client.batch" in names
            and "serving.router.fanout" in names
            and joined
        ):
            return tid, {k: sorted(set(v)) for k, v in shards.items()}
        if len(shards) > len(best[1]):
            best = (None, {k: sorted(set(v))
                           for k, v in shards.items()})
    return best


# --------------------------------------------------------------------- #
# Failover-storm scenario (ISSUE 19): router fleet + live split, one run
# --------------------------------------------------------------------- #
#: storm geometry. Smaller than SHARDED_DEFAULTS: the storm measures
#: SURVIVAL (zero client-visible failures through two kills and a live
#: split), not capacity, so the stream only needs to be big enough that
#: every phase runs under real concurrent load. ``target_wait_s`` is
#: the autotune budget — the storm's batches carry NO deadline, so the
#: admission tuners on both tiers compare queue waits against this
#: target (a kill blip breaches it, the quiet phases recover it: the
#: RETUNE lines of the timeline), while the shed floor stays far above
#: the closed-loop pending depth — tuning moves, shedding never bites.
STORM_DEFAULTS = dict(
    n_vertices=1 << 13, n_edges=1 << 14, window=2048, seed=31,
    batch=32, zipf_a=1.5, lease_s=0.4, phase_s=2.5, clients=3,
    oracle_checks=256, deadline_s=30.0, target_wait_s=0.05,
)

#: the storm's router FLEET is two processes; the first rides
#: ROUTER_SHARD, the second its own event shard (CLIENT_SHARD stays
#: the driver's)
STORM_ROUTER2_SHARD = 12
#: the split child's event shard IS its post-split shard index
STORM_CHILD_SHARD = 2


def _poll_events(path: str, pred, timeout_s: float) -> bool:
    """Poll one shard event file until ``pred`` matches an event (the
    cross-process evidence the storm driver sequences its phases on)."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if any(pred(e) for e in _read_jsonl(path)):
            return True
        time.sleep(0.1)
    return False


def run_storm_scenario(
    root: str,
    *,
    n_vertices: int = STORM_DEFAULTS["n_vertices"],
    n_edges: int = STORM_DEFAULTS["n_edges"],
    window: int = STORM_DEFAULTS["window"],
    seed: int = STORM_DEFAULTS["seed"],
    batch: int = STORM_DEFAULTS["batch"],
    zipf_a: float = STORM_DEFAULTS["zipf_a"],
    lease_s: float = STORM_DEFAULTS["lease_s"],
    phase_s: float = STORM_DEFAULTS["phase_s"],
    clients: int = STORM_DEFAULTS["clients"],
    oracle_checks: int = STORM_DEFAULTS["oracle_checks"],
    deadline_s: float = STORM_DEFAULTS["deadline_s"],
    target_wait_s: float = STORM_DEFAULTS["target_wait_s"],
    split_boot_timeout_s: float = 90.0,
    log: Optional[Callable[[str], None]] = None,
    obs_f=None,
) -> dict:
    """The failover-storm proof (ISSUE 19): one sustained Zipfian run
    through a router FLEET over 2 shard replicas, surviving — in one
    run, under continuous multi-connection load —

    1. **KILL** — SIGKILL one router of the fleet: clients cycle to the
       survivor on their per-fleet address lists (idempotent batch ids
       make the resubmit harmless), and the survivor's hot-key cache
       rebuilds from ordinary reply frames;
    2. **PROMOTE** — SIGKILL shard 0's primary: its standby promotes on
       lease lapse, the routers fail over through shard 0's address
       list;
    3. **SPLIT** — a live split of shard 1: the driver elects the plan
       over the fabric (one winner), a split child boots from the
       parent's snapshot mirror and publishes its address under epoch 1
       once servable, the surviving router adopts the epoch off reply-
       frame stamps and grows a third shard client mid-traffic;
    4. **RETUNE** — ``autotune=True`` on BOTH serving tiers throughout:
       the storm's blips move the admission knobs, the quiet phases
       recover them, and the gate is NO oscillation (at most one revert
       per knob per phase).

    Gates: zero client-visible failures across every phase (driver
    deaths count — the run_rpc_scenario client_errs contract), zero
    oracle mismatches post-split (connected/size/degree vs a single-
    host fold of the whole stream), at least one trace joining client
    -> surviving router -> BOTH post-split shards, promotion + adoption
    evidence in the shipped event streams, and the revert bound above.

    ISSUE 20 adds a TRANSACTIONAL lane: a client thread running
    snapshot-pinned multi-read transactions (:class:`~.txn.TxnContext`)
    through every phase. Gate: zero repeated-read / oracle violations,
    at least one committed transaction spanning each of KILL, PROMOTE,
    and SPLIT, and no lane failures other than typed, counted
    :class:`~.txn.TxnSnapshotExpired` honest expiries.
    """
    import threading

    import numpy as np

    from ..core.ingest import (
        partition_edges_by_vertex,
        vertex_owner_epoch,
    )
    from ..obs import trace as obs_trace
    from ..obs.cluster import ShardSink, shard_events_path
    from ..obs.registry import get_registry, nearest_rank
    from ..serving.client import RpcClient
    from ..serving.query import (
        ComponentSizeQuery,
        ConnectedQuery,
        DegreeQuery,
    )
    from ..serving.reshard import propose_split
    from ..serving.router import demo_shard_edges, spawn_router
    from ..serving.rpc import spawn_replica, wait_portfile
    from ..summaries.forest import fold_edges_host

    say = log or (lambda s: print(s, file=sys.stderr, flush=True))
    os.makedirs(root, exist_ok=True)
    store = os.path.join(root, "reshard")
    os.makedirs(store, exist_ok=True)
    base_cfg = dict(
        n_vertices=n_vertices, n_edges=n_edges, seed=seed,
        window=window,
    )
    # the driver-side oracle: same generator, whole stream, one fold —
    # the split child serves the PARENT's summary, so post-split
    # answers must still match this fold exactly
    src, dst = demo_shard_edges(n_vertices, n_edges, seed)
    olab = fold_edges_host(
        np.arange(n_vertices, dtype=np.int32), src, dst)
    osizes = np.bincount(olab, minlength=n_vertices)
    odeg = (np.bincount(src, minlength=n_vertices)
            + np.bincount(dst, minlength=n_vertices))
    perm = np.random.default_rng(seed + 5).permutation(n_vertices)

    def zipf_keys(rng, k):
        return perm[(rng.zipf(zipf_a, k) - 1) % n_vertices]

    doc: dict = {
        "config": dict(
            n_vertices=n_vertices, n_edges=n_edges, window=window,
            seed=seed, batch=batch, zipf_a=zipf_a, phase_s=phase_s,
            clients=clients, lease_s=lease_s,
            target_wait_s=target_wait_s,
            host_cores=os.cpu_count(),
        ),
    }
    #: the one split of the storm: shard 1 -> (1, 2) at epoch 1
    split_plan = dict(epoch=1, parent=1, child=2, salt=seed)

    procs: list = []
    routers: list = []
    client_sink = None
    #: (name, wall ts) — the storm's phase walls, in event-stream time
    phases: list = []
    try:
        # ---- boot: 2 shard primaries (+ shard 0 standby), autotune +
        # epoch stamping everywhere, event sinks everywhere (the storm
        # IS the evidence cell) ---------------------------------------- #
        for k in range(2):
            sdir = os.path.join(root, f"s{k}")
            procs.append(spawn_replica(dict(
                dir=sdir, role="primary", lease_s=lease_s,
                run_s=900.0, shard=k, autotune=True,
                target_wait_s=target_wait_s,
                reshard=dict(store=store, shard=k),
                cc_shard=dict(base_cfg, shard=k, nshards=2),
                portfile=os.path.join(root, f"s{k}.primary.port"),
                events=shard_events_path(root, k),
            )))
        procs.append(spawn_replica(dict(
            dir=os.path.join(root, "s0"), role="standby",
            lease_s=lease_s, run_s=900.0, shard=100, autotune=True,
            target_wait_s=target_wait_s,
            portfile=os.path.join(root, "s0.standby.port"),
            events=shard_events_path(root, 100),
        )))
        shard_addrs = []
        for k in range(2):
            entry = ["127.0.0.1:%d" % wait_portfile(
                os.path.join(root, f"s{k}.primary.port"))]
            if k == 0:
                entry.append("127.0.0.1:%d" % wait_portfile(
                    os.path.join(root, "s0.standby.port")))
            shard_addrs.append(entry)
        parts = partition_edges_by_vertex(src, dst, None, 2)
        wm = [len(s) for s, _d, _v in parts]
        for k in range(2):
            _wait_watermark(shard_addrs[k][0], wm[k])
        say("storm: 2 shards up (shard 0 has a standby)")

        def spawn_fleet_router(tag: str, ev_shard: int):
            cfg = dict(
                shards=shard_addrs, cache=True, delta=True,
                autotune=True, target_wait_s=target_wait_s,
                reshard=store, run_s=900.0,
                portfile=os.path.join(root, f"router.{tag}.port"),
                meta=os.path.join(root, f"router.{tag}.meta.json"),
                events=shard_events_path(root, ev_shard),
                shard=ev_shard,
            )
            p = spawn_router(cfg)
            return p, "127.0.0.1:%d" % wait_portfile(cfg["portfile"])

        r1p, r1addr = spawn_fleet_router("a", ROUTER_SHARD)
        r2p, r2addr = spawn_fleet_router("b", STORM_ROUTER2_SHARD)
        routers = [r1p, r2p]
        fleet = [r1addr, r2addr]
        say(f"storm: router fleet up ({r1addr}, {r2addr})")

        # the driver's own evidence stream (the split election + the
        # traced batch); tracing is enabled only around those moments
        # so the load loops below run at measurement rates
        client_sink = ShardSink(
            shard_events_path(root, CLIENT_SHARD), shard=CLIENT_SHARD)
        obs_trace.add_sink(client_sink)
        get_registry().add_sink(client_sink)

        # ---- the storm load: every phase runs under this ------------- #
        lock = threading.Lock()
        records: list = []  # (wall_t0, wall_t1, lat_ms, fails)
        errs: list = []
        stop = threading.Event()

        def storm_drive(ci: int) -> None:
            rng = np.random.default_rng(seed + 100 + ci)
            # the fleet list IS the client's address list; start_index
            # spreads the fleet so the router kill is a mid-traffic
            # failover for some clients, a no-op for the rest
            cl = RpcClient(fleet, seed=seed + 100 + ci,
                           start_index=ci)
            try:
                while not stop.is_set():
                    ks = zipf_keys(rng, batch)
                    w0 = time.time()
                    t0 = time.perf_counter()
                    # deadline-less on purpose: the admission tuners
                    # then judge queue waits against target_wait_s
                    # (see STORM_DEFAULTS), and no phase can trade a
                    # failure for a DeadlineExceeded
                    futs = cl.submit_batch(
                        [DegreeQuery(int(v)) for v in ks])
                    fails = 0
                    for f in futs:
                        try:
                            f.result(90)
                        except BaseException as e:
                            fails += 1
                            if len(errs) < 5:
                                with lock:
                                    errs.append(repr(e)[:200])
                    lat = (time.perf_counter() - t0) * 1000.0
                    with lock:
                        records.append((w0, time.time(), lat, fails))
                    time.sleep(0.002)
            except BaseException as e:
                # a DEAD load generator would let the zero-failure
                # gate pass vacuously: its death is the scenario's
                # failure (the run_rpc_scenario client_errs contract)
                with lock:
                    errs.append(f"driver{ci}: {e!r:.300}")
            finally:
                cl.close()

        # ---- the transactional lane (ISSUE 20): snapshot-pinned
        # multi-read transactions riding the same storm. Each txn pins
        # a per-shard snapshot vector from its first reads, re-reads
        # the same keys, and commits only if every repeat is BYTE-
        # IDENTICAL (value, version, boot lineage) and matches the
        # single-host oracle. A TxnSnapshotExpired is an HONEST
        # failure (typed, counted, never a silently fresher answer);
        # anything else is a driver error that fails the gate -------- #
        from ..serving.txn import TxnContext, TxnSnapshotExpired

        tlock = threading.Lock()
        txn_recs: list = []   # (wall_t0, wall_t1, committed)
        tstats = {"txns": 0, "committed": 0, "expired": 0,
                  "violations": 0, "reads": 0}
        texp_kinds: dict = {}
        terrs: list = []

        def txn_drive() -> None:
            cl = RpcClient(fleet, seed=seed + 500, start_index=1)
            rng = np.random.default_rng(seed + 500)
            try:
                while not stop.is_set():
                    w0 = time.time()
                    committed = False
                    expired = False
                    viol = 0
                    reads = 0
                    try:
                        t = TxnContext(deadline_s=90.0)
                        ks = [int(v) for v in zipf_keys(rng, 4)]
                        first = [cl.ask(DegreeQuery(k), timeout=90,
                                        txn=t) for k in ks]
                        again = [cl.ask(DegreeQuery(k), timeout=90,
                                        txn=t) for k in ks]
                        reads = len(first) + len(again)
                        for a, b in zip(first, again):
                            if (a.value, a.version, a.boot) != \
                                    (b.value, b.version, b.boot):
                                viol += 1
                        for k, a in zip(ks, first):
                            if int(a.value) != int(odeg[k]):
                                viol += 1
                        committed = True
                    except TxnSnapshotExpired as e:
                        expired = True
                        with tlock:
                            texp_kinds[e.kind] = \
                                texp_kinds.get(e.kind, 0) + 1
                    except BaseException as e:
                        with tlock:
                            if len(terrs) < 5:
                                terrs.append(repr(e)[:200])
                    with tlock:
                        tstats["txns"] += 1
                        tstats["committed"] += int(committed)
                        tstats["expired"] += int(expired)
                        tstats["violations"] += viol
                        tstats["reads"] += reads
                        txn_recs.append((w0, time.time(), committed))
                    time.sleep(0.002)
            except BaseException as e:
                # same contract as storm_drive: a dead transactional
                # lane must not let its gates pass vacuously
                with tlock:
                    terrs.append(f"txn_driver: {e!r:.300}")
            finally:
                cl.close()

        threads = [
            threading.Thread(target=storm_drive, args=(i,),
                             daemon=True)
            for i in range(clients)
        ] + [threading.Thread(target=txn_drive, daemon=True)]
        phases.append(("steady", time.time()))
        for t in threads:
            t.start()
        time.sleep(phase_s)

        # ---- phase 2: KILL one router of the fleet ------------------- #
        phases.append(("kill_router", time.time()))
        r1p.kill()
        r1p.wait(30)
        say("storm: router a SIGKILLed")
        time.sleep(phase_s)

        # ---- phase 3: KILL shard 0's primary -> PROMOTE -------------- #
        phases.append(("kill_shard", time.time()))
        procs[0].kill()
        procs[0].wait(30)
        say("storm: shard 0 primary SIGKILLed")
        promoted = _poll_events(
            shard_events_path(root, 100),
            lambda e: e.get("name") == "serving.failover"
            and (e.get("labels") or {}).get("reason") == "lease_lapse",
            timeout_s=max(phase_s, 10 * lease_s + 20.0),
        )
        say(f"storm: standby promoted={promoted}")
        time.sleep(phase_s)

        # ---- phase 4: SPLIT shard 1 live ----------------------------- #
        phases.append(("split", time.time()))
        # ONE split budget for the whole phase: the plan commit, the
        # child's snapshot restore + address publish, and the router's
        # adoption all spend from the same clock — each wait gets what
        # REMAINS, never the full original
        split_t0 = time.monotonic()

        def split_left() -> float:
            return max(1.0, split_boot_timeout_s
                       - (time.monotonic() - split_t0))

        obs_trace.enable(registry_spans=False)
        try:
            propose_split(
                store, split_plan["epoch"],
                parent=split_plan["parent"],
                child=split_plan["child"], salt=split_plan["salt"],
            )
        finally:
            obs_trace.disable()
        child_p = spawn_replica(dict(
            # the child FOLLOWS the parent's serving dir (snapshot
            # handoff + catch-up are the mirror it boots from)
            dir=os.path.join(root, "s1"), role="split",
            lease_s=lease_s, run_s=900.0, shard=STORM_CHILD_SHARD,
            autotune=True, target_wait_s=target_wait_s,
            reshard=dict(store=store, shard=STORM_CHILD_SHARD),
            split_epoch=split_plan["epoch"],
            split_boot_timeout_s=split_left(),
            portfile=os.path.join(root, "s2.split.port"),
            events=shard_events_path(root, STORM_CHILD_SHARD),
        ))
        procs.append(child_p)
        child_addr = "127.0.0.1:%d" % wait_portfile(
            os.path.join(root, "s2.split.port"),
            timeout_s=split_left())
        adopted = _poll_events(
            shard_events_path(root, STORM_ROUTER2_SHARD),
            lambda e: e.get("name") == "reshard.adopt"
            and (e.get("labels") or {}).get("site") == "router",
            timeout_s=split_left(),
        )
        say(f"storm: split child at {child_addr}, "
            f"router adopted={adopted}")

        # ---- phase 5: RETUNE — the tuners settle under the new
        # geometry while the load keeps running ------------------------ #
        phases.append(("retune", time.time()))
        time.sleep(phase_s)
        phases.append(("end", time.time()))
        stop.set()
        for t in threads:
            t.join(300)
        survivor_alive = r2p.poll() is None

        # ---- per-phase load accounting ------------------------------- #
        with lock:
            recs = list(records)
            errs = list(errs)
        walls = phases
        load: dict = {}
        for i, (name, t0w) in enumerate(walls[:-1]):
            t1w = walls[i + 1][1]
            in_phase = [r for r in recs if t0w <= r[1] < t1w]
            lats = sorted(r[2] for r in in_phase)
            load[name] = {
                "batches": len(in_phase),
                "failures": int(sum(r[3] for r in in_phase)),
                "p50_ms": (round(nearest_rank(lats, 50), 3)
                           if lats else None),
                "p99_ms": (round(nearest_rank(lats, 99), 3)
                           if lats else None),
            }
        total_failures = int(sum(r[3] for r in recs))
        doc["load"] = load
        wall = ((max(r[1] for r in recs) - min(r[0] for r in recs))
                if recs else 0.0)
        doc["load_total"] = {
            "batches": len(recs), "failures": total_failures,
            "driver_errors": errs,
            # client-visible throughput across the WHOLE storm — kills,
            # split, and retunes included (the benchguard min: watch)
            "qps": (round(len(recs) * batch / wall, 1)
                    if wall > 0 else None),
            # benchguard's ratio algebra skips a committed 0, so the
            # zero-failures contract ships as a 1/0 indicator watched
            # in the min: direction (a fresh 0 regresses, 1 passes)
            "zero_failures": int(total_failures == 0 and not errs),
        }

        # ---- transactional-lane accounting (ISSUE 20) ---------------- #
        with tlock:
            trecs = list(txn_recs)
            tstat = dict(tstats)
            texp = dict(texp_kinds)
            terr = list(terrs)
        spanning: dict = {}
        for name in ("kill_router", "kill_shard", "split"):
            i = next(i for i, (n, _t) in enumerate(walls)
                     if n == name)
            t0w, t1w = walls[i][1], walls[i + 1][1]
            # a txn SPANS the phase when its begin..commit interval
            # overlaps the phase window — only COMMITTED txns count
            # (an expired one proved honesty, not survival)
            spanning[name] = int(sum(
                1 for w0, w1, c in trecs
                if c and w0 < t1w and w1 > t0w))
        twall = ((max(r[1] for r in trecs) - min(r[0] for r in trecs))
                 if trecs else 0.0)
        # the committed 1/0 indicator benchguard watches min:-style —
        # zero repeated-read/oracle violations, no lane deaths, and at
        # least one committed txn spanning EACH chaos phase
        tzero = int(
            tstat["violations"] == 0 and not terr
            and all(v >= 1 for v in spanning.values())
        )
        doc["txn"] = {
            "txns": tstat["txns"],
            "committed": tstat["committed"],
            "expired": tstat["expired"],
            "expired_kinds": texp,
            "violations": tstat["violations"],
            "reads": tstat["reads"],
            "driver_errors": terr,
            "spanning": spanning,
            "qps": (round(tstat["reads"] / twall, 1)
                    if twall > 0 else None),
            "zero_violations": tzero,
        }
        say(f"storm: txn lane {tstat['txns']} txns "
            f"({tstat['committed']} committed, "
            f"{tstat['expired']} expired honestly), "
            f"violations={tstat['violations']}, spanning={spanning}")

        # ---- convergence + the joined trace -------------------------- #
        # both post-split shards must serve the FULL shard-1 stream
        _wait_watermark(shard_addrs[1][0], wm[1])
        _wait_watermark(child_addr, wm[1])
        owners = vertex_owner_epoch(
            np.arange(n_vertices, dtype=np.int64), 2, [split_plan])
        stay = np.where(owners == 1)[0][:batch // 2]
        moved = np.where(owners == 2)[0][:batch // 2]
        obs_trace.enable(registry_spans=False)
        cl = RpcClient([r2addr], seed=seed + 11)
        try:
            tdl = time.monotonic() + deadline_s
            for f in cl.submit_batch(
                [DegreeQuery(int(v))
                 for v in np.concatenate([stay, moved])],
                deadline_s=max(0.5, tdl - time.monotonic()),
            ):
                f.result(60)
        finally:
            cl.close()
            obs_trace.disable()
        joined_trace, trace_shards = _find_joined_trace(
            root,
            exclude=(f"p{ROUTER_SHARD}", f"p{STORM_ROUTER2_SHARD}",
                     f"p{CLIENT_SHARD}"),
            require={"p1", f"p{STORM_CHILD_SHARD}"},
        )
        doc["trace"] = {
            "joined_trace": joined_trace,
            "span_shards": trace_shards,
        }
        say(f"storm: joined trace {joined_trace} across "
            f"{trace_shards}")

        # ---- post-split oracle through the surviving router ---------- #
        rng = np.random.default_rng(seed + 9)
        cl = RpcClient([r2addr], seed=seed + 9)
        bad = 0
        odl = time.monotonic() + deadline_s

        def oremain() -> float:
            return max(0.5, odl - time.monotonic())

        try:
            us = rng.integers(0, n_vertices, oracle_checks)
            vs = rng.integers(0, n_vertices, oracle_checks)
            futs = cl.submit_batch(
                [ConnectedQuery(int(a), int(b))
                 for a, b in zip(us, vs)],
                deadline_s=oremain())
            for a, b, f in zip(us, vs, futs):
                want = bool(olab[a] == olab[b])
                if bool(f.result(60).value) is not want:
                    bad += 1
            # random keys plus BOTH halves of the split shard's
            # keyspace: the moved keys are the ones a mis-adopted
            # epoch would answer from the wrong table
            ks = np.concatenate([
                rng.integers(0, n_vertices, oracle_checks),
                stay, moved,
            ])
            futs = cl.submit_batch(
                [ComponentSizeQuery(int(v)) for v in ks],
                deadline_s=oremain())
            for v, f in zip(ks, futs):
                if int(f.result(60).value) != int(osizes[olab[v]]):
                    bad += 1
            futs = cl.submit_batch(
                [DegreeQuery(int(v)) for v in ks],
                deadline_s=oremain())
            for v, f in zip(ks, futs):
                if int(f.result(60).value) != int(odeg[v]):
                    bad += 1
        finally:
            cl.close()
        doc["oracle"] = {
            "checked": int(len(us) + 2 * len(ks)),
            "mismatches": int(bad),
        }
        say(f"storm: oracle checks {doc['oracle']['checked']}, "
            f"mismatches {bad}")

        # ---- retune timeline: moves allowed, oscillation is not ------ #
        from ..obs.cluster import iter_shard_events

        retunes: dict = {}
        for e in iter_shard_events(root):
            if e.get("name") != "control.retune":
                continue
            lab = e.get("labels") or {}
            key = (e.get("shard") or "?", lab.get("knob") or "?")
            retunes.setdefault(key, []).append(
                (e.get("ts") or 0.0, lab.get("from"), lab.get("to")))
        worst_reverts = 0
        retune_doc = []
        for (sh, knob), moves in sorted(retunes.items()):
            moves.sort()
            for i, (name, t0w) in enumerate(walls[:-1]):
                t1w = walls[i + 1][1]
                ph = [m for m in moves if t0w <= m[0] < t1w]
                # a revert is one A->B->A pair of CONSECUTIVE moves:
                # allowed once per phase (probe + settle), oscillation
                # is more
                rev = sum(
                    1 for a, b in zip(ph, ph[1:])
                    if a[1] == b[2] and a[2] == b[1]
                )
                if ph or rev:
                    retune_doc.append({
                        "shard": sh, "knob": knob, "phase": name,
                        "moves": len(ph), "reverts": rev,
                    })
                worst_reverts = max(worst_reverts, rev)
        doc["retune"] = {
            "timeline": retune_doc,
            "total_moves": int(sum(len(m) for m in retunes.values())),
            "worst_reverts_per_phase": int(worst_reverts),
        }

        # ---- evidence counts + verdict ------------------------------- #
        doc["storm"] = {
            "phases": [
                {"phase": n, "ts": t} for n, t in walls
            ],
            "promoted": bool(promoted),
            "router_killed_rc": r1p.returncode,
            "survivor_alive": bool(survivor_alive),
            "split_adopted": bool(adopted),
            "split_events": _count_events(
                shard_events_path(root, 1), "reshard.split"),
            "agree_events": _count_events(
                shard_events_path(root, CLIENT_SHARD),
                "reshard.agree"),
        }
        every_phase_loaded = all(
            load[n]["batches"] > 0 for n, _t in walls[:-1]
        )
        ok = (
            total_failures == 0
            and not errs
            and every_phase_loaded
            and promoted
            and adopted
            and survivor_alive
            and doc["storm"]["split_events"] >= 1
            and doc["oracle"]["mismatches"] == 0
            and doc["trace"]["joined_trace"] is not None
            and worst_reverts <= 1
            and doc["txn"]["zero_violations"] == 1
        )
        doc["ok"] = bool(ok)
        doc["note"] = (
            "the failover storm: one sustained Zipfian run through a "
            "2-router fleet over 2 shards, surviving a router SIGKILL "
            "(clients cycle to the survivor, idempotent batch ids "
            "make the resubmit harmless), a shard-primary SIGKILL "
            "(lease-lapse standby promotion), and a LIVE split of "
            "shard 1 (one-winner plan election, child boots from the "
            "parent's snapshot mirror, the surviving router adopts "
            "epoch 1 off reply-frame stamps and grows a third shard "
            "client mid-traffic) — with autotune on both tiers. "
            "Gates: zero client-visible failures in every phase "
            "(driver deaths count), zero oracle mismatches post-split "
            "vs a single-host fold, >=1 trace joining client -> "
            "surviving router -> both post-split shards, and no knob "
            "reverting more than once per phase. Batches carry no "
            "deadline so the admission tuners judge waits against "
            "target_wait_s; the shed floor sits far above the "
            "closed-loop pending depth, so knobs move but shedding "
            "never manufactures a failure. A transactional lane "
            "(ISSUE 20) runs snapshot-pinned multi-read transactions "
            "through the same storm: at least one committed txn spans "
            "each of KILL, PROMOTE, and SPLIT with zero repeated-read "
            "or oracle violations — the only permitted failures are "
            "typed, counted TxnSnapshotExpired honesty."
        )
        if not ok:
            doc["reason"] = (
                f"failures={total_failures}, errs={errs}, "
                f"loaded={every_phase_loaded}, promoted={promoted}, "
                f"adopted={adopted}, survivor={survivor_alive}, "
                f"split_events={doc['storm']['split_events']}, "
                f"oracle={doc['oracle']['mismatches']}, "
                f"trace={doc['trace']['joined_trace']}, "
                f"worst_reverts={worst_reverts}, "
                f"txn={doc['txn']['zero_violations']} "
                f"(violations={doc['txn']['violations']}, "
                f"spanning={doc['txn']['spanning']}, "
                f"errs={doc['txn']['driver_errors']})"
            )
        say(f"storm: ok={ok} failures={total_failures} "
            f"promoted={promoted} adopted={adopted} "
            f"retune_moves={doc['retune']['total_moves']} "
            f"worst_reverts={worst_reverts}")
        return doc
    finally:
        if client_sink is not None:
            obs_trace.disable()
            obs_trace.remove_sink(client_sink)
            get_registry().remove_sink(client_sink)
            client_sink.close()
        _teardown(routers)
        _teardown(procs)
        _ship_events(obs_f, root, "storm")
        # driver phase markers: the committed OBS timeline's
        # KILL -> PROMOTE -> SPLIT -> RETUNE walls
        _write_phase_markers(obs_f, phases)


# --------------------------------------------------------------------- #
# Driver
# --------------------------------------------------------------------- #
def _read_jsonl(path: str) -> list:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _count_events(events_path: str, name: str) -> int:
    return sum(
        1 for e in _read_jsonl(events_path) if e.get("name") == name
    )


def _count_rejections(events_path: str) -> int:
    return _count_events(events_path, "resilience.ckpt_rejected")


def _ship_events(obs_f, source, point: str) -> int:
    """Append one run directory's shard events (shard-stamped,
    ``ts``-ordered, tagged with the sweep point) to the merged obs log,
    plus one marker line per flight dump found there — the committed
    ``*_OBS.jsonl`` evidence the bench artifacts reference."""
    if obs_f is None:
        return 0
    from ..obs import flight as obs_flight
    from ..obs.cluster import iter_shard_events

    n = 0
    for ev in iter_shard_events(source):
        ev["point"] = point
        obs_f.write(json.dumps(ev) + "\n")
        n += 1
    root = source if isinstance(source, str) and os.path.isdir(source) \
        else None
    if root is not None:
        for p in obs_flight.find_dumps(root):
            try:
                doc = obs_flight.read_dump(p)
            except Exception:
                doc = {"reason": "unreadable", "n_events": None}
            obs_f.write(json.dumps({
                "kind": "meta", "name": "flight_dump", "point": point,
                "path": os.path.basename(p),
                "reason": doc.get("reason"),
                "n_events": doc.get("n_events"),
                "ts": os.path.getmtime(p),
            }) + "\n")
            n += 1
    obs_f.flush()
    return n


def _write_phase_markers(obs_f, phases) -> None:
    """Append one ``storm_phase`` meta line per driver phase wall to
    the merged obs log — the timeline renderer's section breaks."""
    if obs_f is None:
        return
    for name, ts in phases:
        obs_f.write(json.dumps({
            "kind": "meta", "name": "storm_phase",
            "phase": name, "ts": ts, "point": "storm",
        }) + "\n")
    obs_f.flush()


def run_sweep(
    *,
    windows: int = DEFAULTS["windows"],
    window_edges: int = DEFAULTS["window_edges"],
    superbatch: int = DEFAULTS["superbatch"],
    every: int = DEFAULTS["every"],
    seed: int = DEFAULTS["seed"],
    corrupt: bool = True,
    workdir: Optional[str] = None,
    obs_log: Optional[str] = None,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Kill-at-every-window sweep; returns the artifact document.

    For every ``k`` in ``1..windows``: run a worker that dies after
    ``k`` windows, then relaunch to completion, asserting the combined
    digest stream is oracle-identical and covers every window. With
    ``corrupt=True`` two kill points additionally flip-byte / truncate
    the committed barrier head between kill and resume, proving the
    fallback-to-previous-barrier path end to end (visible as
    ``ckpt_rejected`` counts in those points).

    ``obs_log`` commits the merged event evidence: every point's worker
    event stream (streamed to disk by the workers' :class:`ShardSink`,
    so pre-kill events are INCLUDED) plus flight-dump markers, one
    JSONL file, flushed point by point.
    """
    import shutil
    import tempfile

    from ..obs.registry import nearest_rank

    say = log or (lambda s: print(s, file=sys.stderr, flush=True))
    root = workdir or tempfile.mkdtemp(prefix="chaos_")
    obs_f = open(obs_log, "w") if obs_log else None
    try:
        geometry = dict(
            windows=windows, window_edges=window_edges,
            superbatch=superbatch, every=every, seed=seed,
        )

        def cfg_for(d: str, kill_after: int) -> dict:
            return dict(
                geometry,
                ckpt=os.path.join(d, "c.ckpt"),
                digests=os.path.join(d, "digests.jsonl"),
                events=os.path.join(d, "events.jsonl"),
                meta=os.path.join(d, "meta.json"),
                flight=os.path.join(d, "flight.json"),
                kill_after=kill_after,
            )

        # -- oracle: one uninterrupted run --------------------------------- #
        oracle_dir = os.path.join(root, "oracle")
        os.makedirs(oracle_dir, exist_ok=True)
        say(f"chaos: oracle run ({windows} windows x {window_edges} edges, "
            f"superbatch={superbatch}, every={every})...")
        r = _spawn_worker(cfg_for(oracle_dir, -1))
        if r.returncode != 0:
            raise RuntimeError(
                f"chaos oracle run failed rc={r.returncode}: {r.stderr[-2000:]}"
            )
        oracle = {
            line["o"]: line["d"]
            for line in _read_jsonl(os.path.join(oracle_dir, "digests.jsonl"))
        }
        if sorted(oracle) != list(range(windows)):
            raise RuntimeError(
                f"chaos oracle covered windows {sorted(oracle)}, "
                f"expected 0..{windows - 1}"
            )
        _ship_events(obs_f, oracle_dir, "oracle")

        # two corruption points (one per mode), centered in the sweep so a
        # barrier definitely exists to corrupt
        corrupt_at = {}
        if corrupt and windows >= 2 * every + 2:
            corrupt_at[max(every + 1, windows // 3)] = "flip"
            corrupt_at[max(every + 2, (2 * windows) // 3)] = "truncate"

        points = []
        all_ok = True
        for k in range(1, windows + 1):
            d = os.path.join(root, f"kill_{k:03d}")
            os.makedirs(d, exist_ok=True)
            cfg = cfg_for(d, k)
            point = {"kill_after": k, "corrupt": corrupt_at.get(k)}
            r = _spawn_worker(cfg)
            if r.returncode != KILL_RC:
                point.update(ok=False, reason=(
                    f"kill run rc={r.returncode} (expected {KILL_RC}): "
                    f"{r.stderr[-500:]}"
                ))
                points.append(point)
                all_ok = False
                _ship_events(obs_f, d, f"kill_{k:03d}")
                continue
            mode = corrupt_at.get(k)
            if mode is not None and os.path.exists(cfg["ckpt"]):
                from .faults import corrupt_file

                corrupt_file(cfg["ckpt"], mode, seed=seed + k)
            t0 = time.perf_counter()
            # the resume run gets its OWN flight base: the recorder's
            # no-overwrite suffixing is per-process, so a dump in the fresh
            # resume process would otherwise replace the kill's black box
            r = _spawn_worker(dict(
                cfg, kill_after=-1,
                flight=os.path.join(d, "flight.resume.json"),
            ))
            resume_s = time.perf_counter() - t0
            if r.returncode != 0:
                point.update(ok=False, reason=(
                    f"resume rc={r.returncode}: {r.stderr[-500:]}"
                ))
                points.append(point)
                all_ok = False
                _ship_events(obs_f, d, f"kill_{k:03d}")
                continue
            lines = _read_jsonl(cfg["digests"])
            bad = [
                line for line in lines if oracle.get(line["o"]) != line["d"]
            ]
            covered = sorted({line["o"] for line in lines})
            with open(cfg["meta"]) as f:
                meta = json.load(f)
            from ..obs import flight as obs_flight

            point.update(
                resume_s=round(resume_s, 3),
                first_emission_s=round(meta["first_emission_s"], 4)
                if meta["first_emission_s"] is not None else None,
                resumed_from=meta["resumed_from"],
                replayed=max(0, k - meta["resumed_from"]),
                in_process_restarts=meta["restarts"],
                ckpt_rejected=_count_rejections(cfg["events"]),
                flight_dumps=[
                    os.path.basename(p) for p in obs_flight.find_dumps(d)
                ],
            )
            # the kill fired under an installed recorder, so the point's
            # black box must exist — a sweep whose crashes leave no flight
            # evidence has lost its post-mortem story
            ok = (not bad and covered == list(range(windows))
                  and len(point["flight_dumps"]) >= 1)
            if mode is not None and meta["resumed_from"] > 0:
                # a corrupted head must have been REJECTED (visible in the
                # event log), never loaded
                ok = ok and point["ckpt_rejected"] >= 1
            point["ok"] = ok
            if not ok:
                point["reason"] = (
                    f"{len(bad)} digest mismatches, covered {len(covered)}/"
                    f"{windows} windows, "
                    f"{len(point['flight_dumps'])} flight dumps"
                )
                all_ok = False
            points.append(point)
            _ship_events(obs_f, d, f"kill_{k:03d}")
            say(f"chaos: kill@{k}"
                + (f"+{mode}" if mode else "")
                + f" -> resumed_from={point.get('resumed_from')} "
                f"rejected={point.get('ckpt_rejected')} ok={ok}")

        recov = sorted(
            p["first_emission_s"] for p in points
            if p.get("ok") and p.get("first_emission_s") is not None
        )
        resumes = sorted(
            p["resume_s"] for p in points if p.get("ok") and "resume_s" in p
        )
        doc = {
            "config": geometry,
            "ok": all_ok,
            "kill_points": len(points),
            "restarts_total": sum(
                1 + p.get("in_process_restarts", 0) for p in points
            ),
            "ckpt_rejected_total": sum(
                p.get("ckpt_rejected", 0) for p in points
            ),
            "flight_dumps_total": sum(
                len(p.get("flight_dumps", ())) for p in points
            ),
            "recovery_s": {
                # supervisor-measured: worker start to first (re-)emission,
                # i.e. restore + replay, excluding interpreter boot
                "p50": nearest_rank(recov, 50),
                "p90": nearest_rank(recov, 90),
                "max": recov[-1] if recov else None,
            },
            "resume_wall_s": {
                # full relaunch wall time; dominated by interpreter + jax
                # import on this harness's tiny windows
                "p50": nearest_rank(resumes, 50),
                "max": resumes[-1] if resumes else None,
            },
            "points": points,
            "note": (
                "every kill point must replay to oracle-identical digests "
                "over full window coverage AND leave >=1 flight-recorder "
                "dump (the kill's black box); corrupt points additionally "
                "require the torn head to be rejected (ckpt_rejected >= 1) "
                "with recovery from the previous barrier"
            ),
        }
        if obs_f is not None:
            doc["obs_log"] = os.path.basename(obs_log)
            obs_f.close()
        if workdir is None:
            shutil.rmtree(root, ignore_errors=True)
        return doc
    finally:
        # the obs log handle must not outlive the sweep, even when an
        # oracle check raises mid-sweep (the kept workdir still holds
        # the per-point evidence for the post-mortem)
        if obs_f is not None:
            obs_f.close()


# --------------------------------------------------------------------- #
# Multi-process driver: kill one worker of N at every window ordinal
# --------------------------------------------------------------------- #
def run_mp_sweep(
    *,
    processes: int = MP_DEFAULTS["processes"],
    windows: int = MP_DEFAULTS["windows"],
    window_edges: int = MP_DEFAULTS["window_edges"],
    superbatch: int = MP_DEFAULTS["superbatch"],
    every: int = MP_DEFAULTS["every"],
    seed: int = MP_DEFAULTS["seed"],
    transport: str = "shared_dir",
    corrupt: bool = True,
    failover: bool = True,
    rpc: bool = True,
    workdir: Optional[str] = None,
    obs_log: Optional[str] = None,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Distributed kill sweep over an N-process coordinated cluster.

    ``transport`` selects the per-window dict-exchange backend the
    workers ride: ``"shared_dir"`` (files under each point's
    ``exchange/``) or ``"socket"`` (the driver runs one
    :class:`~gelly_streaming_tpu.fabric.exchange.ExchangeDaemon` per
    point; workers speak GSRP frames to it, and the daemon — owned by
    the never-killed driver — carries exchange tags across worker kills
    and relaunches). Epoch barriers and rendezvous stay on the shared
    directory in both modes: the daemon's store is in-memory, so it is
    the honest home only for state whose replay window is one cluster
    incarnation.

    For every window ordinal ``k``, worker ``k % N`` dies hard after
    ``k`` windows; the :class:`ClusterSupervisor` terminates the rest
    and relaunches ALL workers, which rendezvous on the newest COMPLETE
    epoch and replay. Asserted per point: the combined digest stream is
    oracle-identical with full per-process window coverage, every
    relaunched worker resumed from the SAME epoch (no mixed-epoch
    restore, ever), and the final VertexDicts are byte-identical across
    processes and to the oracle's. One point additionally corrupts one
    shard of the newest complete epoch between kill and relaunch — the
    whole epoch must be skipped (torn, visible in the event logs) and
    every worker must fall back to the SAME previous epoch. With
    ``failover=True`` the sweep also runs the serving-replica failover
    scenario (:func:`failover_main`) and folds its evidence in;
    ``rpc=True`` additionally runs the CROSS-PROCESS wire scenario
    (:func:`run_rpc_scenario` — kill the primary serving binary under
    live multi-connection RPC traffic, standby promoted on lease
    lapse, zero client-visible failures).

    ``obs_log`` commits the sweep's MERGED, shard-labeled event stream:
    every worker's :class:`ShardSink` stream (all points, kills
    included — streaming sinks survive ``os._exit``), flight-dump
    markers, and the driver's own coordination events under shard
    ``driver``.
    """
    import shutil
    import subprocess
    import tempfile

    from ..obs.cluster import ShardSink, shard_events_path
    from ..obs.registry import get_registry, nearest_rank
    from .coordinated import ClusterSupervisor, select_epoch

    say = log or (lambda s: print(s, file=sys.stderr, flush=True))
    root = workdir or tempfile.mkdtemp(prefix="chaos_mp_")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    obs_f = open(obs_log, "w") if obs_log else None
    drv_sink = None
    if obs_f is not None:
        # the driver's registry carries the cluster-level half of the
        # story (cluster_restarts, epoch selection during corruption
        # probes); ship it as its own shard at the end
        drv_sink = ShardSink(os.path.join(root, "driver-events.jsonl"))
        get_registry().add_sink(drv_sink)
    daemons = {}  # point dir -> ExchangeDaemon (socket mode only)
    try:
        geometry = dict(
            processes=processes, windows=windows, window_edges=window_edges,
            superbatch=superbatch, every=every, seed=seed,
            transport=transport,
        )

        def start_daemon(d: str) -> None:
            if transport != "socket":
                return
            from ..fabric import ExchangeDaemon

            daemons[d] = ExchangeDaemon().start()

        def stop_daemon(d: str) -> None:
            dm = daemons.pop(d, None)
            if dm is not None:
                dm.stop()

        def cfg_for(d: str, pid: int, kill_after: int, victim: int,
                    attempt: int = 0) -> dict:
            return dict(
                geometry,
                root=d,
                exchange_addr=(
                    daemons[d].address if d in daemons else None
                ),
                process=pid,
                victim=victim,
                kill_after=kill_after,
                digests=os.path.join(d, f"digests.p{pid}.jsonl"),
                events=shard_events_path(d, pid),
                meta=os.path.join(d, f"meta.p{pid}.json"),
                flight=os.path.join(d, f"flight.p{pid}.a{attempt}.json"),
            )

        def spawner(d: str, victim: int, kill_after: int):
            """spawn(pid, attempt) for the ClusterSupervisor: the kill plan
            rides only the FIRST attempt; relaunches run clean. Worker
            output goes to per-attempt log files (no pipes — a terminated
            worker must never deadlock the driver on a full pipe)."""

            def spawn(pid: int, attempt: int):
                cfg = cfg_for(
                    d, pid,
                    kill_after if attempt == 0 else -1,
                    victim,
                    attempt=attempt,
                )
                log_path = os.path.join(d, f"worker.p{pid}.a{attempt}.log")
                with open(log_path, "wb") as logf:
                    # the child holds its own dup of the fd; closing the
                    # driver's copy immediately keeps the sweep from
                    # accumulating points x processes x attempts open files
                    p = subprocess.Popen(
                        [sys.executable, "-c", _worker_code("mp_worker_main"),
                         json.dumps(cfg)],
                        stdout=logf, stderr=subprocess.STDOUT, env=env,
                    )
                p.log_path = log_path  # ClusterError reads its tail
                return p

            return spawn

        def read_point(d: str) -> tuple:
            """(digest lines per (pid, o), metas per pid) for one point."""
            lines = {}
            bad_dupes = []
            for pid in range(processes):
                for line in _read_jsonl(
                    os.path.join(d, f"digests.p{pid}.jsonl")
                ):
                    key = (pid, line["o"])
                    if key in lines and lines[key] != line["d"]:
                        bad_dupes.append(key)
                    lines[key] = line["d"]
            metas = {}
            for pid in range(processes):
                p = os.path.join(d, f"meta.p{pid}.json")
                if os.path.exists(p):
                    with open(p) as f:
                        metas[pid] = json.load(f)
            return lines, metas, bad_dupes

        # -- oracle: one uninterrupted cluster run ------------------------- #
        oracle_dir = os.path.join(root, "oracle")
        os.makedirs(oracle_dir, exist_ok=True)
        say(f"chaos-mp: oracle cluster ({processes} procs x {windows} "
            f"windows x {window_edges} edges, superbatch={superbatch}, "
            f"every={every})...")
        start_daemon(oracle_dir)
        cs = ClusterSupervisor(
            spawner(oracle_dir, victim=-1, kill_after=-1), processes,
            restart_codes=(KILL_RC,), backoff_base_s=0.0,
            flight_dir=oracle_dir,
        )
        try:
            cs.run()
        finally:
            stop_daemon(oracle_dir)
        oracle, oracle_metas, dupes = read_point(oracle_dir)
        want_keys = {
            (pid, o) for pid in range(processes) for o in range(windows)
        }
        if set(oracle) != want_keys or dupes:
            raise RuntimeError(
                f"chaos-mp oracle covered {len(oracle)}/{len(want_keys)} "
                f"(pid, window) points ({len(dupes)} digest conflicts)"
            )
        oracle_vd = {m["vd_crc"] for m in oracle_metas.values()}
        if len(oracle_metas) != processes or len(oracle_vd) != 1:
            raise RuntimeError(
                f"chaos-mp oracle VertexDicts disagree across processes: "
                f"{oracle_vd}"
            )
        oracle_vd_crc = next(iter(oracle_vd))
        _ship_events(obs_f, oracle_dir, "oracle")

        # the torn-epoch corruption point: late enough that a fallback epoch
        # exists below the one being torn
        corrupt_k = max(2 * every + 2, windows // 2) if corrupt else None
        if corrupt_k is not None and corrupt_k > windows:
            corrupt_k = None

        points = []
        all_ok = True
        for k in range(1, windows + 1):
            d = os.path.join(root, f"kill_{k:03d}")
            os.makedirs(d, exist_ok=True)
            victim = k % processes
            point = {
                "kill_after": k,
                "victim": victim,
                "corrupt": "flip" if k == corrupt_k else None,
            }
            corrupted_epoch = {}

            def before_restart(attempt: int, _d=d, _k=k, _v=victim,
                               _ce=corrupted_epoch):
                if _k != corrupt_k or attempt != 1:
                    return
                ckpt_dir = os.path.join(_d, "ckpt")
                epoch = select_epoch(ckpt_dir, processes, record=False)
                if epoch is None:
                    return
                from .faults import corrupt_file

                shard = os.path.join(
                    ckpt_dir, f"e{epoch:08d}.p{_v}.ckpt"
                )
                if os.path.exists(shard):
                    corrupt_file(shard, "flip", seed=seed + _k)
                    _ce["epoch"] = epoch

            start_daemon(d)
            cs = ClusterSupervisor(
                spawner(d, victim=victim, kill_after=k), processes,
                restart_codes=(KILL_RC,), backoff_base_s=0.0,
                before_restart=before_restart,
                flight_dir=d,
            )
            t0 = time.perf_counter()
            try:
                res = cs.run()
            except Exception as e:
                # one unrecoverable point (a worker bug outside the
                # restart codes, an exhausted restart budget) must not
                # throw away the evidence of every point already measured
                # — record it failed and keep sweeping, like run_sweep
                point.update(
                    resume_s=round(time.perf_counter() - t0, 3),
                    ok=False,
                    reason=f"cluster did not recover: {e!r:.800}",
                    flight_dumps=[
                        os.path.basename(p) for p in cs.flight_dumps
                    ],
                )
                all_ok = False
                points.append(point)
                _ship_events(obs_f, d, f"kill_{k:03d}")
                say(f"chaos-mp: kill@{k} victim=p{victim} -> "
                    f"UNRECOVERED: {type(e).__name__}")
                continue
            finally:
                stop_daemon(d)
            resume_s = time.perf_counter() - t0
            lines, metas, dupes = read_point(d)
            bad = [
                key for key, dg in lines.items() if oracle.get(key) != dg
            ]
            covered_ok = set(lines) >= want_keys
            resumed = {m["resumed_epoch"] for m in metas.values()}
            vd_crcs = {m.get("vd_crc") for m in metas.values()}
            killed = [e for e in res["worker_exits"] if e[1] == KILL_RC]
            point.update(
                resume_s=round(resume_s, 3),
                cluster_restarts=res["restarts"],
                worker_exits=res["worker_exits"],
                resumed_epochs=sorted(resumed),
                first_emission_s=min(
                    (m["first_emission_s"] for m in metas.values()
                     if m.get("first_emission_s") is not None),
                    default=None,
                ),
                epoch_torn_events=sum(
                    _count_events(
                        shard_events_path(d, p),
                        "resilience.epoch_torn",
                    )
                    for p in range(processes)
                ),
                flight_dumps=[
                    os.path.basename(p) for p in res["flight_dumps"]
                ],
            )
            # the contract, point by point: oracle-identical digests over
            # full coverage; every relaunched worker restored from A
            # complete epoch; byte-identical dictionaries; the injected
            # kill really landed. Workers USUALLY agree on one epoch, but
            # agreement is time-of-scan dependent, not guaranteed: a fast
            # worker that restores from epoch e and replays forward
            # re-commits its shards along the way, and that healing commit
            # can COMPLETE a newer epoch (its peer's shard persisted from
            # before the kill) before a slower-booting peer runs its own
            # rendezvous — the peer then selects the newer epoch. Both
            # restores are complete-epoch restores (never mixed within a
            # process), and deterministic replay + digest dedupe make the
            # outcome identical, so skew is recorded (``epoch_agreed``)
            # but only CORRECTNESS failures fail the point.
            ok = (
                not bad and not dupes and covered_ok
                and len(metas) == processes
                and bool(resumed)
                and vd_crcs == {oracle_vd_crc}
                and killed and killed[0][0] == victim
                and res["restarts"] >= 1
                # the victim's kill fired under an installed flight
                # recorder; its dump is the point's black box and must be
                # in the ClusterSupervisor's failure report
                and len(point["flight_dumps"]) >= 1
            )
            point["epoch_agreed"] = len(resumed) == 1
            if k == corrupt_k and "epoch" in corrupted_epoch:
                # the FIRST rendezvous after the corruption must have
                # skipped the torn epoch (fallback strictly below it) and
                # visibly rejected it; a later selector may land back on
                # the corrupted ordinal only after a healing re-commit
                ok = ok and min(resumed) < corrupted_epoch["epoch"]
                ok = ok and point["epoch_torn_events"] >= 1
                point["corrupted_epoch"] = corrupted_epoch["epoch"]
            point["ok"] = ok
            if not ok:
                point["reason"] = (
                    f"{len(bad)} digest mismatches ({len(dupes)} conflicting "
                    f"dupes), covered={len(set(lines) & want_keys)}/"
                    f"{len(want_keys)}, resumed_epochs={sorted(resumed)}, "
                    f"vd_match={vd_crcs == {oracle_vd_crc}}, "
                    f"exits={res['worker_exits']}, "
                    f"flight_dumps={len(point['flight_dumps'])}"
                )
                all_ok = False
            points.append(point)
            _ship_events(obs_f, d, f"kill_{k:03d}")
            say(f"chaos-mp: kill@{k} victim=p{victim}"
                + ("+flip" if k == corrupt_k else "")
                + f" -> resumed_epoch={sorted(resumed)} "
                f"restarts={res['restarts']} ok={ok}")

        # -- serving replica failover point -------------------------------- #
        failover_doc = None
        if failover:
            fd = os.path.join(root, "failover")
            os.makedirs(fd, exist_ok=True)
            cfg = {
                "events": os.path.join(fd, "events.jsonl"),
                "meta": os.path.join(fd, "meta.json"),
                "flight": os.path.join(fd, "flight.json"),
                "seed": seed,
            }
            say("chaos-mp: serving failover scenario...")
            r = _spawn_worker(cfg, entry="failover_main")
            if r.returncode != 0:
                failover_doc = {
                    "ok": False,
                    "reason": f"rc={r.returncode}: {r.stderr[-800:]}",
                }
                all_ok = False
            else:
                with open(cfg["meta"]) as f:
                    meta = json.load(f)
                fo_ok = (
                    meta["promoted"] and meta["reanswered"] == 2
                    and meta["expired"] == 1 and meta["post"] == 1
                    and meta["failover_events"] >= 1
                    and _count_events(cfg["events"], "serving.failover") >= 1
                    # the promotion's latency is now measured, and the dead
                    # worker left its black box
                    and meta.get("promotion_seconds_count", 0) >= 1
                    and len(meta.get("flight_dumps", ())) >= 1
                )
                failover_doc = {"ok": fo_ok, **meta}
                all_ok = all_ok and fo_ok
            _ship_events(obs_f, fd, "failover")
            say(f"chaos-mp: failover ok={failover_doc['ok']}")

        # -- cross-process RPC failover point ------------------------------ #
        rpc_doc = None
        if rpc:
            say("chaos-mp: rpc cross-process failover scenario...")
            try:
                rpc_doc = run_rpc_scenario(
                    os.path.join(root, "rpc"),
                    seed=seed, clients=2, batch=8,
                    post_kill_batches=15, kill_at_sweep=100,
                    log=say, obs_f=obs_f,
                )
            except Exception as e:
                rpc_doc = {"ok": False, "reason": f"{e!r:.800}"}
            all_ok = all_ok and rpc_doc["ok"]

        recov = sorted(
            p["first_emission_s"] for p in points
            if p.get("ok") and p.get("first_emission_s") is not None
        )
        resumes = sorted(
            p["resume_s"] for p in points if p.get("ok") and "resume_s" in p
        )
        doc = {
            "config": geometry,
            "ok": all_ok,
            "kill_points": len(points),
            "cluster_restarts_total": sum(
                p.get("cluster_restarts", 0) for p in points
            ),
            "epoch_torn_events_total": sum(
                p.get("epoch_torn_events", 0) for p in points
            ),
            "flight_dumps_total": sum(
                len(p.get("flight_dumps", ())) for p in points
            ),
            "recovery_s": {
                # worker start to first (re-)emission after relaunch:
                # rendezvous + restore + replay, excluding interpreter boot
                "p50": nearest_rank(recov, 50),
                "p90": nearest_rank(recov, 90),
                "max": recov[-1] if recov else None,
            },
            "resume_wall_s": {
                "p50": nearest_rank(resumes, 50),
                "max": resumes[-1] if resumes else None,
            },
            "points": points,
            "failover": failover_doc,
            "rpc_failover": rpc_doc,
            "note": (
                "every kill-one-of-N point must replay to oracle-identical "
                "digests over full per-process coverage, with every worker "
                "resumed from a COMPLETE epoch (mixed-epoch restores are "
                "rejected by construction; cross-worker agreement is "
                "recorded per point as epoch_agreed) and byte-identical "
                "VertexDicts; "
                "the corrupt point must skip the torn epoch on every worker; "
                "every kill point must leave >=1 flight-recorder dump in "
                "the ClusterSupervisor report; "
                "the failover scenario must promote the standby (promotion "
                "latency measured) with expired queries failing "
                "DeadlineExceeded and the rest re-answered; "
                "the rpc_failover scenario must kill the primary serving "
                "BINARY under live wire traffic with zero client-visible "
                "failures and the standby promoted on lease lapse"
            ),
        }
        if obs_f is not None:
            get_registry().remove_sink(drv_sink)
            drv_sink.close()
            _ship_events(obs_f, {"driver": drv_sink.path}, "driver")
            doc["obs_log"] = os.path.basename(obs_log)
            obs_f.close()
        if workdir is None:
            shutil.rmtree(root, ignore_errors=True)
        return doc
    finally:
        # never leave the driver sink attached to the process-global
        # registry or the obs log handle open when an oracle check or
        # ClusterError aborts the sweep (both releases are idempotent
        # with the success path above; the kept workdir still holds
        # every black box)
        if drv_sink is not None:
            get_registry().remove_sink(drv_sink)
            drv_sink.close()
        if obs_f is not None:
            obs_f.close()
        for dm in daemons.values():  # an abort mid-point leaves one
            dm.stop()


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "worker":
        worker_main(json.loads(sys.argv[2]))
    elif len(sys.argv) >= 3 and sys.argv[1] == "mp_worker":
        mp_worker_main(json.loads(sys.argv[2]))
    elif "--multiprocess" in sys.argv:
        print(json.dumps(run_mp_sweep(), indent=2))
    else:
        print(json.dumps(run_sweep(), indent=2))
