"""Deterministic chaos harness: kill the CC pipeline at every window.

The recovery guarantee this repo claims — a killed process restarts
from the newest valid barrier and finishes with output value-identical
to an uninterrupted run — is only worth stating if something kills the
process at EVERY window and checks. This module is that something:

- :func:`run_sweep` runs an ORACLE pass of the superbatched CC pipeline
  (fixed seeded corpus, per-window emission digests), then for each
  kill point ``k`` launches a fresh worker process that dies hard
  (``os._exit``) after ``k`` windows, optionally corrupts the committed
  barrier head (flip-byte / truncate — the torn-checkpoint fault), and
  relaunches to completion. Every digest line any worker ever wrote
  must equal the oracle digest at its window ordinal, and together they
  must cover every window — which proves both recovery AND that
  replayed re-emissions are value-identical at every kill point.
- Workers append one flushed JSONL digest line per window BEFORE the
  kill hook fires, so the pre-crash evidence survives ``os._exit``; the
  obs registry's event log (written on clean exits) records every
  ``resilience.ckpt_rejected`` so torn artifacts are visibly rejected,
  never silently loaded.

Everything is seeded and index-driven (:mod:`~gelly_streaming_tpu.resilience.faults`),
so a failing kill point reproduces exactly. ``bench.py --chaos`` wraps
:func:`run_sweep` into the committed ``BENCH_CHAOS_CPU.json`` artifact
(recovery-time distribution + restart counts); the test suite runs a
reduced sweep (``-m chaos_full``) and the in-process fast subset
(``-m chaos_fast``).

Worker entry point (subprocess only)::

    python -m gelly_streaming_tpu.resilience.chaos worker '<json cfg>'
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Callable, Optional

#: worker exit code for an injected kill (distinct from real failures)
KILL_RC = 17

#: repo root (the directory holding ``gelly_streaming_tpu``), for
#: subprocess sys.path injection — workers must import this package
#: regardless of the driver's cwd
REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

#: default sweep geometry: small windows + superbatch=2 so barriers,
#: group boundaries, and kill points interleave in every phase
DEFAULTS = dict(
    windows=24, window_edges=256, superbatch=2, every=2, seed=1234
)


def corpus(seed: int, n_edges: int) -> list:
    """Deterministic edge list with SPARSE raw ids (vertex-dict replay
    must reproduce exact compact-id assignment across restarts — same
    discipline as ``tests/_ckpt_worker.py``)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    pairs = rng.integers(0, 600, size=(n_edges, 2))
    return [(int(a) * 7 + 3, int(b) * 7 + 3, 0.0) for a, b in pairs]


def digest(emission) -> str:
    """Stable fingerprint of one per-window emission (the Components
    string form is canonical: sorted roots, sorted members)."""
    import hashlib

    return hashlib.sha1(str(emission).encode()).hexdigest()[:16]


# --------------------------------------------------------------------- #
# Worker (runs in a subprocess; dies hard at the kill point)
# --------------------------------------------------------------------- #
def worker_main(cfg: dict) -> None:
    """Drive the supervised CC pipeline once. ``cfg`` keys: ``ckpt``,
    ``digests``, ``events``, ``meta`` (paths), ``kill_after`` (windows
    consumed before ``os._exit(KILL_RC)``; -1 = run to completion),
    plus the sweep geometry (``windows``/``window_edges``/``superbatch``
    /``every``/``seed``)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from ..aggregate.autockpt import AutoCheckpoint
    from ..core.stream import SimpleEdgeStream
    from ..core.window import CountWindow
    from ..library import ConnectedComponents
    from ..obs.export import JsonlSink
    from ..obs.registry import get_registry
    from . import faults
    from .supervisor import Supervisor

    raw = corpus(cfg["seed"], cfg["windows"] * cfg["window_edges"])
    sink = JsonlSink(cfg["events"])
    get_registry().add_sink(sink)

    def make_stream(vd):
        return SimpleEdgeStream(
            raw, window=CountWindow(cfg["window_edges"]), vertex_dict=vd
        )

    def make_work():
        return ConnectedComponents(superbatch=cfg["superbatch"])

    ac = AutoCheckpoint(cfg["ckpt"], every=cfg["every"], keep=3)
    resumed_from = ac.windows_done()
    sup = Supervisor(
        ac, backoff_base_s=0.0, jitter=0.0, seed=cfg["seed"]
    )
    kill_after = int(cfg.get("kill_after", -1))
    if kill_after >= 0:
        faults.install(faults.FaultPlan(
            seed=cfg["seed"],
            kill_at_window=kill_after - 1,
            kill_exit_code=KILL_RC,
        ))
    t0 = time.perf_counter()
    first = None
    yielded = 0
    with open(cfg["digests"], "a") as out:
        ordinal = resumed_from
        for comps in sup.run(make_stream, make_work):
            if first is None:
                first = time.perf_counter() - t0
            out.write(json.dumps({"o": ordinal, "d": digest(comps)}) + "\n")
            # flush BEFORE the kill hook: os._exit drops python-level
            # buffers, and the pre-crash digest lines are the evidence
            out.flush()
            if faults.active():
                faults.fire("chaos.window", index=ordinal)
            ordinal += 1
            yielded += 1
    with open(cfg["meta"], "w") as f:
        json.dump({
            "resumed_from": resumed_from,
            "restarts": sup.restarts,
            "yielded": yielded,
            "first_emission_s": first,
            "total_s": time.perf_counter() - t0,
        }, f)
    sink.write()
    get_registry().remove_sink(sink)
    faults.clear()


def _spawn_worker(cfg: dict, timeout: float = 600.0):
    import subprocess

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    code = (
        "import sys, json; "
        f"sys.path.insert(0, {REPO_ROOT!r}); "
        "from gelly_streaming_tpu.resilience import chaos; "
        "chaos.worker_main(json.loads(sys.argv[1]))"
    )
    return subprocess.run(
        [sys.executable, "-c", code, json.dumps(cfg)],
        capture_output=True, text=True, timeout=timeout, env=env,
    )


# --------------------------------------------------------------------- #
# Driver
# --------------------------------------------------------------------- #
def _read_jsonl(path: str) -> list:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                out.append(json.loads(line))
    return out


def _count_rejections(events_path: str) -> int:
    return sum(
        1 for e in _read_jsonl(events_path)
        if e.get("name") == "resilience.ckpt_rejected"
    )


def run_sweep(
    *,
    windows: int = DEFAULTS["windows"],
    window_edges: int = DEFAULTS["window_edges"],
    superbatch: int = DEFAULTS["superbatch"],
    every: int = DEFAULTS["every"],
    seed: int = DEFAULTS["seed"],
    corrupt: bool = True,
    workdir: Optional[str] = None,
    log: Optional[Callable[[str], None]] = None,
) -> dict:
    """Kill-at-every-window sweep; returns the artifact document.

    For every ``k`` in ``1..windows``: run a worker that dies after
    ``k`` windows, then relaunch to completion, asserting the combined
    digest stream is oracle-identical and covers every window. With
    ``corrupt=True`` two kill points additionally flip-byte / truncate
    the committed barrier head between kill and resume, proving the
    fallback-to-previous-barrier path end to end (visible as
    ``ckpt_rejected`` counts in those points).
    """
    import shutil
    import tempfile

    from ..obs.registry import nearest_rank

    say = log or (lambda s: print(s, file=sys.stderr, flush=True))
    root = workdir or tempfile.mkdtemp(prefix="chaos_")
    geometry = dict(
        windows=windows, window_edges=window_edges,
        superbatch=superbatch, every=every, seed=seed,
    )

    def cfg_for(d: str, kill_after: int) -> dict:
        return dict(
            geometry,
            ckpt=os.path.join(d, "c.ckpt"),
            digests=os.path.join(d, "digests.jsonl"),
            events=os.path.join(d, "events.jsonl"),
            meta=os.path.join(d, "meta.json"),
            kill_after=kill_after,
        )

    # -- oracle: one uninterrupted run --------------------------------- #
    oracle_dir = os.path.join(root, "oracle")
    os.makedirs(oracle_dir, exist_ok=True)
    say(f"chaos: oracle run ({windows} windows x {window_edges} edges, "
        f"superbatch={superbatch}, every={every})...")
    r = _spawn_worker(cfg_for(oracle_dir, -1))
    if r.returncode != 0:
        raise RuntimeError(
            f"chaos oracle run failed rc={r.returncode}: {r.stderr[-2000:]}"
        )
    oracle = {
        line["o"]: line["d"]
        for line in _read_jsonl(os.path.join(oracle_dir, "digests.jsonl"))
    }
    if sorted(oracle) != list(range(windows)):
        raise RuntimeError(
            f"chaos oracle covered windows {sorted(oracle)}, "
            f"expected 0..{windows - 1}"
        )

    # two corruption points (one per mode), centered in the sweep so a
    # barrier definitely exists to corrupt
    corrupt_at = {}
    if corrupt and windows >= 2 * every + 2:
        corrupt_at[max(every + 1, windows // 3)] = "flip"
        corrupt_at[max(every + 2, (2 * windows) // 3)] = "truncate"

    points = []
    all_ok = True
    for k in range(1, windows + 1):
        d = os.path.join(root, f"kill_{k:03d}")
        os.makedirs(d, exist_ok=True)
        cfg = cfg_for(d, k)
        point = {"kill_after": k, "corrupt": corrupt_at.get(k)}
        r = _spawn_worker(cfg)
        if r.returncode != KILL_RC:
            point.update(ok=False, reason=(
                f"kill run rc={r.returncode} (expected {KILL_RC}): "
                f"{r.stderr[-500:]}"
            ))
            points.append(point)
            all_ok = False
            continue
        mode = corrupt_at.get(k)
        if mode is not None and os.path.exists(cfg["ckpt"]):
            from .faults import corrupt_file

            corrupt_file(cfg["ckpt"], mode, seed=seed + k)
        t0 = time.perf_counter()
        r = _spawn_worker(dict(cfg, kill_after=-1))
        resume_s = time.perf_counter() - t0
        if r.returncode != 0:
            point.update(ok=False, reason=(
                f"resume rc={r.returncode}: {r.stderr[-500:]}"
            ))
            points.append(point)
            all_ok = False
            continue
        lines = _read_jsonl(cfg["digests"])
        bad = [
            line for line in lines if oracle.get(line["o"]) != line["d"]
        ]
        covered = sorted({line["o"] for line in lines})
        with open(cfg["meta"]) as f:
            meta = json.load(f)
        point.update(
            resume_s=round(resume_s, 3),
            first_emission_s=round(meta["first_emission_s"], 4)
            if meta["first_emission_s"] is not None else None,
            resumed_from=meta["resumed_from"],
            replayed=max(0, k - meta["resumed_from"]),
            in_process_restarts=meta["restarts"],
            ckpt_rejected=_count_rejections(cfg["events"]),
        )
        ok = not bad and covered == list(range(windows))
        if mode is not None and meta["resumed_from"] > 0:
            # a corrupted head must have been REJECTED (visible in the
            # event log), never loaded
            ok = ok and point["ckpt_rejected"] >= 1
        point["ok"] = ok
        if not ok:
            point["reason"] = (
                f"{len(bad)} digest mismatches, covered {len(covered)}/"
                f"{windows} windows"
            )
            all_ok = False
        points.append(point)
        say(f"chaos: kill@{k}"
            + (f"+{mode}" if mode else "")
            + f" -> resumed_from={point.get('resumed_from')} "
            f"rejected={point.get('ckpt_rejected')} ok={ok}")

    recov = sorted(
        p["first_emission_s"] for p in points
        if p.get("ok") and p.get("first_emission_s") is not None
    )
    resumes = sorted(
        p["resume_s"] for p in points if p.get("ok") and "resume_s" in p
    )
    doc = {
        "config": geometry,
        "ok": all_ok,
        "kill_points": len(points),
        "restarts_total": sum(
            1 + p.get("in_process_restarts", 0) for p in points
        ),
        "ckpt_rejected_total": sum(
            p.get("ckpt_rejected", 0) for p in points
        ),
        "recovery_s": {
            # supervisor-measured: worker start to first (re-)emission,
            # i.e. restore + replay, excluding interpreter boot
            "p50": nearest_rank(recov, 50),
            "p90": nearest_rank(recov, 90),
            "max": recov[-1] if recov else None,
        },
        "resume_wall_s": {
            # full relaunch wall time; dominated by interpreter + jax
            # import on this harness's tiny windows
            "p50": nearest_rank(resumes, 50),
            "max": resumes[-1] if resumes else None,
        },
        "points": points,
        "note": (
            "every kill point must replay to oracle-identical digests "
            "over full window coverage; corrupt points additionally "
            "require the torn head to be rejected (ckpt_rejected >= 1) "
            "with recovery from the previous barrier"
        ),
    }
    if workdir is None:
        shutil.rmtree(root, ignore_errors=True)
    return doc


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "worker":
        worker_main(json.loads(sys.argv[2]))
    else:
        print(json.dumps(run_sweep(), indent=2))
