"""Streaming GraphSAGE: GNN layers over the window stream (BASELINE #5).

Not in the reference (it has no ML component) — BASELINE.json adds a
"Streaming GraphSAGE layer over the window stream (GNN-style
reduceOnEdges)". The layer is designed MXU-first:

- Neighbor aggregation is a masked mean over edge messages — the same
  ``segment_sum`` primitive as ``reduce_on_edges`` (P2 parallelism), feeding
  two large ``[V, F] @ [F, F']`` matmuls (self + neighbor paths) that run on
  the MXU in bfloat16 (params/activations bf16, accumulation f32 via
  ``preferred_element_type``).
- Multi-chip: edge messages shard over the ``"edges"`` mesh axis (DP), the
  output feature dimension of the weights over ``"model"`` (TP); the
  aggregation all-reduces over the edge axis only
  (:func:`make_sharded_train_step`), so collectives ride ICI.

Plain-pytree parameters (no flax dependency), matching the rest of the
framework.
"""

from __future__ import annotations

import functools
from typing import Dict, Iterator, List

import jax
import jax.numpy as jnp
import numpy as np

from ..core.edgeblock import EdgeAccumulator


def init_graphsage(
    key,
    dims: List[int],
    dtype=jnp.bfloat16,
) -> List[Dict[str, jax.Array]]:
    """He-initialized stack of SAGE layers; ``dims = [in, h1, ..., out]``."""
    params = []
    for i, (fi, fo) in enumerate(zip(dims[:-1], dims[1:])):
        key, k1, k2 = jax.random.split(key, 3)
        scale = jnp.sqrt(2.0 / fi).astype(jnp.float32)
        params.append(
            {
                "w_self": (jax.random.normal(k1, (fi, fo)) * scale).astype(dtype),
                "w_nbr": (jax.random.normal(k2, (fi, fo)) * scale).astype(dtype),
                "b": jnp.zeros((fo,), dtype),
            }
        )
    return params


def mean_aggregate(h, src, dst, mask, num_vertices: int, axis_name=None):
    """Masked mean of in-neighbor features: messages flow src -> dst.

    ``axis_name``: inside ``shard_map`` with the edge columns sharded over
    that mesh axis, the partial sums/counts all-reduce over ICI (P1 edge
    sharding + P3 reduce) before the divide — the sharded mean is exact."""
    m = mask.astype(h.dtype)
    msgs = h[src] * m[:, None]
    agg = jnp.zeros((num_vertices, h.shape[1]), h.dtype).at[dst].add(msgs)
    cnt = jnp.zeros(num_vertices, h.dtype).at[dst].add(m)
    if axis_name is not None:
        agg = jax.lax.psum(agg, axis_name)
        cnt = jax.lax.psum(cnt, axis_name)
    return agg / jnp.maximum(cnt, 1.0)[:, None]


def sage_layer(
    params, h, src, dst, mask, *, activation=jax.nn.relu, use_pallas=False,
    axis_name=None,
):
    """One GraphSAGE layer: act(h @ W_self + mean_nbr(h) @ W_nbr + b).

    ``use_pallas=True`` routes the dense dual-matmul through the fused
    Pallas kernel (``ops/pallas_kernels.py``) — relu activation only;
    aggregation stays on the XLA scatter path either way.
    """
    agg = mean_aggregate(h, src, dst, mask, h.shape[0], axis_name=axis_name)
    if use_pallas:
        from ..ops.pallas_kernels import fused_sage_matmul, pallas_available

        if activation is not jax.nn.relu:
            raise ValueError(
                "use_pallas=True supports only the default relu activation"
            )
        if pallas_available():
            return fused_sage_matmul(
                h, agg, params["w_self"], params["w_nbr"], params["b"],
                activation="relu",
            )
        # off-TPU: the XLA dense path below is the fast fallback
        # (interpret mode is a test-only emulator)
    out = (
        jnp.dot(h, params["w_self"], preferred_element_type=jnp.float32)
        + jnp.dot(agg, params["w_nbr"], preferred_element_type=jnp.float32)
        + params["b"].astype(jnp.float32)
    )
    return activation(out).astype(h.dtype)


def sage_forward(
    params_stack, h, src, dst, mask, *, remat: bool = False, axis_name=None
):
    """Full model: all layers, last layer linear (no activation).

    ``remat=True`` wraps each layer in ``jax.checkpoint`` (rematerialize
    activations in backward — HBM for FLOPs on deep stacks)."""
    n = len(params_stack)
    for i, p in enumerate(params_stack):
        act = jax.nn.relu if i < n - 1 else (lambda x: x)
        layer = functools.partial(
            sage_layer, activation=act, axis_name=axis_name
        )
        if remat:
            layer = jax.checkpoint(layer)
        h = layer(p, h, src, dst, mask)
    return h


@jax.jit
def _forward_jit(params_stack, h, src, dst, mask):
    return sage_forward(params_stack, h, src, dst, mask)


@functools.lru_cache(maxsize=None)
def make_sharded_forward(mesh):
    """Jitted edge-sharded streaming forward (P1 + P3): the window's edge
    columns split over the mesh's ``"edges"`` axis, each shard scatters
    its slice's messages into a replicated [V, F] table, and the partial
    aggregates ``psum`` over ICI before the (replicated) MXU matmuls.
    This is the streaming-inference counterpart of
    :func:`make_sharded_train_step` (round-3 verdict #8: the streaming
    path was single-device)."""
    from jax.sharding import PartitionSpec as P

    from ..parallel import comm
    from ..parallel.mesh import EDGE_AXIS

    def fwd(params_stack, h, src, dst, mask):
        def shard_fn(params_stack, h, src_s, dst_s, mask_s):
            return sage_forward(
                params_stack, h, src_s, dst_s, mask_s, axis_name=EDGE_AXIS
            )

        p_spec = jax.tree.map(lambda _: P(), params_stack)
        return comm.shard_map(
            shard_fn, mesh,
            in_specs=(p_spec, P(), P(EDGE_AXIS), P(EDGE_AXIS), P(EDGE_AXIS)),
            out_specs=P(),
        )(params_stack, h, src, dst, mask)

    return jax.jit(fwd)


def make_sharded_train_step(mesh, lr=1e-2):
    """Build a jitted multi-chip SAGE training step (round-1 signature):
    DP over the edge axis, TP over the output-feature dimension.

    Returns ``(step_fn, shard_params_fn)``; ``step_fn(params, h, src, dst,
    mask, targets) -> (params, loss)``. Thin wrapper over the generic
    :func:`gelly_streaming_tpu.models.training.make_sharded_train_step`
    (which adds optax optimizers, other losses, and remat).
    """
    from .training import make_sharded_train_step as make_generic

    step, shard_params, _ = make_generic(mesh, sage_forward, lr=lr)

    def step_compat(params, h, src, dst, mask, targets):
        params, _, loss = step(params, None, h, src, dst, mask, targets)
        return params, loss

    return step_compat, shard_params


class TableFeatureSource:
    """Device-resident feature store keyed by raw vertex id.

    ``rows(raw_ids)`` gathers feature rows ON DEVICE (ids wrap modulo the
    table length — size the table to the id space for exact stores). This
    is the streaming-system form of the feature input: the per-window
    fill becomes one gather dispatch instead of a host dict loop over
    every newly-seen vertex (round-2 verdict weak #9).
    """

    def __init__(self, table):
        self.table = jnp.asarray(table)

    def rows(self, raw_ids: jax.Array) -> jax.Array:
        return self.table[raw_ids % self.table.shape[0]]


class StreamingGraphSAGE:
    """Embeddings over the accumulated streaming graph, one forward per
    window (the window stream analog of a deployed GNN encoder).

    ``run(stream, features)`` carries the accumulated edge set; per window
    it re-embeds the graph so far. ``features`` is either

    - a dict raw id -> feature vector (missing vertices get zeros);
      windows yield ``out[:n_seen]`` — reference-parity API, host fill
      for newly seen vertices only; or
    - a :class:`TableFeatureSource` (anything with ``.rows``): the whole
      carried feature table is built by device gathers, the loop performs
      NO host sync, and windows yield the full bucketed-capacity
      embedding array. Rows of never-seen compact ids are filler
      (isolated vertices with the dict's slot-filler features — raw id 0
      under ``DeviceVertexDict``); they cannot influence seen vertices
      (no edges touch them). Slice by ``len(stream.vertex_dict)`` at the
      end if exact row counts matter.
    """

    def __init__(self, params_stack, feature_dim: int, mesh=None):
        self.params = params_stack
        self.feature_dim = feature_dim
        #: optional device mesh: the per-window forward shards the edge
        #: columns over the ``"edges"`` axis (:func:`make_sharded_forward`)
        self.mesh = mesh
        self._fwd = _forward_jit if mesh is None else make_sharded_forward(mesh)
        # accumulated graph + feature matrix carried ON DEVICE at bucketed
        # capacity; per window only new edges / new vertices' feature rows
        # transfer host->device
        min_cap = 8 if mesh is None else max(
            8, dict(mesh.shape).get("edges", 1)
        )
        self._edges = EdgeAccumulator(min_capacity=min_cap)
        self._h = None
        self._n_seen = 0

    def run(self, stream, features) -> Iterator[jax.Array]:
        vdict = stream.vertex_dict
        dtype = self.params[0]["w_self"].dtype
        device_source = hasattr(features, "rows")
        for block in stream.blocks():
            s, d, _ = block.to_host()
            self._edges.append(s, d)
            vcap = block.n_vertices
            if device_source:
                self._extend_features_device(vdict, vcap, features, dtype)
                yield self._fwd(
                    self.params, self._h, self._edges.src, self._edges.dst,
                    self._edges.mask(),
                )
                continue
            n = len(vdict)
            self._extend_features(vdict, n, vcap, features, dtype)
            out = self._fwd(
                self.params, self._h, self._edges.src, self._edges.dst,
                self._edges.mask(),
            )
            yield out[:n]

    def state_dict(self) -> dict:
        """Checkpoint surface for the carried graph + features (params are
        user-owned and checkpointed separately, e.g. via save_pytree)."""
        return {
            "edges": self._edges.state_dict(),
            "h": None if self._h is None else np.asarray(self._h),
            "n_seen": self._n_seen,
        }

    def load_state_dict(self, d: dict) -> None:
        self._edges.load_state_dict(d["edges"])
        dtype = self.params[0]["w_self"].dtype
        self._h = None if d["h"] is None else jnp.asarray(d["h"], dtype)
        self._n_seen = int(d["n_seen"])

    def _extend_features_device(self, vdict, vcap: int, features, dtype) -> None:
        """Rebuild the carried feature table by device gather EVERY window:
        the dict's raw table changes as vertices arrive (not only when its
        capacity grows), so a growth-only rebuild would leave vertices
        first seen mid-bucket with slot-0 filler rows. One gather dispatch
        per window, no host sync."""
        raw = vdict.raw_table()
        self._h = features.rows(raw).astype(dtype)
        self._n_seen = int(raw.shape[0])

    def _extend_features(self, vdict, n: int, vcap: int, features, dtype) -> None:
        """Fill feature rows for vertices first seen this window only."""
        if self._h is None:
            self._h = jnp.zeros((vcap, self.feature_dim), dtype)
        elif vcap > self._h.shape[0]:
            pad = jnp.zeros((vcap - self._h.shape[0], self.feature_dim), dtype)
            self._h = jnp.concatenate([self._h, pad])
        if n > self._n_seen:
            raw = vdict.decode(np.arange(self._n_seen, n))
            rows = np.zeros((n - self._n_seen, self.feature_dim), np.float32)
            for i, rv in enumerate(raw):
                f = features.get(int(rv))
                if f is not None:
                    rows[i] = f
            self._h = jax.lax.dynamic_update_slice(
                self._h, jnp.asarray(rows, dtype), (self._n_seen, 0)
            )
            self._n_seen = n
