from .graphsage import (
    StreamingGraphSAGE,
    init_graphsage,
    make_sharded_train_step,
    mean_aggregate,
    sage_forward,
    sage_layer,
)
from .gcn import gcn_forward, gcn_layer, init_gcn
