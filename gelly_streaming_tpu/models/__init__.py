from .graphsage import (
    StreamingGraphSAGE,
    init_graphsage,
    make_sharded_train_step,
    mean_aggregate,
    sage_forward,
    sage_layer,
)
