from .gcn import gcn_forward, gcn_layer, init_gcn
from .graphsage import (
    StreamingGraphSAGE,
    init_graphsage,
    make_sharded_train_step,
    mean_aggregate,
    sage_forward,
    sage_layer,
)
from .training import (
    make_sharded_train_step as make_gnn_train_step,
    mse_loss,
    shard_gnn_params,
    softmax_xent_loss,
)
