"""Streaming GCN layers (Kipf-Welling symmetric normalization).

Second GNN family next to GraphSAGE (``models/graphsage.py``): the layer is
``act(D^-1/2 (A+I) D^-1/2 H W + b)`` computed per window over the
accumulated edge list with the same segment-sum message passing (P2) and
one MXU matmul; normalization uses the current degree vector, so
embeddings track the stream. Shares the GraphSAGE plumbing conventions:
plain-pytree params, bf16-in/f32-accumulate matmuls.
"""

from __future__ import annotations

import functools
from typing import Dict, List

import jax
import jax.numpy as jnp


def init_gcn(key, dims: List[int], dtype=jnp.bfloat16) -> List[Dict[str, jax.Array]]:
    """Glorot-initialized stack of GCN layers; ``dims = [in, h1, ..., out]``."""
    params = []
    for fi, fo in zip(dims[:-1], dims[1:]):
        key, k1 = jax.random.split(key)
        scale = jnp.sqrt(2.0 / (fi + fo)).astype(jnp.float32)
        params.append(
            {
                "w": (jax.random.normal(k1, (fi, fo)) * scale).astype(dtype),
                "b": jnp.zeros((fo,), dtype),
            }
        )
    return params


def gcn_layer(params, h, src, dst, mask, *, activation=jax.nn.relu):
    """One GCN layer over the (undirected-as-given + self-loop) edge set."""
    V = h.shape[0]
    m = mask.astype(h.dtype)
    # degrees with self-loops (the +I term)
    deg = jnp.ones(V, h.dtype).at[src].add(m).at[dst].add(m)
    norm = jax.lax.rsqrt(deg)
    # both directions so A is symmetric, plus the self-loop contribution
    msg_fwd = h[src] * (norm[src] * m)[:, None]
    msg_bwd = h[dst] * (norm[dst] * m)[:, None]
    agg = jnp.zeros_like(h).at[dst].add(msg_fwd).at[src].add(msg_bwd)
    agg = agg + h * norm[:, None]
    agg = agg * norm[:, None]
    out = (
        jnp.dot(agg, params["w"], preferred_element_type=jnp.float32)
        + params["b"].astype(jnp.float32)
    )
    return activation(out).astype(h.dtype)


def gcn_forward(params_stack, h, src, dst, mask, *, remat: bool = False):
    """Full model: all layers, last layer linear.

    ``remat=True`` wraps each layer in ``jax.checkpoint`` — activations
    rematerialize in the backward pass, trading FLOPs for HBM on deep
    stacks (the [V, F] activations dominate memory).
    """
    n = len(params_stack)
    for i, p in enumerate(params_stack):
        act = jax.nn.relu if i < n - 1 else (lambda x: x)
        layer = functools.partial(gcn_layer, activation=act)
        if remat:
            layer = jax.checkpoint(layer, static_argnums=())
        h = layer(p, h, src, dst, mask)
    return h
