"""Shared sharded training for the GNN families (GraphSAGE + GCN).

One generic step builder covers what ``models/graphsage.py`` round-1 did
for SAGE only (ROADMAP #6 / round-1 verdict weak item #7): DP over the
mesh ``"edges"`` axis for the edge messages, TP over the output-feature
dimension of every weight, expressed as ``NamedSharding`` constraints so
XLA inserts the psums/all-gathers on ICI. New here:

- works for any layer-stack forward with the ``(params_stack, h, src,
  dst, mask)`` signature (both families, plus user models of that shape);
- optional **optax** optimizer (full ``GradientTransformation`` support;
  plain SGD remains the no-dependency default);
- optional per-layer ``jax.checkpoint`` rematerialization for deep stacks
  (``remat=True`` forwarded to the model's forward).

Parameters stay bf16; optimizer math runs in f32 master copies inside the
step and re-casts, the standard mixed-precision recipe.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp


def mse_loss(out, targets):
    return jnp.mean((out.astype(jnp.float32) - targets.astype(jnp.float32)) ** 2)


def softmax_xent_loss(out, targets):
    """``targets`` are int class ids over the vertex axis."""
    logp = jax.nn.log_softmax(out.astype(jnp.float32), axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, targets[:, None], axis=-1))


def shard_gnn_params(params_stack, mesh):
    """Place a layer stack on the mesh: 2-D weights split over the output
    feature dimension (TP, ``"model"`` axis), 1-D biases likewise."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import MODEL_AXIS

    wsh = NamedSharding(mesh, P(None, MODEL_AXIS))
    bsh = NamedSharding(mesh, P(MODEL_AXIS))

    def place(leaf):
        return jax.device_put(leaf, wsh if leaf.ndim == 2 else bsh)

    return jax.tree.map(place, params_stack)


def make_sharded_train_step(
    mesh,
    forward_fn: Callable,
    *,
    lr: float = 1e-2,
    optimizer: Optional[Any] = None,
    loss_fn: Callable = mse_loss,
    remat: bool = False,
) -> Tuple[Callable, Callable, Callable]:
    """Build a jitted multi-chip training step for a GNN layer stack.

    Returns ``(step_fn, shard_params_fn, init_opt_fn)``:

    - ``step_fn(params, opt_state, h, src, dst, mask, targets) ->
      (params, opt_state, loss)``;
    - ``shard_params_fn(params) -> params`` placed on the mesh;
    - ``init_opt_fn(params) -> opt_state`` (``None``-state for plain SGD).

    ``optimizer`` is any optax ``GradientTransformation``; when omitted,
    plain SGD with ``lr`` runs without the optax dependency.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..parallel.mesh import EDGE_AXIS

    esh = NamedSharding(mesh, P(EDGE_AXIS))
    rep = NamedSharding(mesh, P())

    def shard_params(params_stack):
        return shard_gnn_params(params_stack, mesh)

    def init_opt(params_stack):
        if optimizer is None:
            return None
        f32 = jax.tree.map(lambda p: p.astype(jnp.float32), params_stack)
        return optimizer.init(f32)

    def full_loss(params, h, src, dst, mask, targets):
        out = forward_fn(params, h, src, dst, mask, remat=remat)
        return loss_fn(out, targets)

    @jax.jit
    def step(params, opt_state, h, src, dst, mask, targets):
        h = jax.lax.with_sharding_constraint(h, rep)
        src = jax.lax.with_sharding_constraint(src, esh)
        dst = jax.lax.with_sharding_constraint(dst, esh)
        mask = jax.lax.with_sharding_constraint(mask, esh)
        loss, grads = jax.value_and_grad(full_loss)(
            params, h, src, dst, mask, targets
        )
        if optimizer is None:
            params = jax.tree.map(
                lambda p, g: (
                    p.astype(jnp.float32) - lr * g.astype(jnp.float32)
                ).astype(p.dtype),
                params,
                grads,
            )
            return params, opt_state, loss
        f32 = jax.tree.map(lambda p: p.astype(jnp.float32), params)
        g32 = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        updates, opt_state = optimizer.update(g32, opt_state, f32)
        f32 = jax.tree.map(lambda p, u: p + u, f32, updates)
        params = jax.tree.map(lambda p, q: p.astype(q.dtype), f32, params)
        return params, opt_state, loss

    return step, shard_params, init_opt
