"""Self-tuning control plane (ISSUE 15).

One adaptive controller layer that consumes live registry streams (and
obs-independent direct taps) and retunes the knobs that were hand-picked
until now: superbatch K, prefetch depth, serving admission/shed
watermarks — with hysteresis, bounded step sizes, and every decision
logged as a ``control.retune`` registry event the timeline renders.

- :mod:`signals` — :class:`SignalReader`, THE retune-signal
  implementation (windowed registry deltas + direct stopwatch taps).
- :mod:`controller` — :class:`AutoK`, :class:`PrefetchTuner`,
  :class:`AdmissionTuner`, bundled by :class:`ControlPlane`.
"""

from .controller import (
    AdmissionTuner,
    AutoK,
    ControlPlane,
    PrefetchTuner,
    default_plane,
    log_retune,
)
from .signals import SignalReader

__all__ = [
    "AdmissionTuner",
    "AutoK",
    "ControlPlane",
    "PrefetchTuner",
    "SignalReader",
    "default_plane",
    "log_retune",
]
