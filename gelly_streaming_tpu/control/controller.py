"""The self-tuning control plane: close the loops the tracing opened.

PR 3 made every stage measurable (per-stage spans, queue-wait,
producer-blocked/consumer-idle counters) and PR 5 closed ONE loop with
them (checkpoint cadence, ``every="auto"``). This module closes the
rest: three knob tuners sharing one decision discipline —

- :class:`AutoK` — superbatch sizing
  (``SummaryAggregation(superbatch="auto")``): a guarded hill-climb on
  measured group throughput over a power-of-``step`` K ladder, with the
  ``window.pack`` / ``engine.superbatch_dispatch`` span ratio and
  prefetch idle seconds (read through the shared
  :class:`~gelly_streaming_tpu.control.signals.SignalReader`) as the
  climb hint, and a window-size-shift detector that re-enters the climb
  when the stream's shape changes mid-run.
- :class:`PrefetchTuner` — prefetch queue depth
  (:func:`~gelly_streaming_tpu.core.pipeline.prefetch`) from the
  producer-blocked / consumer-idle shares of each decision window.
- :class:`AdmissionTuner` — serving admission + shed watermarks
  (``StreamServer(autotune=True)`` / ``ShardRouter(autotune=True)``)
  from measured queue wait vs the deadline budgets queries actually
  carry; queue wait is the LEADING signal (it grows before answer
  latency breaches the budget, so shedding starts while the protected
  classes still have headroom).

The shared discipline, pinned by ``tests/test_control.py``:

- **Bounded step**: every retune moves the knob one rung
  (``x step`` / ``/ step`` for ladder knobs, one multiplicative notch
  for the admission fraction). A decision can never jump the knob
  across the range, however loud the signal.
- **Hysteresis**: moves need the signal past a threshold by a margin
  (``hi``/``lo`` bands), a refused probe is remembered with the
  throughput band it failed against and is not retried until the
  landscape changes materially (``reprobe_band``), and every revert
  starts a cooldown. Adjacent-rung oscillation under noisy signals is
  a bug by contract.
- **Decisions are events**: every knob move logs a
  ``control.retune{knob,from,to,signal}`` registry event — gated on
  ``obs.enable()`` (GL005: the control plane must cost ~0 in disabled
  runs) — which ``obs.timeline`` renders as RETUNE story lines next to
  COMMIT/PROMOTE. The tuners also keep a bounded in-object ``history``
  so tests and bench artifacts read decisions without obs.

The controller must never LOSE to the hand-tuned constants: the
``bench.py --autotune`` harness (``BENCH_AUTOTUNE_CPU.json``,
benchguard-watched) proves ``superbatch="auto"`` holds >= 0.9x the
hand-picked-K throughput on the committed latency-curve cliff cell and
re-tunes K across a mid-stream window-size shift with zero oracle
mismatches.
"""

from __future__ import annotations

from typing import Optional

from ..obs import trace as _trace
from ..obs.registry import get_registry
from .signals import SignalReader

#: bounded length of every tuner's in-object decision history
HISTORY_MAX = 256


def log_retune(knob: str, old, new, signal: str) -> None:
    """One knob move as a registry event (the timeline's RETUNE line).
    Gated: with obs disabled a retune still HAPPENS (the tuners run on
    direct taps), it just is not logged."""
    if _trace.on():
        get_registry().counter(
            "control.retune", knob=knob,
            **{"from": str(old), "to": str(new), "signal": signal},
        ).inc()


class _HistoryMixin:
    def _record(self, knob: str, old, new, signal: str) -> None:
        h = self.history
        h.append((old, new, signal))
        if len(h) > HISTORY_MAX:
            del h[: len(h) - HISTORY_MAX]
        log_retune(knob, old, new, signal)


class AutoK(_HistoryMixin):
    """Superbatch-K tuner: guarded hill-climb over a power-of-``step``
    ladder, driven by per-group throughput taps from the drive loop.

    The drive loop calls :meth:`tap_group` once per folded group (host
    wall seconds per group — cadence-rate, works with obs off). Every
    ``decide_groups`` groups at the CURRENT K the tuner decides:

    - climbing up, a probe must IMPROVE throughput by ``improve``
      (default 8%) or it reverts — per-dispatch fixed cost already
      amortized means bigger groups only add memory and latency grain;
    - climbing down, a probe may KEEP throughput within ``keep``
      (default 5% loss) — the tuner prefers the smallest K inside the
      throughput band, so converged K carries the least emission
      latency and checkpoint granularity the plateau allows;
    - a refused rung is remembered against the throughput it lost to
      and not retried until throughput at the held K moves by more than
      ``reprobe_band`` (no oscillation between adjacent rungs under
      noisy measurements);
    - a window-size shift of ``shift_factor`` or more (mean edges per
      window vs the anchor the current ladder was learned at) clears
      that memory and re-enters the climb toward the new optimum —
      DOWN when windows grew (less fusion needed per dispatch), UP when
      they shrank.

    With obs enabled, the ``engine.superbatch_dispatch`` vs
    ``window.superbatch_pack``/``window.pack`` span ratio breaks a hold:
    dispatch seconds per window far above pack seconds per window means
    per-dispatch fixed cost still dominates, so the tuner re-probes up
    even though held throughput has not moved. ``pipeline.consumer_idle_s``
    rides into the decision log as evidence. Obs off, the hill-climb
    alone converges (the bench proves it); the spans only speed it up.
    """

    def __init__(
        self,
        *,
        k0: int = 1,
        k_max: int = 256,
        step: int = 4,
        decide_groups: int = 1,
        improve: float = 1.08,
        keep: float = 0.95,
        reprobe_band: float = 0.25,
        shift_factor: float = 2.0,
        cooldown: int = 2,
        dispatch_ratio_hi: float = 4.0,
        signals: Optional[SignalReader] = None,
        knob: str = "superbatch_k",
    ):
        if step < 2:
            raise ValueError(f"step must be >= 2, got {step}")
        self.k = max(1, int(k0))
        self.k_max = max(1, int(k_max))
        self.step = int(step)
        self.decide_groups = max(1, int(decide_groups))
        self.improve = float(improve)
        self.keep = float(keep)
        self.reprobe_band = float(reprobe_band)
        self.shift_factor = float(shift_factor)
        self.cooldown = max(0, int(cooldown))
        self.dispatch_ratio_hi = float(dispatch_ratio_hi)
        self.signals = signals if signals is not None else SignalReader()
        self.knob = knob
        #: (old_k, new_k, signal) per decision that moved the knob
        self.history: list = []
        # decision state
        self._stats: dict = {}     # k -> [groups, edges, seconds, windows]
        self._base: Optional[tuple] = None   # (k, eps) accepted point
        self._probing: Optional[str] = None  # "up" | "down" | None
        self._hold_eps: Optional[float] = None
        self._cool = 0
        self._failed: dict = {}    # refused k -> base eps it lost to
        self._w_anchor: Optional[float] = None

    # -- drive-loop surface -------------------------------------------- #
    def current_k(self) -> int:
        """The K the packer should tile the NEXT group at (the drive
        loop's ``k_fn``; read from the prefetch producer thread — a
        plain int read, no lock needed)."""
        return self.k

    def tap_group(self, n_windows: int, n_edges: int, wall_s: float) -> int:
        """One folded group's measurement. Attribution is by the
        group's OWN window count: groups packed at the previous K are
        still in flight for a prefetch depth after a retune, and a
        final partial group never reaches ``decide_groups`` at its odd
        size — both stay honest without special cases. Seconds credited
        as FOREIGN by the consumer (a checkpoint barrier landing inside
        this group's yields —
        :func:`~gelly_streaming_tpu.control.signals.add_excluded_s`)
        are subtracted so a rare out-of-band stall cannot masquerade as
        a throughput collapse at the current K. Returns the K for
        upcoming groups."""
        from .signals import take_excluded_s

        wall_s -= take_excluded_s()
        if n_windows <= 0 or wall_s <= 0:
            return self.k
        st = self._stats.get(n_windows)
        if st is None:
            st = self._stats[n_windows] = [0, 0.0, 0.0, 0]
        st[0] += 1
        st[1] += float(n_edges)
        st[2] += float(wall_s)
        st[3] += int(n_windows)
        cur = self._stats.get(self.k)
        if cur is not None and cur[0] >= self.decide_groups:
            eps = cur[1] / cur[2]
            w_mean = cur[1] / max(1, cur[3])
            del self._stats[self.k]
            self._decide(eps, w_mean)
        return self.k

    # -- decision core -------------------------------------------------- #
    def _rung(self, direction: str) -> int:
        nxt = self.k * self.step if direction == "up" else \
            self.k // self.step
        return max(1, min(self.k_max, nxt))

    def _move(self, new_k: int, signal: str) -> None:
        if new_k != self.k:
            self._record(self.knob, self.k, new_k, signal)
            self.k = new_k
            # drop any stale accumulation at the new rung: a leftover
            # bucket from an earlier visit (or from same-count groups of
            # a different window size) must not decide the fresh probe
            self._stats.pop(new_k, None)

    def _enter_hold(self, eps: float) -> None:
        self._probing = None
        self._hold_eps = eps

    def _probe(self, direction: str, signal: str) -> bool:
        """Move one rung if it exists and is not band-refused."""
        nxt = self._rung(direction)
        if nxt == self.k:
            return False
        base_eps = self._base[1] if self._base else None
        refused = self._failed.get(nxt)
        if refused is not None and base_eps is not None and \
                abs(base_eps - refused) <= self.reprobe_band * refused:
            return False  # the landscape it failed against still holds
        self._failed.pop(nxt, None)
        self._probing = direction
        self._move(nxt, signal)
        return True

    def _decide(self, eps: float, w_mean: float) -> None:
        # window-size shift: the ladder was learned at another window
        # shape — forget refusals and re-climb toward the new optimum
        if self._w_anchor is None:
            self._w_anchor = w_mean
        elif w_mean >= self.shift_factor * self._w_anchor or \
                w_mean * self.shift_factor <= self._w_anchor:
            grew = w_mean > self._w_anchor
            self._w_anchor = w_mean
            self._failed.clear()
            # in-flight groups packed at the OLD window size share a
            # window count with post-shift groups; their mixed
            # edges/seconds must not feed post-shift decisions
            self._stats.clear()
            self._cool = 0
            self._base = (self.k, eps)
            if self._probe("down" if grew else "up", "window-shift"):
                return
            self._enter_hold(eps)
            return
        if self._probing is not None and self._base is not None:
            base_k, base_eps = self._base
            ok = (
                eps >= self.improve * base_eps
                if self._probing == "up"
                else eps >= self.keep * base_eps
            )
            if ok:
                direction = self._probing
                self._base = (self.k, eps)
                if not self._probe(direction, "eps-" + (
                        "improved" if direction == "up" else "held")):
                    self._enter_hold(eps)
            else:
                self._failed[self.k] = base_eps
                self._move(base_k, "probe-reverted")
                self._enter_hold(base_eps)
                self._cool = self.cooldown
            return
        if self._base is None:
            # first decision: adopt the measured point, start climbing
            self._base = (self.k, eps)
            if not self._probe("up", "initial-climb"):
                self._enter_hold(eps)
            return
        # holding
        if self._cool > 0:
            self._cool -= 1
            self._hold_eps = eps if self._hold_eps is None else \
                0.8 * self._hold_eps + 0.2 * eps
            return
        held = self._hold_eps if self._hold_eps is not None else eps
        if held > 0 and abs(eps - held) > self.reprobe_band * held:
            # the landscape moved materially: re-learn from here
            self._failed.clear()
            self._base = (self.k, eps)
            direction = "up" if self.k < self.k_max else "down"
            if self._probe(direction, "eps-shift"):
                return
            self._enter_hold(eps)
            return
        if self._span_hint() and self._base is not None:
            self._base = (self.k, eps)
            if self._probe("up", "dispatch-share"):
                return
        self._hold_eps = 0.8 * held + 0.2 * eps

    def _span_hint(self) -> bool:
        """Obs-on climb hint: dispatch seconds per window dwarfing pack
        seconds per window means per-dispatch fixed cost still
        dominates at the held K."""
        dn, ds = self.signals.span_delta("engine.superbatch_dispatch")
        dn2, ds2 = self.signals.span_delta("engine.dispatch")
        pn, ps = self.signals.span_delta("window.superbatch_pack")
        pn2, ps2 = self.signals.span_delta("window.pack")
        # consumed so the next window starts fresh even when unused
        self.signals.counter_delta("pipeline.consumer_idle_s")
        disp_windows = dn * self.k + dn2
        pack_windows = pn * self.k + pn2
        if disp_windows <= 0 or pack_windows <= 0:
            return False
        disp_pw = (ds + ds2) / disp_windows
        pack_pw = (ps + ps2) / pack_windows
        return pack_pw > 0 and disp_pw > self.dispatch_ratio_hi * pack_pw


class PrefetchTuner(_HistoryMixin):
    """Prefetch-depth tuner for
    :func:`~gelly_streaming_tpu.core.pipeline.prefetch`.

    The prefetch loop taps it per item (one clock subtraction each on
    the put and get paths — opting into tuning opts into that cost);
    every ``decide_items`` items it compares the decision window's
    producer-blocked and consumer-idle SHARES of wall time:

    - consumer idle above ``hi``: the producer is the bottleneck and
      bursty — deepen the queue one rung (x2) so lookahead absorbs the
      bursts, up to ``depth_max``;
    - producer blocked above ``hi`` with the consumer never idle: the
      consumer is the bottleneck and the queue is pure ballast — shrink
      one rung toward ``depth_min`` (same throughput, less memory
      pinned in queued blocks);
    - anything between the bands holds (hysteresis), and every move
      starts a ``cooldown`` so one noisy window cannot thrash the depth.
    """

    def __init__(
        self,
        *,
        depth: int = 2,
        depth_min: int = 1,
        depth_max: int = 16,
        decide_items: int = 32,
        hi: float = 0.25,
        lo: float = 0.05,
        cooldown: int = 2,
        knob: str = "prefetch_depth",
    ):
        self.depth = max(1, int(depth))
        self.depth_min = max(1, int(depth_min))
        self.depth_max = max(self.depth_min, int(depth_max))
        self.depth = min(max(self.depth, self.depth_min), self.depth_max)
        self.decide_items = max(1, int(decide_items))
        self.hi = float(hi)
        self.lo = float(lo)
        self.cooldown = max(0, int(cooldown))
        self.knob = knob
        self.history: list = []
        import threading
        import time as _time

        self._lock = threading.Lock()
        self._clock = _time.perf_counter
        self._blocked = 0.0
        self._idle = 0.0
        self._items = 0
        self._t0: Optional[float] = None
        self._cool = 0

    def tap_put(self, blocked_s: float) -> None:
        """Producer-side: seconds this put spent over the soft depth cap
        (0.0 for an immediate put)."""
        if blocked_s > 0:
            with self._lock:
                self._blocked += blocked_s

    def tap_get(self, idle_s: float) -> None:
        """Consumer-side: seconds this pull waited on an empty queue."""
        with self._lock:
            if idle_s > 0:
                self._idle += idle_s
            self._items += 1
            now = self._clock()
            if self._t0 is None:
                self._t0 = now
                return
            if self._items < self.decide_items:
                return
            wall = max(1e-9, now - self._t0)
            blocked_share = self._blocked / wall
            idle_share = self._idle / wall
            self._blocked = 0.0
            self._idle = 0.0
            self._items = 0
            self._t0 = now
        self._decide(blocked_share, idle_share)

    def _decide(self, blocked_share: float, idle_share: float) -> None:
        if self._cool > 0:
            self._cool -= 1
            return
        old = self.depth
        if idle_share > self.hi and self.depth < self.depth_max:
            self.depth = min(self.depth_max, self.depth * 2)
            self._record(self.knob, old, self.depth, "consumer-idle")
            self._cool = self.cooldown
        elif blocked_share > self.hi and idle_share < self.lo \
                and self.depth > self.depth_min:
            self.depth = max(self.depth_min, self.depth // 2)
            self._record(self.knob, old, self.depth, "producer-blocked")
            self._cool = self.cooldown


class AdmissionTuner(_HistoryMixin):
    """Admission/shed tuner for the serving tier.

    The serving worker taps it once per answered sweep with the sweep's
    oldest queue wait (the leading signal: waits grow before answer
    latency breaches anyone's deadline) and the tightest deadline
    budget the sweep's queries carried. Every ``decide_sweeps`` sweeps:

    - worst wait above ``hi`` of the budget: shed earlier — shrink
      ``max_pending`` one multiplicative notch (``step``) and pull the
      shed watermark down with it, never below ``floor_frac`` of the
      configured ceiling;
    - worst wait below ``lo`` of the budget with headroom shed away:
      recover one notch toward the CONFIGURED ceiling (the operator's
      limit is the contract; the tuner only moves inside it);
    - between the bands: hold. Every move starts a ``cooldown``.

    With no deadlines in the traffic and no ``target_wait_s``
    configured there is no budget to compare against, so the tuner
    holds — admission then behaves exactly as the hand-set constants.
    """

    def __init__(
        self,
        *,
        max_pending: int,
        shed_watermark: float = 0.8,
        target_wait_s: Optional[float] = None,
        hi: float = 0.5,
        lo: float = 0.2,
        step: float = 0.7,
        floor_frac: float = 0.1,
        decide_sweeps: int = 8,
        cooldown: int = 2,
        knob: str = "max_pending",
    ):
        self.ceiling = max(1, int(max_pending))
        self.max_pending = self.ceiling
        self.shed_watermark_ceiling = float(shed_watermark)
        self.shed_watermark = float(shed_watermark)
        self.target_wait_s = target_wait_s
        self.hi = float(hi)
        self.lo = float(lo)
        self.step = float(step)
        self.floor = max(1, int(floor_frac * self.ceiling))
        self.decide_sweeps = max(1, int(decide_sweeps))
        self.cooldown = max(0, int(cooldown))
        self.knob = knob
        self.history: list = []
        self._sweeps = 0
        self._worst_wait = 0.0
        self._min_budget: Optional[float] = None
        self._cool = 0

    def shed_level(self) -> int:
        """The absolute shed watermark the server applies (recomputed
        from the tuned fraction and tuned admission limit)."""
        return max(1, int(self.shed_watermark * self.max_pending))

    def tap_entries(self, queue_wait_s: float, entries) -> bool:
        """One sweep's evidence from raw ``(t0, deadline_abs|None)``
        pairs: computes the tightest budget and defers to
        :meth:`tap_sweep` — THE one leading-signal computation both
        serving tiers call (StreamServer's worker sweep and the
        router's drain sweep), so budget selection can never drift
        between them. Returns True when the knobs moved."""
        budget = None
        for t0, dl in entries:
            if dl is not None:
                b = dl - t0
                if budget is None or b < budget:
                    budget = b
        return self.tap_sweep(queue_wait_s, budget)

    def tap_sweep(self, queue_wait_s: float,
                  min_budget_s: Optional[float]) -> bool:
        """One answered sweep's evidence; returns True when the knobs
        moved (the caller re-applies them to its admission fields)."""
        self._sweeps += 1
        if queue_wait_s > self._worst_wait:
            self._worst_wait = queue_wait_s
        if min_budget_s is not None and (
                self._min_budget is None or min_budget_s < self._min_budget):
            self._min_budget = min_budget_s
        if self._sweeps < self.decide_sweeps:
            return False
        worst = self._worst_wait
        budget = self._min_budget if self._min_budget is not None \
            else self.target_wait_s
        self._sweeps = 0
        self._worst_wait = 0.0
        self._min_budget = None
        if budget is None or budget <= 0:
            return False
        if self._cool > 0:
            self._cool -= 1
            return False
        frac = worst / budget
        old = self.max_pending
        if frac > self.hi and self.max_pending > self.floor:
            self.max_pending = max(
                self.floor, int(self.max_pending * self.step)
            )
            self.shed_watermark = max(
                0.25, self.shed_watermark * self.step
            )
            self._record(self.knob, old, self.max_pending, "queue-wait")
            self._cool = self.cooldown
            return True
        if frac < self.lo and self.max_pending < self.ceiling:
            self.max_pending = min(
                self.ceiling, max(self.max_pending + 1,
                                  int(self.max_pending / self.step))
            )
            self.shed_watermark = min(
                self.shed_watermark_ceiling,
                self.shed_watermark / self.step,
            )
            self._record(self.knob, old, self.max_pending, "wait-recovered")
            self._cool = self.cooldown
            return True
        return False


class ControlPlane:
    """One run's bundle of tuners sharing a
    :class:`~gelly_streaming_tpu.control.signals.SignalReader` — what
    the drive loop / server carries around instead of three loose
    objects. Any slot may be None (the loop only exercises the knobs it
    owns)."""

    def __init__(self, *, autok: Optional[AutoK] = None,
                 prefetch: Optional[PrefetchTuner] = None,
                 admission: Optional[AdmissionTuner] = None,
                 signals: Optional[SignalReader] = None):
        self.signals = signals if signals is not None else SignalReader()
        self.autok = autok
        self.prefetch = prefetch
        self.admission = admission


def default_plane(k0: int = 1) -> ControlPlane:
    """The stock ``superbatch="auto"`` plane every group-folded run
    builds unless one was injected: AutoK from ``k0`` + an adaptive
    group-prefetch tuner over ONE shared SignalReader. Lives here so
    the engine/CC/bipartiteness run loops cannot drift apart on the
    default-plane shape."""
    signals = SignalReader()
    return ControlPlane(
        autok=AutoK(k0=max(1, int(k0)), signals=signals),
        prefetch=PrefetchTuner(),
        signals=signals,
    )
