"""SignalReader: THE retune-signal implementation.

Every loop this repo closes — checkpoint cadence
(``AutoCheckpoint(every="auto")``), superbatch sizing
(``SummaryAggregation(superbatch="auto")``), prefetch depth, serving
admission — needs the same two kinds of evidence:

1. **Direct taps**: stopwatch samples the tuned code path measures
   itself (one ``perf_counter`` subtraction per barrier/group/sweep —
   cadence-rate, never per-edge). These work with observability
   DISABLED: a controller must keep tuning in production runs that pay
   ~0 for obs, so its primary signals cannot live behind the obs gate.
2. **Windowed registry deltas**: the spans and counters PR 3 already
   mirrors into the :class:`~gelly_streaming_tpu.obs.registry.MetricRegistry`
   (``trace.span_seconds{span=window.pack}``,
   ``pipeline.consumer_idle_s``, ...). Registry instruments are
   lifetime-cumulative; a retune decision needs "since my last
   decision", so the reader keeps per-name marks and hands back deltas.
   These reads gate on ``obs.enable()`` — with obs off the registry
   holds nothing and the reader returns zeros without touching it
   (zero-allocation on the disabled path: no scan, no dict build).

Before this module each closed loop carried a private copy of (1)
(``AutoCheckpoint`` measured barrier cost with inline ``perf_counter``
fields) and nothing consumed (2) at all; the ISSUE 15 satellite pins
them onto this one implementation so a new knob never re-invents the
measurement.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

from ..obs import trace as _trace
from ..obs.registry import get_registry

#: shared empty delta — the disabled-obs path hands this back instead
#: of allocating a fresh tuple per read
_ZERO: Tuple[float, float] = (0.0, 0.0)

#: per-thread seconds a throughput measurement should NOT charge to the
#: measured pipeline (see add_excluded_s)
_EXCLUDED = threading.local()


def add_excluded_s(dt: float) -> None:
    """Credit ``dt`` seconds of FOREIGN work to this thread's running
    exclusion budget. A throughput tap that wraps consumer-side
    processing (the drive loop's per-group wall) would otherwise charge
    rare out-of-band stalls — a checkpoint barrier's state capture +
    serialize is the shipped case — to the group they happened to land
    in, and one polluted sample can revert a good probe or fire a
    spurious re-climb. The code that KNOWS the stall is foreign
    (``AutoCheckpoint._snapshot``) credits it here; the tap subtracts
    it via :func:`take_excluded_s`. Thread-local because the barrier
    and the drive loop run on the same consumer thread."""
    _EXCLUDED.s = getattr(_EXCLUDED, "s", 0.0) + float(dt)


def take_excluded_s() -> float:
    """Drain this thread's exclusion budget (0.0 when none accrued)."""
    s = getattr(_EXCLUDED, "s", 0.0)
    if s:
        _EXCLUDED.s = 0.0
    return s


class SignalReader:
    """Windowed retune signals: direct taps + registry deltas.

    Direct taps (:meth:`observe`) are always live; registry reads
    (:meth:`counter_delta` / :meth:`span_delta`) return zeros with obs
    disabled. A reader is NOT thread-safe by design: each closed loop
    owns one and reads it from its own decision point (the barrier
    loop, the group drive loop, the serving sweep).
    """

    def __init__(self, registry=None):
        # None = resolve the process registry at read time (tests swap
        # it via set_registry; a cached handle would pin the old one)
        self._registry = registry
        #: name -> [count, total, last] direct samples (lifetime)
        self._direct: Dict[str, list] = {}
        #: registry key -> (count, sum) at the previous delta read
        self._marks: Dict[str, Tuple[float, float]] = {}

    # -- direct taps (obs-independent) --------------------------------- #
    def observe(self, name: str, value: float) -> None:
        """Record one direct sample (seconds, edges, ...); costs a dict
        probe and two adds — cheap enough for cadence-rate call sites,
        deliberately not for per-edge ones."""
        cell = self._direct.get(name)
        if cell is None:
            cell = self._direct[name] = [0, 0.0, 0.0]
        cell[0] += 1
        cell[1] += value
        cell[2] = value

    def last(self, name: str) -> Optional[float]:
        """The most recent direct sample (None before the first)."""
        cell = self._direct.get(name)
        return None if cell is None else cell[2]

    def total(self, name: str) -> Tuple[int, float]:
        """Lifetime ``(count, sum)`` of a direct tap."""
        cell = self._direct.get(name)
        return (0, 0.0) if cell is None else (cell[0], cell[1])

    # -- registry deltas (gated on obs) -------------------------------- #
    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    def counter_delta(self, name: str) -> float:
        """Sum of ``name`` counters (all label sets) accrued since the
        previous call for this name; 0.0 with obs disabled."""
        if not _trace.on():
            return 0.0
        total = 0.0
        for _labels, inst in self._reg().find(name):
            total += inst.value
        prev = self._marks.get(name, _ZERO)[1]
        self._marks[name] = (0.0, total)
        return total - prev

    def span_delta(self, span_name: str) -> Tuple[float, float]:
        """``(count, seconds)`` accrued in the
        ``trace.span_seconds{span=span_name}`` histogram since the
        previous call; ``(0, 0)`` with obs disabled (span mirroring
        itself requires ``obs.enable()``, so there is nothing to read)."""
        if not _trace.on():
            return _ZERO
        key = "span:" + span_name
        count = 0.0
        total = 0.0
        for labels, inst in self._reg().find("trace.span_seconds"):
            if labels.get("span") == span_name:
                count += inst.count
                total += inst.sum
        prev = self._marks.get(key, _ZERO)
        self._marks[key] = (count, total)
        return count - prev[0], total - prev[1]
