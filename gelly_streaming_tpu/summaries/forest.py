"""Window-local CC fold over a lazily-canonicalized forest carry.

The dense-label engine (``summaries/labels.py``) pays O(vcap) work per
window — init_labels + full-table fixpoint + combine — even when the
window touches <=2W vertices. That is the wrong cost shape vs the
reference, whose per-partition fold touches only the window's edges
(``SummaryBulkAggregation.java:76-80``); the honest CPU bracket measured
it directly (BENCH_CPU r4: 0.45x the compiled baseline at 1M-edge
windows, V-bound at scale 23).

This module is the round-5 redesign: the carried summary becomes a
**pointer forest** ``canon[vcap]`` (int32, ``canon[v] <= v``, acyclic by
the strictly-decreasing min-root invariant) that is only *canonicalized*
— chains collapsed to flat labels — at emission or checkpoint time.
Per window, every kernel is sized by the window, not the vertex space:

1. The HOST computes the window's touched set beside the stream (unique
   endpoints of the cached pre-padding columns, order unspecified — the
   novelty-shadow pattern: zero device->host reads in the producer loop)
   and renumbers the window's edges into local indices ``[0, T)``.
2. The DEVICE chases the touched vertices' pointers to their current
   roots (``lax.while_loop`` of O(T) gathers; chains only pass through
   former roots, and touched vertices are fully path-compressed every
   window).
3. A min-label fixpoint over the **local** T-sized table joins the
   window's edges with "same current root" chain constraints (from one
   T-sort), exactly the dense kernel's hook+shortcut but on a table the
   size of the window.
4. One masked scatter re-roots the old roots (and the touched vertices,
   for path compression) to the merged component's min root.

The remaining vcap-sized costs are bandwidth-only: the functional
scatter's buffer copy (which is also what keeps per-window emissions
valid snapshots — the pre-scatter buffer stays alive for any lazy
emission holding it) and the step-2 scratch memset — two linear HBM
passes per window instead of the dense path's ~10-20 full-table
gather/scatter fixpoint passes.

Reference parity: this is the ``UpdateCC``/``CombineCC`` pair of
``library/ConnectedComponents.java:83-126`` with the DisjointSet's
pointer forest kept on device and its find-with-path-compression
vectorized over the window's touched set.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.edgeblock import bucket_capacity
from .labels import _propagate

_I32_MAX = jnp.iinfo(jnp.int32).max

#: jitted per-(Tcap, Wcap, vcap, mesh, tree, degree) window steps;
#: bounded FIFO like the engine's step cache (each signature costs
#: seconds on a remote TPU).
_FOREST_STEP_CACHE: dict = {}
_FOREST_STEP_CACHE_MAX = 32


def _table_combine(tcap: int):
    """Merge two local label tables over the same touched set: the
    union's constraints are exactly the pointer edges of both tables
    (``labels.label_combine`` on plain arrays)."""
    iota = jnp.arange(tcap, dtype=jnp.int32)

    def combine(a, b):
        u = jnp.concatenate([iota, iota])
        w = jnp.concatenate([a, b])
        return _propagate(
            jnp.minimum(a, b), u, w, jnp.ones(2 * tcap, bool)
        )

    return combine


def chase_and_group(canon, tid, tmask, tcap: int, vcap: int):
    """Shared forest-step front half (CC + signed-cover carries).

    1. Chase touched pointers to their current roots. Read-only on
       canon, so chains are static during the chase; roots satisfy
       canon[r] == r and chains strictly decrease (min-root invariant)
       so the loop terminates. Padding lanes chase from 0, which is
       always self-rooted (canon[0] <= 0).
    2. "Same current root" constraints WITHOUT a sort (argsort over the
       touched bucket measured 375 ms on the CPU backend): scatter each
       lane's local index into a vcap scratch keyed by root, so every
       lane learns its group's representative lane — one bandwidth-bound
       memset+scatter+gather instead of a comparison sort. Edge
       (i, rep_i) unifies the group; pads self-loop.

    Returns ``(r, v2, key_, iota)``: current roots per lane, the group-
    edge targets, the root-value keys (+inf on pads), and the lane iota.
    """
    r0 = jnp.where(tmask, canon[tid], 0)
    r = lax.while_loop(
        lambda r: jnp.any(canon[r] != r), lambda r: canon[r], r0
    )
    iota = jnp.arange(tcap, dtype=jnp.int32)
    sid_r = jnp.where(tmask, r, vcap)
    scratch = jnp.full(vcap, _I32_MAX, jnp.int32).at[sid_r].min(
        jnp.where(tmask, iota, _I32_MAX), mode="drop"
    )
    rep = scratch[jnp.where(tmask, r, 0)]
    v2 = jnp.where(tmask, rep, iota)
    key_ = jnp.where(tmask, r, _I32_MAX)
    return r, v2, key_, iota


def commit_roots(canon, local, key_, r, tid, tmask, tcap: int, vcap: int):
    """Shared forest-step back half: the merged component's new root is
    the min of its members' old roots (each old root is the min id of
    its old component, so the min over merged roots is the min id of the
    merged component); re-root the old roots and path-compress the
    touched lanes (pads dropped). Returns ``(canon, nr)`` — ``nr`` is
    each lane's final root value (the cover carry's conflict latch reads
    it)."""
    minr = jnp.full(tcap, _I32_MAX, jnp.int32).at[local].min(key_)
    nr = minr[local]
    sid_r = jnp.where(tmask, r, vcap)
    canon = canon.at[sid_r].set(nr, mode="drop")
    tid_s = jnp.where(tmask, tid, vcap)
    canon = canon.at[tid_s].set(nr, mode="drop")
    return canon, nr


def _forest_step_fn(tcap: int, wcap: int, vcap: int, mesh=None,
                    tree: bool = False, degree: int = 2):
    key = (tcap, wcap, vcap, mesh, tree, degree)
    fn = _FOREST_STEP_CACHE.get(key)
    if fn is not None:
        return fn

    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel import comm
        from ..parallel.mesh import EDGE_AXIS

        p = mesh.shape[EDGE_AXIS]
        combine = _table_combine(tcap)

    def step(canon, tid, tmask, lu, lv):
        r, v2, key_, iota = chase_and_group(canon, tid, tmask, tcap, vcap)
        # local min-label fixpoint on the T-sized table (window edges +
        # group edges; lu/lv pads are (0,0) self-loops, no mask needed).
        # Under a mesh this is the engine's per-shard-fold + cross-shard-
        # combine shape on WINDOW-SIZED tables: each shard folds its
        # slice of the edge columns (the T-sized group edges replicate —
        # same constraints everywhere), then the T-sized label tables
        # merge through the bulk stack or the ppermute butterfly. The
        # vcap-sized carry never crosses the mesh.
        if mesh is None:
            u = jnp.concatenate([lu, iota])
            w = jnp.concatenate([lv, v2])
            local = _propagate(iota, u, w, jnp.ones(u.shape[0], bool))
        else:
            def shard_fn(lu_s, lv_s):
                u = jnp.concatenate([lu_s, iota])
                w = jnp.concatenate([lv_s, v2])
                lab = _propagate(iota, u, w, jnp.ones(u.shape[0], bool))
                if tree:
                    return comm.tree_all_reduce(
                        lab, EDGE_AXIS, combine, p, degree=degree
                    )
                return lab[None]

            out = comm.shard_map(
                shard_fn, mesh, (P(EDGE_AXIS), P(EDGE_AXIS)),
                P() if tree else P(EDGE_AXIS),
            )(lu, lv)
            local = out if tree else comm.stacked_reduce(out, p, combine)
        canon, _nr = commit_roots(canon, local, key_, r, tid, tmask, tcap, vcap)
        return canon

    fn = jax.jit(step)
    if len(_FOREST_STEP_CACHE) >= _FOREST_STEP_CACHE_MAX:
        _FOREST_STEP_CACHE.pop(next(iter(_FOREST_STEP_CACHE)))
    _FOREST_STEP_CACHE[key] = fn
    return fn


def init_forest(vcap: int) -> jax.Array:
    """Fresh forest: every vertex self-rooted."""
    return jnp.arange(vcap, dtype=jnp.int32)


def grow_forest(canon: jax.Array, new_vcap: int) -> jax.Array:
    old = canon.shape[0]
    if new_vcap <= old:
        return canon
    return jnp.concatenate(
        [canon, jnp.arange(old, new_vcap, dtype=jnp.int32)]
    )


class WindowPrep:
    """Reusable host scratch for the per-window touched-set + local
    renumbering. Native single pass when the toolchain is available
    (``native.NativeWindowPrep``: epoch-stamped, ~10-15 ms/1M-edge
    window); numpy bitmap + LUT fallback (~50 ms — still 13x faster than
    the ``np.unique`` + ``searchsorted`` it replaced, whose binary
    search is cache-miss bound). Touched-id ORDER differs between the
    two (arrival vs sorted) — the device kernels index by position, not
    value, so both are valid; emission/checkpoint never depend on it."""

    __slots__ = ("bm", "lut", "_native")

    def __init__(self):
        self.bm = np.zeros(0, bool)
        self.lut = np.zeros(0, np.int32)
        try:
            from .. import native

            self._native = native.NativeWindowPrep()
        except Exception:
            self._native = None

    def prep(self, src_h, dst_h, vcap: int):
        """-> (tids unique endpoints, lu, lv local indices)."""
        if self._native is not None:
            return self._native.run(src_h, dst_h, vcap)
        if len(self.bm) < vcap:
            self.bm = np.zeros(vcap, bool)
            self.lut = np.zeros(vcap, np.int32)
        bm = self.bm
        bm[src_h] = True
        bm[dst_h] = True
        tids = np.nonzero(bm[:vcap])[0].astype(np.int32)
        bm[tids] = False  # restore the scratch without an O(V) clear
        self.lut[tids] = np.arange(len(tids), dtype=np.int32)
        return tids, self.lut[src_h], self.lut[dst_h]


def pad_window(prep, src_h, dst_h, vcap: int, wmin: int = 8):
    """Shared host prep + pow2 bucket padding for the window-local steps
    (CC forest + signed-cover): returns ``(tids, tcap, wcap, tid, tmask,
    lu, lv)`` with the touched bucket masked and the edge columns
    zero-padded (pad rows are (0,0) self-loops; carries whose space
    makes those meaningful — the cover — add their own edge mask)."""
    n = len(src_h)
    tids, lu_r, lv_r = prep.prep(src_h, dst_h, vcap)
    t = len(tids)
    tcap = bucket_capacity(t, minimum=8)
    wcap = bucket_capacity(n, minimum=wmin)
    tid = np.zeros(tcap, np.int32)
    tid[:t] = tids
    tmask = np.zeros(tcap, bool)
    tmask[:t] = True
    lu = np.zeros(wcap, np.int32)
    lv = np.zeros(wcap, np.int32)
    lu[:n] = lu_r
    lv[:n] = lv_r
    return tids, tcap, wcap, tid, tmask, lu, lv


def forest_window(
    canon: jax.Array,
    src_h: np.ndarray,
    dst_h: np.ndarray,
    vcap: int,
    prep: WindowPrep,
    mesh=None,
    tree: bool = False,
    degree: int = 2,
) -> Tuple[jax.Array, np.ndarray]:
    """Fold one window (host compact-id columns) into the forest.

    ``prep`` is REQUIRED: it is the reusable per-stream scratch (native
    wprep handle + vcap-sized table) — constructing one per window would
    silently re-allocate all of it, defeating the class's design
    (round-5 advisor finding 4). Callers hold one WindowPrep per stream.

    Returns ``(new_canon, touched_ids)`` where ``touched_ids`` holds the
    window's unique endpoints (ORDER UNSPECIFIED: arrival order from the
    native prep, sorted from the numpy fallback — every consumer indexes
    by position or treats them as a set) — the caller maintains the host
    first-seen log for emission. All device inputs are bucketed to
    powers of two so a stream hits O(log^2) jit signatures.
    """
    if prep is None:
        raise ValueError(
            "forest_window requires a per-stream WindowPrep (its scratch "
            "is reusable by design; allocating one per window would "
            "silently re-create the native handle and vcap-sized table)"
        )
    n = len(src_h)
    if n == 0:
        return canon, np.zeros(0, np.int32)
    wmin = 8
    if mesh is not None:
        from ..parallel.mesh import EDGE_AXIS

        # the sharded columns must divide by the axis size; passing it as
        # the bucket minimum keeps every bucket divisible for ANY axis
        # width (the edgeblock.py convention), not just powers of two
        wmin = max(wmin, mesh.shape[EDGE_AXIS])
    tids, tcap, wcap, tid, tmask, lu, lv = pad_window(
        prep, src_h, dst_h, vcap, wmin
    )
    step = _forest_step_fn(tcap, wcap, vcap, mesh, tree, degree)
    canon = step(
        canon,
        jnp.asarray(tid),
        jnp.asarray(tmask),
        jnp.asarray(lu),
        jnp.asarray(lv),
    )
    return canon, tids


#: device-mirror scatter for the host carry (jit re-specializes per
#: (ncap, vcap) shape pair automatically)
_mirror_jit = jax.jit(lambda c, i, v: c.at[i].set(v, mode="drop"))


def mirror_update(
    canon: jax.Array, idx_np: np.ndarray, val_np: np.ndarray, vcap: int
) -> jax.Array:
    """Apply a host-computed re-rooting to the device pointer-forest
    mirror: one masked scatter (pads dropped at index ``vcap``)."""
    n = len(idx_np)
    if n == 0:
        return canon
    ncap = bucket_capacity(n, minimum=8)
    idx = np.full(ncap, vcap, np.int64)
    val = np.zeros(ncap, np.int32)
    idx[:n] = idx_np
    val[:n] = val_np
    return _mirror_jit(canon, jnp.asarray(idx), jnp.asarray(val))


def resolve_flat(canon: jax.Array) -> jax.Array:
    """Canonicalize the forest to flat labels ON DEVICE (checkpoint /
    mode-switch sync point): pointer-jumping doubles chain shortcuts per
    pass, so depth is log2 of the longest chain."""

    def body(lab):
        return lab[lab]

    return lax.while_loop(
        lambda lab: jnp.any(lab[lab] != lab), body, canon
    )


def resolve_flat_host(canon_np: np.ndarray) -> np.ndarray:
    """Host-side canonicalization (emission materialization path)."""
    lab = canon_np
    while True:
        nxt = lab[lab]
        if np.array_equal(nxt, lab):
            return lab
        lab = nxt


class TouchLog:
    """Append-only first-seen log of touched compact ids.

    The host computes the touched set per window anyway (it builds the
    local renumbering), so first-seen tracking costs one vectorized
    bitmap lookup — the novelty-shadow pattern. Emissions snapshot the
    log by COUNT only: the first ``count`` entries of an append-only log
    never change, so a lazy emission is O(1) at yield time.
    """

    __slots__ = ("seen", "ids", "count")

    def __init__(self, vcap: int = 0):
        self.seen = np.zeros(vcap, bool)
        self.ids = np.zeros(256, np.int32)
        self.count = 0

    def grow(self, vcap: int) -> None:
        if vcap > len(self.seen):
            self.seen = np.concatenate(
                [self.seen, np.zeros(vcap - len(self.seen), bool)]
            )

    def add(self, tids: np.ndarray) -> None:
        fresh = tids[~self.seen[tids]]
        if len(fresh) == 0:
            return
        self.seen[fresh] = True
        need = self.count + len(fresh)
        if need > len(self.ids):
            cap = len(self.ids)
            while cap < need:
                cap *= 2
            grown = np.zeros(cap, np.int32)
            grown[: self.count] = self.ids[: self.count]
            self.ids = grown
        self.ids[self.count : need] = fresh
        self.count = need

    def touched_bool(self, vcap: int) -> np.ndarray:
        out = np.zeros(vcap, bool)
        out[: len(self.seen)] = self.seen[:vcap]
        return out

    @staticmethod
    def from_touched_bool(tb: np.ndarray) -> "TouchLog":
        log = TouchLog(len(tb))
        log.add(np.nonzero(tb)[0].astype(np.int32))
        return log
