"""Window-local CC fold over a lazily-canonicalized forest carry.

The dense-label engine (``summaries/labels.py``) pays O(vcap) work per
window — init_labels + full-table fixpoint + combine — even when the
window touches <=2W vertices. That is the wrong cost shape vs the
reference, whose per-partition fold touches only the window's edges
(``SummaryBulkAggregation.java:76-80``); the honest CPU bracket measured
it directly (BENCH_CPU r4: 0.45x the compiled baseline at 1M-edge
windows, V-bound at scale 23).

This module is the round-5 redesign: the carried summary becomes a
**pointer forest** ``canon[vcap]`` (int32, ``canon[v] <= v``, acyclic by
the strictly-decreasing min-root invariant) that is only *canonicalized*
— chains collapsed to flat labels — at emission or checkpoint time.
Per window, every kernel is sized by the window, not the vertex space:

1. The HOST computes the window's touched set beside the stream (unique
   endpoints of the cached pre-padding columns, order unspecified — the
   novelty-shadow pattern: zero device->host reads in the producer loop)
   and renumbers the window's edges into local indices ``[0, T)``.
2. The DEVICE chases the touched vertices' pointers to their current
   roots (``lax.while_loop`` of O(T) gathers; chains only pass through
   former roots, and touched vertices are fully path-compressed every
   window).
3. A min-label fixpoint over the **local** T-sized table joins the
   window's edges with "same current root" chain constraints (from one
   T-sort), exactly the dense kernel's hook+shortcut but on a table the
   size of the window.
4. One masked scatter re-roots the old roots (and the touched vertices,
   for path compression) to the merged component's min root.

The remaining vcap-sized costs are bandwidth-only: the functional
scatter's buffer copy (which is also what keeps per-window emissions
valid snapshots — the pre-scatter buffer stays alive for any lazy
emission holding it) and the step-2 scratch memset — two linear HBM
passes per window instead of the dense path's ~10-20 full-table
gather/scatter fixpoint passes.

Reference parity: this is the ``UpdateCC``/``CombineCC`` pair of
``library/ConnectedComponents.java:83-126`` with the DisjointSet's
pointer forest kept on device and its find-with-path-compression
vectorized over the window's touched set.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.edgeblock import bucket_capacity
from .labels import _propagate

_I32_MAX = jnp.iinfo(jnp.int32).max

#: jitted per-(Tcap, Wcap, vcap, mesh, tree, degree) window steps;
#: bounded FIFO like the engine's step cache (each signature costs
#: seconds on a remote TPU).
_FOREST_STEP_CACHE: dict = {}
_FOREST_STEP_CACHE_MAX = 32


def _table_combine(tcap: int):
    """Merge two local label tables over the same touched set: the
    union's constraints are exactly the pointer edges of both tables
    (``labels.label_combine`` on plain arrays)."""
    iota = jnp.arange(tcap, dtype=jnp.int32)

    def combine(a, b):
        u = jnp.concatenate([iota, iota])
        w = jnp.concatenate([a, b])
        return _propagate(
            jnp.minimum(a, b), u, w, jnp.ones(2 * tcap, bool)
        )

    return combine


def chase_and_group(canon, tid, tmask, tcap: int, vcap: int):
    """Shared forest-step front half (CC + signed-cover carries).

    1. Chase touched pointers to their current roots. Read-only on
       canon, so chains are static during the chase; roots satisfy
       canon[r] == r and chains strictly decrease (min-root invariant)
       so the loop terminates. Padding lanes chase from 0, which is
       always self-rooted (canon[0] <= 0).
    2. "Same current root" constraints WITHOUT a sort (argsort over the
       touched bucket measured 375 ms on the CPU backend): scatter each
       lane's local index into a vcap scratch keyed by root, so every
       lane learns its group's representative lane — one bandwidth-bound
       memset+scatter+gather instead of a comparison sort. Edge
       (i, rep_i) unifies the group; pads self-loop.

    Returns ``(r, v2, key_, iota)``: current roots per lane, the group-
    edge targets, the root-value keys (+inf on pads), and the lane iota.
    """
    r0 = jnp.where(tmask, canon[tid], 0)
    r = lax.while_loop(
        lambda r: jnp.any(canon[r] != r), lambda r: canon[r], r0
    )
    iota = jnp.arange(tcap, dtype=jnp.int32)
    sid_r = jnp.where(tmask, r, vcap)
    scratch = jnp.full(vcap, _I32_MAX, jnp.int32).at[sid_r].min(
        jnp.where(tmask, iota, _I32_MAX), mode="drop"
    )
    rep = scratch[jnp.where(tmask, r, 0)]
    v2 = jnp.where(tmask, rep, iota)
    key_ = jnp.where(tmask, r, _I32_MAX)
    return r, v2, key_, iota


def commit_roots(canon, local, key_, r, tid, tmask, tcap: int, vcap: int):
    """Shared forest-step back half: the merged component's new root is
    the min of its members' old roots (each old root is the min id of
    its old component, so the min over merged roots is the min id of the
    merged component); re-root the old roots and path-compress the
    touched lanes (pads dropped). Returns ``(canon, nr)`` — ``nr`` is
    each lane's final root value (the cover carry's conflict latch reads
    it)."""
    minr = jnp.full(tcap, _I32_MAX, jnp.int32).at[local].min(key_)
    nr = minr[local]
    sid_r = jnp.where(tmask, r, vcap)
    canon = canon.at[sid_r].set(nr, mode="drop")
    tid_s = jnp.where(tmask, tid, vcap)
    canon = canon.at[tid_s].set(nr, mode="drop")
    return canon, nr


def _make_local_fixpoint(tcap: int, mesh=None, tree: bool = False,
                         degree: int = 2):
    """The T-sized local min-label fixpoint, shared by the per-window
    step and the superbatch scan body: ``fixpoint(seed, lu, lv,
    targets)`` folds the window's edge columns PLUS the pointer edges
    ``(i, targets[i])`` (lu/lv pads are (0,0) self-loops, no mask
    needed; the pointer edges must ride along as EDGES because
    ``_propagate`` hooks only edge endpoints — the label_combine
    correctness argument, labels.py). The per-window step seeds from
    iota with the same-root group edges as targets; the superbatch scan
    body seeds from (and targets) the carried group label table. Under
    a mesh this is the engine's per-shard-fold + cross-shard-combine
    shape on WINDOW-SIZED tables: each shard folds its slice of the
    edge columns (the T-sized pointer edges replicate — same
    constraints everywhere), then the label tables merge through the
    bulk stack or the ppermute butterfly. The vcap-sized carry never
    crosses the mesh."""
    if mesh is not None:
        from jax.sharding import PartitionSpec as P

        from ..parallel import comm
        from ..parallel.mesh import EDGE_AXIS

        p = mesh.shape[EDGE_AXIS]
        combine = _table_combine(tcap)

    iota = jnp.arange(tcap, dtype=jnp.int32)

    def fixpoint(seed, lu, lv, targets):
        if mesh is None:
            u = jnp.concatenate([lu, iota])
            w = jnp.concatenate([lv, targets])
            return _propagate(seed, u, w, jnp.ones(u.shape[0], bool))

        def shard_fn(lu_s, lv_s):
            u = jnp.concatenate([lu_s, iota])
            w = jnp.concatenate([lv_s, targets])
            lab = _propagate(seed, u, w, jnp.ones(u.shape[0], bool))
            if tree:
                return comm.tree_all_reduce(
                    lab, EDGE_AXIS, combine, p, degree=degree
                )
            return lab[None]

        out = comm.shard_map(
            shard_fn, mesh, (P(EDGE_AXIS), P(EDGE_AXIS)),
            P() if tree else P(EDGE_AXIS),
        )(lu, lv)
        return out if tree else comm.stacked_reduce(out, p, combine)

    return fixpoint


def _forest_step_fn(tcap: int, wcap: int, vcap: int, mesh=None,
                    tree: bool = False, degree: int = 2):
    key = (tcap, wcap, vcap, mesh, tree, degree)
    fn = _FOREST_STEP_CACHE.get(key)
    if fn is not None:
        return fn

    fixpoint = _make_local_fixpoint(tcap, mesh, tree, degree)

    def step(canon, tid, tmask, lu, lv):
        r, v2, key_, iota = chase_and_group(canon, tid, tmask, tcap, vcap)
        local = fixpoint(iota, lu, lv, v2)
        canon, _nr = commit_roots(canon, local, key_, r, tid, tmask, tcap, vcap)
        return canon

    fn = jax.jit(step)
    if len(_FOREST_STEP_CACHE) >= _FOREST_STEP_CACHE_MAX:
        _FOREST_STEP_CACHE.pop(next(iter(_FOREST_STEP_CACHE)))
    _FOREST_STEP_CACHE[key] = fn
    return fn


def _forest_superbatch_fn(tcap: int, wcap: int, vcap: int, k: int,
                          mesh=None, tree: bool = False, degree: int = 2):
    """K forest window-steps fused into one jitted dispatch, GROUP-LOCAL.

    The naive fusion — scanning the per-window step with the vcap-sized
    canon as the carry — still pays vcap-sized work per window (XLA
    materializes carry updates, and the group-rep scratch memset is
    vcap-wide), which is exactly the cost shape the forest carry exists
    to avoid. This kernel instead hoists ALL vcap-sized work to the
    group boundary:

    1. ONE root chase + same-root grouping over the group's union
       touched set (``chase_and_group`` — one vcap scratch memset per
       GROUP, not per window);
    2. a ``lax.scan`` over the K windows whose carry is only the
       T-sized local label table: window k folds its edge columns into
       the carried table (seeded ``_propagate``) and emits
       ``nr_k[lane] = min pre-group root value of lane's merged group``
       — the per-window new-root assignment, [k, tcap];
    3. ONE masked scatter pair re-roots the old roots and
       path-compresses the whole touched set with the final window's
       assignment.

    Sequential window semantics are preserved by the carried table
    (window k sees every merge from windows < k); per-window canon
    snapshots are recovered lazily from ``(r, nr_k)`` by
    :class:`ForestReplay` — value-identical under resolution to the
    per-window path's canon (pointer SHAPE may differ: the fused commit
    path-compresses the group's touched set once at the end, which
    changes no root assignment).

    The input canon is NOT donated: the pre-group buffer backs the
    group's lazy emissions — the one vcap-copy per GROUP replaces the
    per-window path's copy per WINDOW.
    """
    key = ("superbatch", tcap, wcap, vcap, k, mesh, tree, degree)
    fn = _FOREST_STEP_CACHE.get(key)
    if fn is not None:
        return fn

    fixpoint = _make_local_fixpoint(tcap, mesh, tree, degree)

    def step(canon, tid, tmask, lu, lv):
        r, v2, key_, iota = chase_and_group(canon, tid, tmask, tcap, vcap)
        # v2 maps each lane to the MIN lane of its pre-group root group:
        # a depth-1 min-rooted pointer forest, i.e. already a valid
        # label table encoding the group constraints — no fixpoint needed
        lab0 = v2

        def body(lab, xs):
            lu_k, lv_k = xs
            lab = fixpoint(lab, lu_k, lv_k, lab)
            minr = jnp.full(tcap, _I32_MAX, jnp.int32).at[lab].min(key_)
            return lab, minr[lab]

        lab_end, nr_s = lax.scan(body, lab0, (lu, lv))
        nr_end = nr_s[-1]
        sid_r = jnp.where(tmask, r, vcap)
        canon = canon.at[sid_r].set(nr_end, mode="drop")
        tid_s = jnp.where(tmask, tid, vcap)
        canon = canon.at[tid_s].set(nr_end, mode="drop")
        return canon, r, nr_s

    fn = jax.jit(step)
    if len(_FOREST_STEP_CACHE) >= _FOREST_STEP_CACHE_MAX:
        _FOREST_STEP_CACHE.pop(next(iter(_FOREST_STEP_CACHE)))
    _FOREST_STEP_CACHE[key] = fn
    return fn


def init_forest(vcap: int) -> jax.Array:
    """Fresh forest: every vertex self-rooted."""
    return jnp.arange(vcap, dtype=jnp.int32)


def grow_forest(canon: jax.Array, new_vcap: int) -> jax.Array:
    old = canon.shape[0]
    if new_vcap <= old:
        return canon
    return jnp.concatenate(
        [canon, jnp.arange(old, new_vcap, dtype=jnp.int32)]
    )


class WindowPrep:
    """Reusable host scratch for the per-window touched-set + local
    renumbering. Native single pass when the toolchain is available
    (``native.NativeWindowPrep``: epoch-stamped, ~10-15 ms/1M-edge
    window); numpy bitmap + LUT fallback (~50 ms — still 13x faster than
    the ``np.unique`` + ``searchsorted`` it replaced, whose binary
    search is cache-miss bound). Touched-id ORDER differs between the
    two (arrival vs sorted) — the device kernels index by position, not
    value, so both are valid; emission/checkpoint never depend on it."""

    __slots__ = ("bm", "lut", "_native")

    def __init__(self):
        self.bm = np.zeros(0, bool)
        self.lut = np.zeros(0, np.int32)
        try:
            from .. import native

            self._native = native.NativeWindowPrep()
        except Exception:
            self._native = None

    def prep(self, src_h, dst_h, vcap: int):
        """-> (tids unique endpoints, lu, lv local indices)."""
        if self._native is not None:
            return self._native.run(src_h, dst_h, vcap)
        if len(self.bm) < vcap:
            self.bm = np.zeros(vcap, bool)
            self.lut = np.zeros(vcap, np.int32)
        bm = self.bm
        bm[src_h] = True
        bm[dst_h] = True
        tids = np.nonzero(bm[:vcap])[0].astype(np.int32)
        bm[tids] = False  # restore the scratch without an O(V) clear
        self.lut[tids] = np.arange(len(tids), dtype=np.int32)
        return tids, self.lut[src_h], self.lut[dst_h]


def pad_window(prep, src_h, dst_h, vcap: int, wmin: int = 8):
    """Shared host prep + pow2 bucket padding for the window-local steps
    (CC forest + signed-cover): returns ``(tids, tcap, wcap, tid, tmask,
    lu, lv)`` with the touched bucket masked and the edge columns
    zero-padded (pad rows are (0,0) self-loops; carries whose space
    makes those meaningful — the cover — add their own edge mask)."""
    n = len(src_h)
    tids, lu_r, lv_r = prep.prep(src_h, dst_h, vcap)
    t = len(tids)
    tcap = bucket_capacity(t, minimum=8)
    wcap = bucket_capacity(n, minimum=wmin)
    tid = np.zeros(tcap, np.int32)
    tid[:t] = tids
    tmask = np.zeros(tcap, bool)
    tmask[:t] = True
    lu = np.zeros(wcap, np.int32)
    lv = np.zeros(wcap, np.int32)
    lu[:n] = lu_r
    lv[:n] = lv_r
    return tids, tcap, wcap, tid, tmask, lu, lv


def forest_window(
    canon: jax.Array,
    src_h: np.ndarray,
    dst_h: np.ndarray,
    vcap: int,
    prep: WindowPrep,
    mesh=None,
    tree: bool = False,
    degree: int = 2,
) -> Tuple[jax.Array, np.ndarray]:
    """Fold one window (host compact-id columns) into the forest.

    ``prep`` is REQUIRED: it is the reusable per-stream scratch (native
    wprep handle + vcap-sized table) — constructing one per window would
    silently re-allocate all of it, defeating the class's design
    (round-5 advisor finding 4). Callers hold one WindowPrep per stream.

    Returns ``(new_canon, touched_ids)`` where ``touched_ids`` holds the
    window's unique endpoints (ORDER UNSPECIFIED: arrival order from the
    native prep, sorted from the numpy fallback — every consumer indexes
    by position or treats them as a set) — the caller maintains the host
    first-seen log for emission. All device inputs are bucketed to
    powers of two so a stream hits O(log^2) jit signatures.
    """
    if prep is None:
        raise ValueError(
            "forest_window requires a per-stream WindowPrep (its scratch "
            "is reusable by design; allocating one per window would "
            "silently re-create the native handle and vcap-sized table)"
        )
    n = len(src_h)
    if n == 0:
        return canon, np.zeros(0, np.int32)
    wmin = 8
    if mesh is not None:
        from ..parallel.mesh import EDGE_AXIS

        # the sharded columns must divide by the axis size; passing it as
        # the bucket minimum keeps every bucket divisible for ANY axis
        # width (the edgeblock.py convention), not just powers of two
        wmin = max(wmin, mesh.shape[EDGE_AXIS])
    tids, tcap, wcap, tid, tmask, lu, lv = pad_window(
        prep, src_h, dst_h, vcap, wmin
    )
    step = _forest_step_fn(tcap, wcap, vcap, mesh, tree, degree)
    canon = step(
        canon,
        jnp.asarray(tid),
        jnp.asarray(tmask),
        jnp.asarray(lu),
        jnp.asarray(lv),
    )
    return canon, tids


class ForestReplay:
    """Lazy mid-group canon reconstruction for superbatch emissions.

    A superbatch dispatch materializes only the FINAL canon plus the
    group's per-window new-root assignments (``nr``, device ``[k, tcap]``)
    over the group-shared touched lanes (host ``tid``/``tmask``, device
    old roots ``r``). A window-k emission that is actually read rebuilds
    that window's canon on host: copy the pre-group base and apply
    window k's assignment to the old roots and the touched set — the
    same scatter pair the fused commit runs with the last window's
    assignment, so the reconstruction resolves identically to the
    per-window path's canon. Unread emissions cost nothing; the delta
    download happens once per group on first read.
    """

    __slots__ = ("_base", "_tid", "_tmask", "_r_dev", "_nr_dev",
                 "_base_np", "_r", "_nr")

    def __init__(self, base_canon, tid: np.ndarray, tmask: np.ndarray,
                 r_dev, nr_stack):
        self._base = base_canon  # device buffer, pre-group (not donated)
        self._tid = tid          # host [tcap]
        self._tmask = tmask      # host [tcap]
        self._r_dev = r_dev      # device [tcap]
        self._nr_dev = nr_stack  # device [k, tcap]
        self._base_np = None
        self._r = None
        self._nr = None

    def canon_np(self, k: int) -> np.ndarray:
        """Host canon after window ``k`` of the group (a private copy)."""
        if self._r is None:
            self._r = np.asarray(self._r_dev)
            self._nr = np.asarray(self._nr_dev)
            self._base_np = np.asarray(self._base)
        canon = self._base_np.copy()
        m = self._tmask
        canon[self._r[m]] = self._nr[k][m]
        canon[self._tid[m]] = self._nr[k][m]
        return canon


def forest_superbatch(
    canon: jax.Array,
    windows,
    vcap: int,
    prep: WindowPrep,
    mesh=None,
    tree: bool = False,
    degree: int = 2,
) -> Tuple[jax.Array, list, "ForestReplay"]:
    """Fold K windows (list of host ``(src_h, dst_h)`` column pairs)
    into the forest as ONE fused group-local dispatch.

    Host side, two prep passes through the same per-stream
    :class:`WindowPrep` scratch: (a) one prep per window for the
    PER-WINDOW touched ids (the first-seen log advances in window
    order), (b) one prep over the group's concatenated columns for the
    GROUP touched set and the group-local edge renumbering — the lane
    space the device scan's carried label table lives in. All K windows
    pad to the group's bucketed caps, so a stream hits
    O(log^2 x distinct-k) jit signatures; padding lanes are inert in
    every kernel (pads chase from 0 and scatter-drop).

    Returns ``(new_canon, [touched_ids per window], replay)`` — the
    caller feeds ``touched_ids`` to its first-seen log in window order
    and hands ``replay`` to the group's lazy emissions.
    """
    if prep is None:
        raise ValueError(
            "forest_superbatch requires a per-stream WindowPrep (see "
            "forest_window)"
        )
    k = len(windows)
    _e = np.zeros(0, np.int32)
    # (a) per-window touched ids, in window order, for the TouchLog
    win_tids = [
        prep.prep(s, d, vcap)[0] if len(s) else _e for s, d in windows
    ]
    # (b) group touched set + group-local renumbering in ONE pass
    src_g = np.concatenate([s for s, _ in windows]) if k else _e
    dst_g = np.concatenate([d for _, d in windows]) if k else _e
    if len(src_g):
        tids_g, lu_all, lv_all = prep.prep(src_g, dst_g, vcap)
    else:
        tids_g, lu_all, lv_all = _e, _e, _e
    n_max = max((len(s) for s, _ in windows), default=0)
    wmin = 8
    if mesh is not None:
        from ..parallel.mesh import EDGE_AXIS

        wmin = max(wmin, mesh.shape[EDGE_AXIS])
    tcap = bucket_capacity(len(tids_g), minimum=8)
    wcap = bucket_capacity(n_max, minimum=wmin)
    t = len(tids_g)
    tid = np.zeros(tcap, np.int32)
    tid[:t] = tids_g
    tmask = np.zeros(tcap, bool)
    tmask[:t] = True
    lu = np.zeros((k, wcap), np.int32)
    lv = np.zeros((k, wcap), np.int32)
    off = 0
    for i, (s, _) in enumerate(windows):
        n = len(s)
        lu[i, :n] = lu_all[off:off + n]
        lv[i, :n] = lv_all[off:off + n]
        off += n
    step = _forest_superbatch_fn(tcap, wcap, vcap, k, mesh, tree, degree)
    new_canon, r_dev, nr_s = step(
        canon,
        jnp.asarray(tid),
        jnp.asarray(tmask),
        jnp.asarray(lu),
        jnp.asarray(lv),
    )
    replay = ForestReplay(canon, tid, tmask, r_dev, nr_s)
    return new_canon, win_tids, replay


class MirrorReplay:
    """Lazy mid-group canon reconstruction for HOST-carry superbatches.

    The host union-find computes each window's re-rooting delta
    ``(idx, val)`` on host anyway; the superbatch path defers the device
    mirror to ONE batched scatter per group, so mid-group canons exist
    only as these host deltas. Reconstruction is cumulative (deltas
    apply in window order); sequential reads advance incrementally, a
    backward read restarts from the pre-group base. The base device
    buffer downloads once, lazily.
    """

    __slots__ = ("_base", "_deltas", "_canon", "_upto")

    def __init__(self, base_canon, deltas):
        self._base = base_canon  # device buffer, pre-group
        # [(touched, roots, changed, changed_roots) per window]
        self._deltas = deltas
        self._canon = None
        self._upto = -1

    def canon_np(self, k: int) -> np.ndarray:
        """Host canon after window ``k`` of the group (a private copy)."""
        if self._canon is None or k < self._upto:
            self._canon = np.asarray(self._base).copy()
            self._upto = -1
        for j in range(self._upto + 1, k + 1):
            t, r, c, cr = self._deltas[j]
            self._canon[t] = r
            self._canon[c] = cr
        self._upto = k
        return self._canon.copy()


#: device-mirror scatter for the host carry (jit re-specializes per
#: (ncap, vcap) shape pair automatically)
_mirror_jit = jax.jit(lambda c, i, v: c.at[i].set(v, mode="drop"))


def mirror_update(
    canon: jax.Array, idx_np: np.ndarray, val_np: np.ndarray, vcap: int
) -> jax.Array:
    """Apply a host-computed re-rooting to the device pointer-forest
    mirror: one masked scatter (pads dropped at index ``vcap``)."""
    n = len(idx_np)
    if n == 0:
        return canon
    ncap = bucket_capacity(n, minimum=8)
    idx = np.full(ncap, vcap, np.int64)
    val = np.zeros(ncap, np.int32)
    idx[:n] = idx_np
    val[:n] = val_np
    return _mirror_jit(canon, jnp.asarray(idx), jnp.asarray(val))


def resolve_flat(canon: jax.Array) -> jax.Array:
    """Canonicalize the forest to flat labels ON DEVICE (checkpoint /
    mode-switch sync point): pointer-jumping doubles chain shortcuts per
    pass, so depth is log2 of the longest chain."""

    def body(lab):
        return lab[lab]

    return lax.while_loop(
        lambda lab: jnp.any(lab[lab] != lab), body, canon
    )


def resolve_flat_host(canon_np: np.ndarray) -> np.ndarray:
    """Host-side canonicalization (emission materialization path)."""
    lab = canon_np
    while True:
        nxt = lab[lab]
        if np.array_equal(nxt, lab):
            return lab
        lab = nxt


def fold_edges_host(canon_np: np.ndarray, src: np.ndarray,
                    dst: np.ndarray) -> np.ndarray:
    """Fold ONE edge-column group into a host forest table, returning a
    fully-canonical min-rooted flat table (``out[v] <= v``, depth 1).

    The host analog of the group-fold window step: min-label hooking
    over the group's edges alternated with :func:`resolve_flat_host`
    pointer jumping until fixpoint — every pass is whole-array numpy,
    never a per-edge Python loop. Monotone (labels only decrease), so
    it terminates; the result's components are exactly the input
    table's components unioned with the group's edges. Callers pass
    MANY windows' (or many shards') columns concatenated as one group —
    one fold call for the whole group is the group-fold shape."""
    lab = resolve_flat_host(np.asarray(canon_np))
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if len(src) == 0:
        return lab
    lab = lab.copy()
    while True:
        lo = np.minimum(lab[src], lab[dst])
        before = lab
        lab = lab.copy()
        # hook both endpoints' current ROOTS down to the edge minimum;
        # the flat invariant between passes makes lab[src] the root
        np.minimum.at(lab, before[src], lo)
        np.minimum.at(lab, before[dst], lo)
        lab = resolve_flat_host(lab)
        if np.array_equal(lab, before):
            return lab


def fold_into_forest_host(canon_np: np.ndarray, src: np.ndarray,
                          dst: np.ndarray) -> np.ndarray:
    """Fold a SMALL edge group into a BIG flat table without paying the
    whole-table fixpoint per pass (ISSUE 18's per-pane fold shape: a
    few thousand edges against a table of a million rows, where
    :func:`fold_edges_host`'s resolve-per-pass iterations are all
    table scans).

    Union happens at ROOT granularity: the group's edges project to
    edges between current component roots, those roots compact to a
    dense local id space, the local forest folds with
    :func:`fold_edges_host` (tiny arrays, same fixpoint), and ONE
    whole-table mapping pass rewrites every vertex whose root merged.
    Roots are min vertex ids and the local fold picks the min local
    index — which is the min root under the sorted compaction — so the
    result is byte-identical to ``fold_edges_host(canon_np, src, dst)``
    (the oracle contract), at O(group·fixpoint + table) instead of
    O(table·fixpoint)."""
    lab = resolve_flat_host(np.asarray(canon_np))
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if len(src) == 0:
        return lab
    rs, rd = lab[src], lab[dst]
    roots = np.unique(np.concatenate([rs, rd]))
    if len(roots) < 2:
        return lab
    local = fold_edges_host(
        np.arange(len(roots), dtype=np.int64),
        np.searchsorted(roots, rs),
        np.searchsorted(roots, rd),
    )
    newroot = roots[local]
    if np.array_equal(newroot, roots):
        return lab  # the group united nothing new
    # one table pass: a scatter/gather translation table (root ->
    # merged root, identity elsewhere) beats a binary search per row
    trans = np.arange(len(lab), dtype=np.int64)
    trans[roots] = newroot
    return trans[lab]


def merge_forest_tables_host(tables) -> np.ndarray:
    """Cross-shard union step: merge N same-length forest tables into
    one canonical table whose components are the components of the
    UNION of the inputs' edge sets.

    Each input forest IS a spanning structure of its own components
    (edges ``(i, t[i])`` where ``t[i] != i``), so concatenating every
    table's non-trivial pointer edges into ONE group and folding them
    with :func:`fold_edges_host` yields exactly the union connectivity
    — the scatter-gather merge a sharded serving router performs, in
    one group-fold call rather than N incremental ones."""
    tables = [np.asarray(t) for t in tables]
    if not tables:
        raise ValueError("merge_forest_tables_host needs >= 1 table")
    n = len(tables[0])
    for t in tables:
        if len(t) != n:
            raise ValueError(
                f"forest tables disagree on length: {len(t)} != {n}"
            )
    srcs, dsts = [], []
    for t in tables:
        i = np.nonzero(t != np.arange(len(t), dtype=t.dtype))[0]
        srcs.append(i.astype(np.int64))
        dsts.append(t[i].astype(np.int64))
    return fold_edges_host(
        np.arange(n, dtype=np.int32),
        np.concatenate(srcs) if srcs else np.zeros(0, np.int64),
        np.concatenate(dsts) if dsts else np.zeros(0, np.int64),
    )


def apply_forest_delta_host(lab: np.ndarray, sizes: np.ndarray,
                            src: np.ndarray, dst: np.ndarray) -> np.ndarray:
    """Incremental counterpart to :func:`merge_forest_tables_host`:
    union a SMALL batch of delta edges into an existing canonical host
    forest IN PLACE, O(changed rows * alpha) instead of O(forest).

    ``lab`` is a min-rooted pointer table (``lab[v] <= v``; flat or the
    output of earlier delta applications) and ``sizes`` the per-dense-id
    member counts of its roots; both are mutated. Unions hook the LARGER
    root under the smaller (min-label discipline), so the invariant —
    and therefore agreement with a from-scratch
    :func:`merge_forest_tables_host` rebuild after
    :func:`resolve_flat_host` — is preserved exactly. Path-halving on
    the find walks keeps amortized chains near-flat between full
    rebuilds.

    Returns the dense ids of every root that participated in an
    EFFECTIVE union (winners and absorbed alike; empty when no edge
    changed connectivity) — the selective cache-invalidation signal the
    sharded router keys on: a cached answer whose roots are disjoint
    from this set provably kept its components untouched."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    if len(src) != len(dst):
        raise ValueError(
            f"delta columns disagree on length: {len(src)} != {len(dst)}"
        )
    touched = set()
    for a, b in zip(src.tolist(), dst.tolist()):
        ra = a
        while lab[ra] != ra:
            lab[ra] = lab[lab[ra]]  # path halving
            ra = int(lab[ra])
        rb = b
        while lab[rb] != rb:
            lab[rb] = lab[lab[rb]]
            rb = int(lab[rb])
        if ra == rb:
            continue
        if rb < ra:
            ra, rb = rb, ra
        lab[rb] = ra
        sizes[ra] += sizes[rb]
        touched.add(ra)
        touched.add(rb)
    if not touched:
        return np.zeros(0, np.int64)
    return np.fromiter(touched, np.int64, len(touched))


def repair_forest_host(
    lab: np.ndarray,
    expired_src: np.ndarray,
    expired_dst: np.ndarray,
    surviving_src: np.ndarray,
    surviving_dst: np.ndarray,
):
    """Decremental counterpart to :func:`apply_forest_delta_host`: REPAIR
    a host forest after a batch of edges EXPIRED (event-time retraction,
    ISSUE 18), rebuilding ONLY the affected components.

    Union-find supports cheap union but not cheap deletion; the repair
    rule this repo uses is bounded recompute from the carried table: the
    components the expired edges touched (their roots in ``lab``) are
    reset to singletons, and exactly the SURVIVING edges incident to
    those components are re-folded through :func:`fold_edges_host` — one
    group-fold call over the suspect subgraph, never the whole stream.
    An edge's endpoints always share a component, so membership of ONE
    endpoint in an affected component selects precisely the suspect
    edges.

    ``lab`` is a canonical forest table (any pointer depth; resolved
    here). ``surviving_src``/``surviving_dst`` are the live edge
    multiset AFTER the expiry (callers keep per-pane columns, so this is
    a concatenation of the surviving panes' views, not a recompute).
    Returns ``(new_lab, stats)`` where ``new_lab`` is fully-canonical
    min-rooted flat (byte-identical to a from-scratch
    :func:`fold_edges_host` over the surviving multiset — the oracle
    contract ``tests/test_eventtime.py`` pins) and ``stats`` records the
    bounded-recompute evidence: affected roots/members and re-folded
    edge count (the retraction-vs-rebuild ratio ``bench.py --eventtime``
    commits)."""
    lab = resolve_flat_host(np.asarray(lab))
    expired_src = np.asarray(expired_src, np.int64)
    expired_dst = np.asarray(expired_dst, np.int64)
    surviving_src = np.asarray(surviving_src, np.int64)
    surviving_dst = np.asarray(surviving_dst, np.int64)
    if len(expired_src) != len(expired_dst):
        raise ValueError(
            f"expired columns disagree on length: "
            f"{len(expired_src)} != {len(expired_dst)}"
        )
    if len(surviving_src) != len(surviving_dst):
        raise ValueError(
            f"surviving columns disagree on length: "
            f"{len(surviving_src)} != {len(surviving_dst)}"
        )
    stats = {"roots": 0, "members": 0, "refolded": 0,
             "surviving": int(len(surviving_src))}
    if len(expired_src) == 0:
        return lab, stats
    roots = np.unique(
        np.concatenate([lab[expired_src], lab[expired_dst]])
    )
    # membership via a scatter bitmap (roots are vertex ids, so the
    # bitmap is table-sized): one gather instead of isin's sort
    root_hit = np.zeros(len(lab), bool)
    root_hit[roots] = True
    affected = root_hit[lab]
    members = np.nonzero(affected)[0]
    out = lab.copy()
    out[members] = members.astype(out.dtype)
    if len(surviving_src):
        suspect = affected[surviving_src]
        s = surviving_src[suspect]
        d = surviving_dst[suspect]
        stats["refolded"] = int(len(s))
        if len(s):
            out = fold_into_forest_host(out, s, d)
    stats["roots"] = int(len(roots))
    stats["members"] = int(len(members))
    return out, stats


class TouchLog:
    """Append-only first-seen log of touched compact ids.

    The host computes the touched set per window anyway (it builds the
    local renumbering), so first-seen tracking costs one vectorized
    bitmap lookup — the novelty-shadow pattern. Emissions snapshot the
    log by COUNT only: the first ``count`` entries of an append-only log
    never change, so a lazy emission is O(1) at yield time.
    """

    __slots__ = ("seen", "ids", "count")

    def __init__(self, vcap: int = 0):
        self.seen = np.zeros(vcap, bool)
        self.ids = np.zeros(256, np.int32)
        self.count = 0

    def grow(self, vcap: int) -> None:
        if vcap > len(self.seen):
            self.seen = np.concatenate(
                [self.seen, np.zeros(vcap - len(self.seen), bool)]
            )

    def add(self, tids: np.ndarray) -> None:
        fresh = tids[~self.seen[tids]]
        if len(fresh) == 0:
            return
        self.seen[fresh] = True
        self._append(fresh)

    def _append(self, fresh: np.ndarray) -> None:
        need = self.count + len(fresh)
        if need > len(self.ids):
            cap = len(self.ids)
            while cap < need:
                cap *= 2
            grown = np.zeros(cap, np.int32)
            grown[: self.count] = self.ids[: self.count]
            self.ids = grown
        self.ids[self.count : need] = fresh
        self.count = need

    def add_grouped(self, ids: np.ndarray, counts: np.ndarray) -> list:
        """Batch K windows' touched sets in ONE vectorized pass.

        ``ids`` is a GROUP-unique concatenation in window first-seen
        order with per-window lengths ``counts`` (the shape
        ``CompactUnionFind.fold_group`` emits); per-window ``add`` calls
        cost ~0.1 ms each in numpy call overhead, which dominates
        1k-edge windows. Returns the per-window log counts (the
        emission snapshots ``add`` would have produced)."""
        fresh_mask = ~self.seen[ids]
        fresh = ids[fresh_mask]
        self.seen[fresh] = True
        before = self.count
        self._append(fresh)
        ends = np.cumsum(np.asarray(counts, np.int64))
        fresh_cum = np.concatenate(
            [[0], np.cumsum(fresh_mask.astype(np.int64))]
        )
        return (before + fresh_cum[ends]).tolist()

    def touched_bool(self, vcap: int) -> np.ndarray:
        out = np.zeros(vcap, bool)
        out[: len(self.seen)] = self.seen[:vcap]
        return out

    @staticmethod
    def from_touched_bool(tb: np.ndarray) -> "TouchLog":
        log = TouchLog(len(tb))
        log.add(np.nonzero(tb)[0].astype(np.int32))
        return log
