from .disjointset import DisjointSet
from .labels import Components, cc_fold, grow_labels, init_labels, label_combine
from .candidates import Candidates, cover_fold, cover_grow, init_cover
from .adjacency import AdjacencyListGraph
