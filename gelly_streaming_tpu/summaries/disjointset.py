"""Host-side DisjointSet: API/verification twin of the dense device labels.

The reference's per-partition CC state is a pointer-chasing union-find over
HashMaps (``summaries/DisjointSet.java:30-154``: ``makeSet``/``find`` with
path compression/``union`` by rank/``merge``). Pointer-chasing cannot run on
a TPU; the device-side equivalent is the dense label array in
``summaries/labels.py``. This host twin exists for three reasons:

1. API parity — users of the reference receive ``DisjointSet`` objects from
   ``aggregate(new ConnectedComponents(...))``; the TPU CC emits
   :class:`Components`, and this class converts/compares.
2. Differential testing — tests union the same edges here and check the
   device labels produce identical partitions.
3. Host algorithms (spanner combine) that genuinely want a union-find.

``__str__`` reproduces the Java ``toString`` shape
(``DisjointSet.java:139-153``): ``{root=[v1, v2], ...}`` — the format the
reference's ConnectedComponentsTest parses.
"""

from __future__ import annotations

from typing import Dict, Iterable, List


class DisjointSet:
    """Union-find with path compression and union by rank."""

    def __init__(self, elements: Iterable[int] = ()):  # noqa: D401
        self._parent: Dict[int, int] = {}
        self._rank: Dict[int, int] = {}
        for e in elements:
            self.make_set(e)

    def make_set(self, e: int) -> None:
        if e not in self._parent:
            self._parent[e] = e
            self._rank[e] = 0

    def find(self, e: int) -> int | None:
        """Root of ``e``'s set (path-compressing), or None if unseen
        (``DisjointSet.java:71-85``)."""
        if e not in self._parent:
            return None
        root = e
        while self._parent[root] != root:
            root = self._parent[root]
        while self._parent[e] != root:  # compress
            self._parent[e], e = root, self._parent[e]
        return root

    def union(self, a: int, b: int) -> None:
        """Union by rank (``DisjointSet.java:97-123``)."""
        self.make_set(a)
        self.make_set(b)
        ra, rb = self.find(a), self.find(b)
        if ra == rb:
            return
        if self._rank[ra] < self._rank[rb]:
            ra, rb = rb, ra
        self._parent[rb] = ra
        if self._rank[ra] == self._rank[rb]:
            self._rank[ra] += 1

    def merge(self, other: "DisjointSet") -> None:
        """Absorb another union-find, naive-hash-join style
        (``DisjointSet.java:132-136``)."""
        for e, p in other._parent.items():
            self.union(e, p)

    # ------------------------------------------------------------------ #
    def elements(self) -> List[int]:
        return list(self._parent)

    def components(self) -> Dict[int, List[int]]:
        """root -> sorted member list."""
        comps: Dict[int, List[int]] = {}
        for e in self._parent:
            comps.setdefault(self.find(e), []).append(e)
        return {r: sorted(m) for r, m in comps.items()}

    def component_sets(self) -> List[frozenset]:
        return [frozenset(m) for m in self.components().values()]

    def __len__(self) -> int:
        return len(self._parent)

    def __str__(self) -> str:
        comps = self.components()
        inner = ", ".join(
            f"{root}={members}" for root, members in sorted(comps.items())
        )
        return "{" + inner + "}"
