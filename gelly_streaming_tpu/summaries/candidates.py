"""Bipartiteness state: signed double cover over dense labels.

The reference tracks 2-colored candidate components in a nested
TreeMap structure with sign-flipping merges and a global failure latch
(``summaries/Candidates.java:27-197``). SURVEY.md §7 replaces the whole
structure with a classic reduction: run connected components on the *signed
double cover* — every vertex v becomes two cover nodes (v,+) and (v,-), and
every edge (u,v) becomes cover edges (u,+)-(v,-) and (u,-)-(v,+). The graph
is bipartite iff no vertex's two cover nodes land in the same component.
That turns all of ``Candidates``' pointer logic into the same dense label
kernels CC uses (``summaries/labels.py``), sharing its collectives.

Layout: cover node (v,+) = index v, (v,-) = index v + vcap, in a label table
of size 2*vcap.

:class:`Candidates` is the host-side emission object, reproducing the
reference's output format byte-for-byte: ``(true,{1={1=(1,true), ...}})`` /
``(false,{})`` (golden strings in ``BipartitenessCheckTest.java:19-21`` and
``NonBipartitnessCheckTest.java:19-20``).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..core.edgeblock import bucket_capacity
from .forest import chase_and_group, commit_roots, pad_window
from .labels import _propagate, init_labels


def init_cover(vcap: int) -> Dict[str, jax.Array]:
    """Fresh signed-double-cover label state (2*vcap cover nodes)."""
    return init_labels(2 * vcap)


def cover_fold(
    state: Dict[str, jax.Array],
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    vcap: int,
) -> Dict[str, jax.Array]:
    """Fold a window's edges into the cover labels.

    Edge (u,v) adds cover constraints (u,+)~(v,-) and (u,-)~(v,+)
    — the dense replacement for ``Candidates.add`` / ``merge``
    (``Candidates.java:52-139``).
    """
    u = jnp.concatenate([src, src + vcap])
    w = jnp.concatenate([dst + vcap, dst])
    m = jnp.concatenate([mask, mask])
    labels = _propagate(state["labels"], u, w, m)
    touched = state["touched"].at[src].max(mask).at[dst].max(mask)
    return {"labels": labels, "touched": touched}


def _shift_cover_labels(lab: np.ndarray, old_vcap: int, new_vcap: int) -> np.ndarray:
    """The cover re-indexing rule, shared by BOTH carries (divergence here
    would break their cross-restorable checkpoints): cover node (v,-)
    moves from v+old to v+new, and label/pointer VALUES into the negative
    half shift by the same amount."""
    new_lab = np.arange(2 * new_vcap, dtype=np.int32)
    shifted = np.where(lab >= old_vcap, lab - old_vcap + new_vcap, lab)
    new_lab[:old_vcap] = shifted[:old_vcap]
    new_lab[new_vcap : new_vcap + old_vcap] = shifted[old_vcap:]
    return new_lab


def cover_grow(state: Dict[str, jax.Array], old_vcap: int, new_vcap: int) -> Dict[str, jax.Array]:
    """Re-index the cover when the vertex capacity bucket grows
    (see :func:`_shift_cover_labels`)."""
    if new_vcap <= old_vcap:
        return state
    tch = np.asarray(state["touched"])
    new_lab = _shift_cover_labels(np.asarray(state["labels"]), old_vcap, new_vcap)
    new_tch = np.zeros(2 * new_vcap, dtype=bool)
    new_tch[:old_vcap] = tch[:old_vcap]
    new_tch[new_vcap : new_vcap + old_vcap] = tch[old_vcap:]
    return {"labels": jnp.asarray(new_lab), "touched": jnp.asarray(new_tch)}


#: jitted cover window steps, keyed (tcap, wcap, vcap); bounded FIFO
_COVER_STEP_CACHE: dict = {}
_COVER_STEP_CACHE_MAX = 32

_I32_MAX = np.iinfo(np.int32).max


def _cover_step_fn(tcap: int, wcap: int, vcap: int):
    """Window-local signed-cover step (round 5): the forest CC step
    (``summaries/forest.py``) over the 2*vcap cover id space, plus the
    bipartiteness conflict latch.

    Layout: the touched bucket holds the window's base touched set twice
    — lane i is cover node (t_i, +) = t_i and lane i + tcap is
    (t_i, -) = t_i + vcap — so a lane's sibling is at a fixed offset.
    CONFLICT COMPLETENESS: a new odd cycle means some vertex's two cover
    nodes connect THIS window; the merged cover component is then
    sign-symmetric, so every touched member's sibling lies in the same
    component — checking ``final_root[i] == final_root[i + tcap]`` over
    the touched lanes alone misses nothing. The latch carries on device
    (monotone OR), so the producer loop stays zero-D2H.
    """
    key = (tcap, wcap, vcap)
    fn = _COVER_STEP_CACHE.get(key)
    if fn is not None:
        return fn

    tcap2, vcap2 = 2 * tcap, 2 * vcap

    def step(canon, failed, tid, tmask, lu, lv, emask):
        # cover touched bucket + cover edges, derived in-graph from the
        # base prep (no extra host pass): (u,+)~(v,-) and (u,-)~(v,+).
        # UNLIKE the plain CC forest step, pad rows need a real mask: a
        # pad (0,0) is a harmless self-loop in base space but maps to
        # (0,+)~(0,-) in the cover — a fabricated odd cycle.
        tid2 = jnp.concatenate([tid, tid + vcap])
        tmask2 = jnp.concatenate([tmask, tmask])
        lu2 = jnp.concatenate([lu, lu + tcap])
        lv2 = jnp.concatenate([lv + tcap, lv])
        emask2 = jnp.concatenate([emask, emask])
        r, v2, key_, iota = chase_and_group(canon, tid2, tmask2, tcap2, vcap2)
        u = jnp.concatenate([lu2, iota])
        w = jnp.concatenate([lv2, v2])
        m = jnp.concatenate([emask2, jnp.ones(tcap2, bool)])
        local = _propagate(iota, u, w, m)
        canon, nr = commit_roots(
            canon, local, key_, r, tid2, tmask2, tcap2, vcap2
        )
        # sibling conflict over the touched lanes (see docstring)
        conflict = jnp.any(
            tmask & (nr[:tcap] == nr[tcap:])
        )
        return canon, failed | conflict

    fn = jax.jit(step)
    if len(_COVER_STEP_CACHE) >= _COVER_STEP_CACHE_MAX:
        _COVER_STEP_CACHE.pop(next(iter(_COVER_STEP_CACHE)))
    _COVER_STEP_CACHE[key] = fn
    return fn


def cover_forest_window(canon, failed, src_h, dst_h, vcap: int, prep):
    """Fold one window (host base columns) into the cover forest.
    Returns ``(canon, failed, base_touched_ids)``."""
    n = len(src_h)
    if n == 0:
        return canon, failed, np.zeros(0, np.int32)
    tids, tcap, wcap, tid, tmask, lu, lv = pad_window(
        prep, src_h, dst_h, vcap
    )
    emask = np.zeros(wcap, bool)
    emask[:n] = True
    step = _cover_step_fn(tcap, wcap, vcap)
    canon, failed = step(
        canon, failed,
        jnp.asarray(tid), jnp.asarray(tmask),
        jnp.asarray(lu), jnp.asarray(lv), jnp.asarray(emask),
    )
    return canon, failed, tids


def _cover_superbatch_fn(tcap: int, wcap: int, vcap: int, k: int):
    """K cover window-steps fused into one jitted dispatch, GROUP-LOCAL —
    the signed-cover analog of ``forest._forest_superbatch_fn`` (the
    bipartiteness carry's ``GroupFoldable`` kernel):

    1. ONE root chase + same-root grouping over the group's union
       touched set, expanded to BOTH cover halves (lane i = (t_i, +),
       lane i + tcap = (t_i, -)) — one 2*vcap scratch memset per GROUP;
    2. a ``lax.scan`` over the K windows whose carry is the 2*tcap-sized
       local label table plus the failure latch: window k folds its
       cover edges ((u,+)~(v,-), (u,-)~(v,+); pad rows carry a real edge
       mask, the ``_cover_step_fn`` caveat) into the carried table and
       emits its new-root assignment ``nr_k`` PLUS the latch after the
       window (the per-window sibling-conflict check runs over the
       GROUP's touched lanes — sound, because ``nr_k`` equality means
       "same cover component as of window k" for every group-touched
       lane, and complete, because a conflict arising at window k lives
       in a sign-symmetric component whose touched members witness it);
    3. ONE masked scatter pair commits the final assignment.

    Mid-group canons reconstruct lazily from ``(r, nr_k)`` via
    :class:`~gelly_streaming_tpu.summaries.forest.ForestReplay` (the
    cover id space is just a forest of 2*vcap nodes, so the CC replay
    applies verbatim); the input canon is NOT donated — the pre-group
    buffer backs the group's lazy emissions."""
    key = ("superbatch", tcap, wcap, vcap, k)
    fn = _COVER_STEP_CACHE.get(key)
    if fn is not None:
        return fn

    tcap2, vcap2 = 2 * tcap, 2 * vcap

    def step(canon, failed, tid, tmask, lu, lv, emask):
        # cover touched bucket + per-window cover edges, derived
        # in-graph from the base prep (lu/lv/emask are [k, wcap])
        tid2 = jnp.concatenate([tid, tid + vcap])
        tmask2 = jnp.concatenate([tmask, tmask])
        lu2 = jnp.concatenate([lu, lu + tcap], axis=1)
        lv2 = jnp.concatenate([lv + tcap, lv], axis=1)
        emask2 = jnp.concatenate([emask, emask], axis=1)
        r, v2, key_, iota = chase_and_group(canon, tid2, tmask2, tcap2, vcap2)
        # v2 is a depth-1 min-rooted forest encoding the pre-group
        # same-root constraints — already a valid label table seed
        lab0 = v2

        def body(c, xs):
            lab, fail = c
            lu_k, lv_k, em_k = xs
            u = jnp.concatenate([lu_k, iota])
            w = jnp.concatenate([lv_k, lab])
            m = jnp.concatenate([em_k, jnp.ones(tcap2, bool)])
            lab = _propagate(lab, u, w, m)
            minr = jnp.full(tcap2, _I32_MAX, jnp.int32).at[lab].min(key_)
            nr = minr[lab]
            fail = fail | jnp.any(tmask & (nr[:tcap] == nr[tcap:]))
            return (lab, fail), (nr, fail)

        (_lab_end, fail_end), (nr_s, fail_s) = lax.scan(
            body, (lab0, failed), (lu2, lv2, emask2)
        )
        nr_end = nr_s[-1]
        sid_r = jnp.where(tmask2, r, vcap2)
        canon = canon.at[sid_r].set(nr_end, mode="drop")
        tid_s = jnp.where(tmask2, tid2, vcap2)
        canon = canon.at[tid_s].set(nr_end, mode="drop")
        return canon, fail_end, r, nr_s, fail_s

    fn = jax.jit(step)
    if len(_COVER_STEP_CACHE) >= _COVER_STEP_CACHE_MAX:
        _COVER_STEP_CACHE.pop(next(iter(_COVER_STEP_CACHE)))
    _COVER_STEP_CACHE[key] = fn
    return fn


def cover_forest_superbatch(canon, failed, windows, vcap: int, prep):
    """Fold K windows (list of host base ``(src_h, dst_h)`` column
    pairs) into the cover forest as ONE fused group-local dispatch —
    the cover analog of :func:`~gelly_streaming_tpu.summaries.forest.forest_superbatch`,
    sharing its host prep shape: one prep per window for the per-window
    touched ids (the first-seen log advances in window order), one prep
    over the concatenated columns for the group touched set + the
    group-local renumbering.

    Returns ``(new_canon, new_failed, [touched_ids per window], replay,
    fail_stack)`` — ``replay`` is a cover-space
    :class:`~gelly_streaming_tpu.summaries.forest.ForestReplay` for lazy
    mid-group canon reconstruction, ``fail_stack`` the device ``[k]``
    per-window failure latches."""
    from .forest import ForestReplay

    if prep is None:
        raise ValueError(
            "cover_forest_superbatch requires a per-stream WindowPrep "
            "(see forest_window)"
        )
    k = len(windows)
    _e = np.zeros(0, np.int32)
    win_tids = [
        prep.prep(s, d, vcap)[0] if len(s) else _e for s, d in windows
    ]
    src_g = np.concatenate([s for s, _ in windows]) if k else _e
    dst_g = np.concatenate([d for _, d in windows]) if k else _e
    if len(src_g):
        tids_g, lu_all, lv_all = prep.prep(src_g, dst_g, vcap)
    else:
        tids_g, lu_all, lv_all = _e, _e, _e
    n_max = max((len(s) for s, _ in windows), default=0)
    tcap = bucket_capacity(len(tids_g), minimum=8)
    wcap = bucket_capacity(n_max, minimum=8)
    t = len(tids_g)
    tid = np.zeros(tcap, np.int32)
    tid[:t] = tids_g
    tmask = np.zeros(tcap, bool)
    tmask[:t] = True
    lu = np.zeros((k, wcap), np.int32)
    lv = np.zeros((k, wcap), np.int32)
    emask = np.zeros((k, wcap), bool)
    off = 0
    for i, (s, _) in enumerate(windows):
        n = len(s)
        lu[i, :n] = lu_all[off:off + n]
        lv[i, :n] = lv_all[off:off + n]
        emask[i, :n] = True
        off += n
    step = _cover_superbatch_fn(tcap, wcap, vcap, k)
    new_canon, new_failed, r_dev, nr_s, fail_s = step(
        canon, failed,
        jnp.asarray(tid), jnp.asarray(tmask),
        jnp.asarray(lu), jnp.asarray(lv), jnp.asarray(emask),
    )
    # the replay works in the 2*vcap cover id space: both cover halves
    # of the touched bucket, the chased old roots, the per-window
    # assignments — exactly the CC replay's contract
    tid2 = np.concatenate([tid, tid + vcap])
    tmask2 = np.concatenate([tmask, tmask])
    replay = ForestReplay(canon, tid2, tmask2, r_dev, nr_s)
    return new_canon, new_failed, win_tids, replay, fail_s


def cover_grow_forest(canon, old_vcap: int, new_vcap: int):
    """Re-index the cover forest when the vertex capacity bucket grows
    (one host rebuild per pow2 growth event, same cost shape and SAME
    rule as the dense ``cover_grow`` — see :func:`_shift_cover_labels`;
    a pointer forest re-indexes exactly like flat labels)."""
    if new_vcap <= old_vcap:
        return canon
    return jnp.asarray(
        _shift_cover_labels(np.asarray(canon), old_vcap, new_vcap)
    )


class Candidates:
    """Host emission object with reference-format string output.

    ``success`` False means an odd cycle was found; the map is then empty
    (``Candidates.fail``, ``Candidates.java:194-196``). On success the map is
    component -> {vertex: (vertex, sign)} with the component keyed by its
    smallest raw vertex id, that root colored ``true``, and every other
    vertex's sign = (same cover side as the root).
    """

    def __init__(self, success=None, components=None, *, _lazy=None):
        self._success = success
        self._components = components
        # (canon_dev | (replay, window_k, fail_stack), failed_dev,
        # touch_log, count, vcap, vdict): forest-carry emission — one
        # device read + host canonicalization on first access, so
        # unread windows cost nothing. The replay form is the
        # superbatched carry's mid-group view (from_forest_replay).
        self._lazy = _lazy

    def _mat(self) -> None:
        if self._lazy is None:
            return
        from .forest import resolve_flat_host

        canon, failed, log, count, vcap, vdict = self._lazy
        if isinstance(canon, tuple):
            # superbatch replay: reconstruct this window's cover canon
            # from the group's delta stack, verdict from the stacked
            # per-window latch (one device read each, on first access)
            replay, kk, fail_s = canon
            self._lazy = None
            if bool(np.asarray(fail_s[kk])):
                self._success, self._components = False, {}
                return
            lab = resolve_flat_host(replay.canon_np(kk))
        else:
            lab_np, failed_np = jax.device_get((canon, failed))
            self._lazy = None
            if bool(failed_np):
                self._success, self._components = False, {}
                return
            lab = resolve_flat_host(np.asarray(lab_np))
        # the log holds BASE ids only (< vcap at snapshot time); the
        # negative cover half derives as base + vcap, and from_cover only
        # reads the base half of the mask — so a dict that grew past the
        # snapshot's vcap cannot push ids into the negative half (a held
        # emission stays a valid snapshot)
        touched = np.zeros(2 * vcap, bool)
        touched[np.asarray(log.ids[:count])] = True
        c = Candidates.from_cover(
            {"labels": lab, "touched": touched}, vcap, vdict
        )
        self._success, self._components = c.success, c.components

    @property
    def success(self) -> bool:
        self._mat()
        return self._success

    @property
    def components(self) -> Dict[int, Dict[int, bool]]:
        self._mat()
        return self._components

    @staticmethod
    def from_forest(canon, failed, log, count, vcap, vdict) -> "Candidates":
        return Candidates(_lazy=(canon, failed, log, count, vcap, vdict))

    @staticmethod
    def from_forest_replay(replay, k, fail_stack, log, count, vcap,
                           vdict) -> "Candidates":
        """Lazy mid-group emission for the superbatched cover carry
        (:func:`cover_forest_superbatch`): window ``k``'s cover canon
        reconstructs from the group ``replay`` on first read, its
        verdict from the stacked per-window latch ``fail_stack[k]``."""
        return Candidates(
            _lazy=((replay, k, fail_stack), None, log, count, vcap, vdict)
        )

    def __bool__(self) -> bool:
        """Truthiness == the bipartiteness verdict (``success``): a
        failed check printing ``(false,{})`` must not read as truthy
        through Python's default object truthiness."""
        return self.success

    @staticmethod
    def from_cover(state: Dict[str, jax.Array], vcap: int, vdict) -> "Candidates":
        labels = np.asarray(state["labels"])
        touched = np.asarray(state["touched"])
        n = len(vdict)
        seen = np.nonzero(touched[:n])[0]
        pos = labels[seen]
        neg = labels[seen + vcap]
        if np.any(pos == neg):
            return Candidates(False, {})
        # Base component id: the min cover label of the pair identifies the
        # base component (each base component owns exactly 2 cover comps).
        base = np.minimum(pos, neg)
        comps: Dict[int, Dict[int, bool]] = {}
        for b in np.unique(base):
            members = seen[base == b]
            raws = np.asarray([vdict.decode_one(int(c)) for c in members])
            order = np.argsort(raws)
            members, raws = members[order], raws[order]
            root = members[0]  # min raw id
            root_side = labels[root]
            signs = labels[members] == root_side
            comps[int(raws[0])] = {
                int(r): bool(s) for r, s in zip(raws.tolist(), signs.tolist())
            }
        return Candidates(True, comps)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Candidates)
            and self.success == other.success
            and self.components == other.components
        )

    def __str__(self) -> str:
        if not self.success:
            return "(false,{})"
        outer = ", ".join(
            "%d={%s}"
            % (
                comp,
                ", ".join(
                    "%d=(%d,%s)" % (v, v, "true" if s else "false")
                    for v, s in sorted(vs.items())
                ),
            )
            for comp, vs in sorted(self.components.items())
        )
        return "(true,{%s})" % outer

    __repr__ = __str__
