"""Bipartiteness state: signed double cover over dense labels.

The reference tracks 2-colored candidate components in a nested
TreeMap structure with sign-flipping merges and a global failure latch
(``summaries/Candidates.java:27-197``). SURVEY.md §7 replaces the whole
structure with a classic reduction: run connected components on the *signed
double cover* — every vertex v becomes two cover nodes (v,+) and (v,-), and
every edge (u,v) becomes cover edges (u,+)-(v,-) and (u,-)-(v,+). The graph
is bipartite iff no vertex's two cover nodes land in the same component.
That turns all of ``Candidates``' pointer logic into the same dense label
kernels CC uses (``summaries/labels.py``), sharing its collectives.

Layout: cover node (v,+) = index v, (v,-) = index v + vcap, in a label table
of size 2*vcap.

:class:`Candidates` is the host-side emission object, reproducing the
reference's output format byte-for-byte: ``(true,{1={1=(1,true), ...}})`` /
``(false,{})`` (golden strings in ``BipartitenessCheckTest.java:19-21`` and
``NonBipartitnessCheckTest.java:19-20``).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .labels import _propagate, init_labels


def init_cover(vcap: int) -> Dict[str, jax.Array]:
    """Fresh signed-double-cover label state (2*vcap cover nodes)."""
    return init_labels(2 * vcap)


def cover_fold(
    state: Dict[str, jax.Array],
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    vcap: int,
) -> Dict[str, jax.Array]:
    """Fold a window's edges into the cover labels.

    Edge (u,v) adds cover constraints (u,+)~(v,-) and (u,-)~(v,+)
    — the dense replacement for ``Candidates.add`` / ``merge``
    (``Candidates.java:52-139``).
    """
    u = jnp.concatenate([src, src + vcap])
    w = jnp.concatenate([dst + vcap, dst])
    m = jnp.concatenate([mask, mask])
    labels = _propagate(state["labels"], u, w, m)
    touched = state["touched"].at[src].max(mask).at[dst].max(mask)
    return {"labels": labels, "touched": touched}


def cover_grow(state: Dict[str, jax.Array], old_vcap: int, new_vcap: int) -> Dict[str, jax.Array]:
    """Re-index the cover when the vertex capacity bucket grows.

    Cover node (v,-) moves from v+old_vcap to v+new_vcap, and label *values*
    pointing into the negative half must shift by the same amount.
    """
    if new_vcap <= old_vcap:
        return state
    lab = np.asarray(state["labels"])
    tch = np.asarray(state["touched"])
    new_lab = np.arange(2 * new_vcap, dtype=np.int32)
    new_tch = np.zeros(2 * new_vcap, dtype=bool)
    shifted = np.where(lab >= old_vcap, lab - old_vcap + new_vcap, lab)
    new_lab[:old_vcap] = shifted[:old_vcap]
    new_lab[new_vcap : new_vcap + old_vcap] = shifted[old_vcap:]
    new_tch[:old_vcap] = tch[:old_vcap]
    new_tch[new_vcap : new_vcap + old_vcap] = tch[old_vcap:]
    return {"labels": jnp.asarray(new_lab), "touched": jnp.asarray(new_tch)}


class Candidates:
    """Host emission object with reference-format string output.

    ``success`` False means an odd cycle was found; the map is then empty
    (``Candidates.fail``, ``Candidates.java:194-196``). On success the map is
    component -> {vertex: (vertex, sign)} with the component keyed by its
    smallest raw vertex id, that root colored ``true``, and every other
    vertex's sign = (same cover side as the root).
    """

    def __init__(self, success: bool, components: Dict[int, Dict[int, bool]]):
        self.success = success
        self.components = components

    def __bool__(self) -> bool:
        """Truthiness == the bipartiteness verdict (``success``): a
        failed check printing ``(false,{})`` must not read as truthy
        through Python's default object truthiness."""
        return self.success

    @staticmethod
    def from_cover(state: Dict[str, jax.Array], vcap: int, vdict) -> "Candidates":
        labels = np.asarray(state["labels"])
        touched = np.asarray(state["touched"])
        n = len(vdict)
        seen = np.nonzero(touched[:n])[0]
        pos = labels[seen]
        neg = labels[seen + vcap]
        if np.any(pos == neg):
            return Candidates(False, {})
        # Base component id: the min cover label of the pair identifies the
        # base component (each base component owns exactly 2 cover comps).
        base = np.minimum(pos, neg)
        comps: Dict[int, Dict[int, bool]] = {}
        for b in np.unique(base):
            members = seen[base == b]
            raws = np.asarray([vdict.decode_one(int(c)) for c in members])
            order = np.argsort(raws)
            members, raws = members[order], raws[order]
            root = members[0]  # min raw id
            root_side = labels[root]
            signs = labels[members] == root_side
            comps[int(raws[0])] = {
                int(r): bool(s) for r, s in zip(raws.tolist(), signs.tolist())
            }
        return Candidates(True, comps)

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Candidates)
            and self.success == other.success
            and self.components == other.components
        )

    def __str__(self) -> str:
        if not self.success:
            return "(false,{})"
        outer = ", ".join(
            "%d={%s}"
            % (
                comp,
                ", ".join(
                    "%d=(%d,%s)" % (v, v, "true" if s else "false")
                    for v, s in sorted(vs.items())
                ),
            )
            for comp, vs in sorted(self.components.items())
        )
        return "(true,{%s})" % outer

    __repr__ = __str__
