"""Dense label propagation: the device-side union-find replacement.

SURVEY.md §7's core bet: the reference's ``DisjointSet`` pointer-chasing
(``summaries/DisjointSet.java``) densifies into an int32 ``labels[V]`` array
where ``labels[v]`` is the (compact) index of the smallest vertex known
reachable from ``v``. Per window, min-label propagation with pointer jumping
runs to fixpoint inside a ``lax.while_loop`` — the Shiloach-Vishkin-style
hook-and-shortcut scheme that maps onto gathers/scatter-mins the TPU
executes as dense vector ops.

Key kernels:

- :func:`cc_fold` — fold one EdgeBlock into a label table (the ``UpdateCC``
  analog, ``library/ConnectedComponents.java:83-86``).
- :func:`label_combine` — merge two label tables. NOTE: elementwise min is
  NOT sufficient (a link recorded in only one table can be dropped); the
  correct merge treats both tables as pointer graphs — edges (v, a[v]) and
  (v, b[v]) — and re-runs the fixpoint (the ``CombineCC``/``DisjointSet.
  merge`` analog).
- :func:`grow_labels` — extend a table when the vertex dictionary grows.

All kernels are jit-compatible pure functions over (labels, touched) pairs;
``touched`` tracks which vertices have appeared in any edge so emission can
skip never-seen singletons (matching the reference, whose DisjointSet only
contains vertices from processed edges).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

_I32_MAX = jnp.iinfo(jnp.int32).max


def init_labels(vcap: int) -> Dict[str, jax.Array]:
    """Fresh state: every vertex its own component, nothing touched."""
    return {
        "labels": jnp.arange(vcap, dtype=jnp.int32),
        "touched": jnp.zeros(vcap, dtype=bool),
    }


def _propagate(labels: jax.Array, u: jax.Array, v: jax.Array, mask: jax.Array) -> jax.Array:
    """Min-label fixpoint over the constraint edges (u[i] ~ v[i] where mask).

    Each iteration: hook (scatter-min of min(label_u, label_v) onto both
    endpoints) + shortcut (pointer jump ``labels[labels]``), until no label
    changes. Padding rows carry +inf updates (no-ops under min).
    """

    def body(state):
        lab, _ = state
        lu = lab[u]
        lv = lab[v]
        m = jnp.where(mask, jnp.minimum(lu, lv), _I32_MAX)
        new = lab.at[u].min(m).at[v].min(m)
        new = new[new]  # shortcut: one round of pointer jumping
        return new, jnp.any(new != lab)

    def cond(state):
        return state[1]

    labels, _ = lax.while_loop(cond, body, (labels, jnp.bool_(True)))
    return labels


def cc_fold(state: Dict[str, jax.Array], src: jax.Array, dst: jax.Array, mask: jax.Array) -> Dict[str, jax.Array]:
    """Fold one window's edges into the label table (per-shard update)."""
    labels = _propagate(state["labels"], src, dst, mask)
    ones = mask
    touched = state["touched"].at[src].max(ones).at[dst].max(ones)
    return {"labels": labels, "touched": touched}


def label_combine(a: Dict[str, jax.Array], b: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
    """Merge two label tables into the labels of the union graph.

    Correctness: the union's constraints are exactly the pointer edges
    (v, a.labels[v]) and (v, b.labels[v]); re-running the fixpoint over those
    2V edges yields CC of the union. (Plain elementwise min would lose links:
    with a = [.., 5~3], b = [.., 5~1], min drops the 3~5 link.)
    """
    la, lb = a["labels"], b["labels"]
    V = la.shape[0]
    iota = jnp.arange(V, dtype=jnp.int32)
    u = jnp.concatenate([iota, iota])
    w = jnp.concatenate([la, lb])
    labels = _propagate(jnp.minimum(la, lb), u, w, jnp.ones(2 * V, bool))
    return {"labels": labels, "touched": a["touched"] | b["touched"]}


def grow_labels(state: Dict[str, jax.Array], new_vcap: int) -> Dict[str, jax.Array]:
    """Extend the table when the vertex dictionary bucket grows."""
    old = state["labels"].shape[0]
    if new_vcap <= old:
        return state
    ext = jnp.arange(old, new_vcap, dtype=jnp.int32)
    return {
        "labels": jnp.concatenate([state["labels"], ext]),
        "touched": jnp.concatenate([state["touched"], jnp.zeros(new_vcap - old, bool)]),
    }


# --------------------------------------------------------------------------- #
# Host-side emission
# --------------------------------------------------------------------------- #
class Components:
    """Host view of a label table: the TPU stand-in for the emitted
    ``DisjointSet`` (``library/ConnectedComponents.java:41``).

    ``components`` maps the component's representative (min *raw* vertex id)
    to the sorted raw member list. ``__str__`` matches the Java map format
    the reference's test parser reads (``DisjointSet.java:139-153``).
    """

    def __init__(self, components: Optional[Dict[int, List[int]]] = None, *,
                 _lazy=None, _lazy_forest=None, _lazy_replay=None):
        self._components = components
        self._lazy = _lazy  # (labels_dev, touched_dev, n, vdict)
        # (canon_dev, touch_log, count, vdict): forest-carry emission —
        # canon chains resolve on host at materialization; the touched
        # set is the first `count` entries of the append-only host log
        self._lazy_forest = _lazy_forest
        # (ForestReplay, window_index, touch_log, count, vdict):
        # superbatch emission — the mid-group canon reconstructs from
        # the group's delta stack on first read (forest.ForestReplay)
        self._lazy_replay = _lazy_replay

    @property
    def components(self) -> Dict[int, List[int]]:
        """Materialized (root -> sorted members) map; device sync + host
        grouping happen on first access, so un-inspected per-window
        emissions cost nothing (windows pipeline on device)."""
        if self._components is None:
            if self._lazy_replay is not None:
                from .forest import resolve_flat_host

                replay, win, log, count, vdict = self._lazy_replay
                labels = resolve_flat_host(replay.canon_np(win))
                idx = np.sort(log.ids[:count])
            elif self._lazy_forest is not None:
                from .forest import resolve_flat_host

                canon_dev, log, count, vdict = self._lazy_forest
                labels = resolve_flat_host(np.asarray(canon_dev))
                idx = np.sort(log.ids[:count])
            else:
                labels_dev, touched_dev, n, vdict = self._lazy
                labels = np.asarray(labels_dev)
                touched = np.asarray(touched_dev)
                if n is None:
                    # deferred dict-size read (device dicts: len() syncs
                    # the pipeline, so it must happen at materialization,
                    # not at emission). Safe because `touched` was
                    # snapshotted with the labels: vertices first seen
                    # after this window are False there, so a larger n
                    # admits nothing extra.
                    n = len(vdict)
                idx = np.nonzero(touched[: min(n, touched.shape[0])])[0]
            lab = labels[idx]
            raw = vdict.decode(idx)
            # one (label, raw) lexsort: every component's member slice
            # comes out ascending, so the root is its first element and
            # no per-component python sort runs (a scale-23 giant
            # component paid seconds in sorted() per materialization)
            order = np.lexsort((raw, lab))
            lab_s = lab[order]
            raw_s = raw[order]
            _, starts = np.unique(lab_s, return_index=True)
            self._components = {}
            for members in np.split(raw_s, starts[1:]):
                ms = members.tolist()
                self._components[ms[0]] = ms
        return self._components

    @staticmethod
    def from_labels(state: Dict[str, jax.Array], vdict) -> "Components":
        """Lazy view over the label table: defers BOTH the device sync and
        the dict-size read to materialization (``len()`` on a device-
        resident dict would sync the pipeline every window; the snapshotted
        ``touched`` mask makes the later, larger size equivalent)."""
        return Components(
            _lazy=(state["labels"], state["touched"], None, vdict)
        )

    @staticmethod
    def from_forest(canon, log, vdict) -> "Components":
        """Lazy view over a forest carry (``summaries/forest.py``): the
        canon snapshot is this window's immutable device buffer; the
        touched set snapshots as a COUNT into the append-only host log."""
        return Components(_lazy_forest=(canon, log, log.count, vdict))

    @staticmethod
    def from_forest_replay(replay, win: int, log, count: int,
                           vdict) -> "Components":
        """Lazy view over window ``win`` of a forest SUPERBATCH
        (``forest.ForestReplay``): the mid-group canon exists only as
        the group's delta stack and reconstructs on first read; the
        touched set snapshots as the caller-recorded per-window COUNT
        into the append-only host log (the log advances past this
        window before the group's emissions surface)."""
        return Components(_lazy_replay=(replay, win, log, count, vdict))

    def num_components(self) -> int:
        return len(self.components)

    def component_sets(self) -> List[frozenset]:
        return [frozenset(m) for m in self.components.values()]

    def __eq__(self, other) -> bool:
        return isinstance(other, Components) and self.components == other.components

    def __str__(self) -> str:
        inner = ", ".join(
            f"{root}={members}" for root, members in sorted(self.components.items())
        )
        return "{" + inner + "}"

    __repr__ = __str__
