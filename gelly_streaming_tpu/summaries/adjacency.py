"""AdjacencyListGraph: host adjacency + hop-bounded BFS (the spanner oracle).

Port-parity twin of ``summaries/AdjacencyListGraph.java:29-140``: an
undirected adjacency map with a level-tagged bounded BFS used by the
k-spanner's distance test. The spanner's per-edge decision ("is there
already a path of <= k hops?") is inherently sequential in arrival order, and
the reference runs it inside a parallelism-bound window fold — SURVEY.md §7
keeps it host-side (build order step 5), with the same API, so the algorithm
slots into the aggregation engine as a host-state summary.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Set


class AdjacencyListGraph:
    """Undirected adjacency map + bounded BFS (``AdjacencyListGraph.java``)."""

    def __init__(self) -> None:
        self.adj: Dict[int, Set[int]] = {}

    def add_edge(self, u: int, v: int) -> None:
        """Insert undirected (both directions — ``AdjacencyListGraph.java:46-67``)."""
        self.adj.setdefault(u, set()).add(v)
        self.adj.setdefault(v, set()).add(u)

    def has_edge(self, u: int, v: int) -> bool:
        return v in self.adj.get(u, ())

    def bounded_bfs(self, src: int, trg: int, k: int) -> bool:
        """True iff a path src->trg of at most k hops exists
        (``AdjacencyListGraph.java:79-116``)."""
        if src not in self.adj or trg not in self.adj:
            return False
        if src == trg:
            return True
        q: deque = deque([(src, 0)])
        visited = {src}
        while q:
            node, depth = q.popleft()
            if depth >= k:
                continue
            for nbr in self.adj.get(node, ()):
                if nbr == trg:
                    return True
                if nbr not in visited:
                    visited.add(nbr)
                    q.append((nbr, depth + 1))
        return False

    def edges(self):
        """Yield each undirected edge once (u <= v)."""
        for u, nbrs in self.adj.items():
            for v in nbrs:
                if u <= v:
                    yield u, v

    def num_edges(self) -> int:
        return sum(1 for _ in self.edges())

    def copy(self) -> "AdjacencyListGraph":
        g = AdjacencyListGraph()
        g.adj = {u: set(nbrs) for u, nbrs in self.adj.items()}
        return g

    def reset(self) -> None:
        self.adj.clear()
