"""The group-fold execution contract: ANY carry can declare a fused path.

PR 2's superbatch work flattened the small-window latency cliff (208k ->
5.99M eps at 1024-edge windows) but wired the fused K-window paths ad
hoc: the engine scan lives in ``SummaryAggregation._superbatch_step``,
the CC carries fork their own run loop (``forest_superbatch`` /
``cuf_fold_group``), and every other workload — ``IncrementalPageRank``'s
custom loop, the bipartiteness cover carry — stayed on the per-window
cliff. This module extracts the contract those paths implement into ONE
declared protocol, so a library algorithm gets the superbatch path by
declaring a fold, not by forking the engine.

The contract (:class:`GroupFoldable`):

1. **Pack once.** A :class:`~gelly_streaming_tpu.core.window.SuperbatchGroup`
   arrives with K windows' host column views from ONE group encode
   (``Windower.pack_window_cols`` — zero per-window device work on the
   ingest fast path). The fold consumes the group, never re-packs.
2. **Fold fused.** :meth:`GroupFoldable.fold_group` folds the whole
   group as ONE dispatch — a ``lax.scan`` over stacked columns (the
   engine, PageRank, the cover carry) or one native call (the host CC
   union-find) — and yields exactly ``len(group)`` per-window emissions
   whose VALUES are identical to the per-window path's.
3. **Reconstruct lazily.** Mid-group carry states exist only as the
   group's delta stack; an emission that is actually read rebuilds its
   window's view on first access (``ForestReplay`` / ``MirrorReplay`` /
   stacked-row slices via ``emission.iter_unstacked``). Unread windows
   cost nothing.
4. **Checkpoint on boundaries.** The carried summary is observable only
   between groups; :meth:`GroupFoldable.checkpoint_granularity` reports
   the effective stride so barrier drivers
   (:class:`~gelly_streaming_tpu.aggregate.autockpt.AutoCheckpoint`)
   align — a mid-group snapshot can never pair an end-of-group summary
   with a mid-group window count.

:func:`drive_group_folded` is THE superbatch drive loop shared by every
implementation (the engine, the CC mixin, bipartiteness, PageRank):
groups come from the stream's packer and are prefetched one group ahead
so the host assembles group N+1 while the device folds N.

:func:`verify_group_fold` is the reusable conformance check — a new
``GroupFoldable`` carry pins its per-window/group value identity with
one call (``tests/test_groupfold.py`` uses it for all four
implementations).
"""

from __future__ import annotations

import abc
from typing import Any, Callable, Iterator, Optional

#: groups prefetched ahead of the fold — the group-granular pipeline
#: coupling every drive loop uses (host assembly of group N+1 overlaps
#: the fold of N; deeper would only hold more packed columns live)
GROUP_PREFETCH_DEPTH = 2


class GroupFoldable(abc.ABC):
    """A workload whose carry declares a fused K-window group path.

    Implementations fold one
    :class:`~gelly_streaming_tpu.core.window.SuperbatchGroup` per
    dispatch and yield per-window emissions that are VALUE-IDENTICAL to
    their per-window path (the module-doc contract). The protocol is
    deliberately engine-agnostic: ``SummaryAggregation`` subclasses and
    standalone workloads (``IncrementalPageRank``) implement it alike.
    """

    @abc.abstractmethod
    def fold_group(self, group) -> Iterator[Any]:
        """Fold one supported group as ONE fused dispatch; yield its
        ``len(group)`` per-window emissions (lazy mid-group views)."""

    def group_supported(self, group) -> bool:
        """Whether THIS group can take the fused path. Implementations
        that depend on the packer's host column views or its seen-count
        record override this (an unsupported group runs through
        :meth:`fold_group_fallback` — correctness never depends on how
        a group was packed)."""
        return True

    def fold_group_fallback(self, group) -> Iterator[Any]:
        """Per-window fold of an unsupported group. Only reached when
        :meth:`group_supported` can return False; the default keeps the
        contract loud for implementations that claimed universal
        support."""
        raise NotImplementedError(
            f"{type(self).__name__}.group_supported rejected a group "
            "but no fold_group_fallback is implemented"
        )

    def checkpoint_granularity(self) -> int:
        """Window stride at which the carried state is observable: the
        group size where the run loop folds fused, 1 where it opts out.
        Subclasses whose run loop opts out under extra conditions
        (transient CC/bipartiteness) override this."""
        return int(getattr(self, "superbatch", 1) or 1)

    #: cumulative windows of every group whose fold has STARTED in the
    #: current :func:`drive_group_folded` run (None outside one) — the
    #: carried state transitions to end-of-group at the group's FIRST
    #: emission, so a barrier is safe exactly when the consumer's yield
    #: count equals this watermark
    _gf_folded: Optional[int] = None

    def checkpoint_aligned(self, done_windows: int) -> bool:
        """Whether a checkpoint barrier may land after ``done_windows``
        emissions of the CURRENT run (counted from the run's start —
        the resume offset is the caller's). Inside a group-folded run
        the answer is exact per group boundary — including variable
        tiling under ``superbatch="auto"`` and the final partial group
        — because the drive loop maintains :attr:`_gf_folded`; outside
        one it falls back to the static ``checkpoint_granularity``
        modulo rule. :class:`~gelly_streaming_tpu.aggregate.autockpt.AutoCheckpoint`
        consults this instead of the modulo rule when the work offers
        it."""
        folded = self._gf_folded
        if folded is not None:
            return done_windows == folded
        return done_windows % max(1, self.checkpoint_granularity()) == 0


def drive_group_folded(workload: GroupFoldable, stream, k: int,
                       prefetch_groups: int = GROUP_PREFETCH_DEPTH,
                       controller=None) -> Iterator[Any]:
    """THE superbatch drive loop: pack K windows per group through the
    stream's packer (:func:`~gelly_streaming_tpu.core.window.iter_superbatches`
    — zero per-window device assembly on the windower fast path),
    prefetch ahead, and delegate each group to the workload's declared
    fold. Shared by every :class:`GroupFoldable` so the drive semantics
    (group boundaries, prefetch coupling, fallback routing) cannot drift
    between implementations.

    ``controller`` (a :class:`~gelly_streaming_tpu.control.ControlPlane`
    or bare :class:`~gelly_streaming_tpu.control.AutoK`) switches the
    loop adaptive: groups come from the DYNAMIC packer with the
    controller's ``current_k`` consulted at every group boundary, each
    folded group's wall seconds are tapped back
    (:meth:`~gelly_streaming_tpu.control.AutoK.tap_group` — includes
    the consumer's emission handling, i.e. the true pipeline
    throughput), and the group prefetch runs under the controller's
    :class:`~gelly_streaming_tpu.control.PrefetchTuner` when it carries
    one. Retunes land a prefetch-depth of groups late (the packer runs
    ahead); the tuner attributes measurements by each group's actual
    window count, so the lag costs convergence time, never correctness.
    """
    import time as _time

    from ..core.pipeline import prefetch
    from ..core.window import iter_superbatches, iter_superbatches_dynamic

    autok = getattr(controller, "autok", controller)
    tuner = getattr(controller, "prefetch", None)
    if autok is None:
        groups = iter_superbatches(stream, k)
    else:
        groups = iter_superbatches_dynamic(stream, autok.current_k)
    if tuner is None:
        prefetched = prefetch(groups, prefetch_groups)
    else:
        prefetched = prefetch(groups, tuner.depth_max, tuner=tuner)
    if autok is not None:
        # drain any foreign-time credit a previous run on this thread
        # accrued but never consumed (e.g. an oracle run without a
        # controller) so it cannot deflate this run's first tap
        from ..control.signals import take_excluded_s

        take_excluded_s()
    workload._gf_folded = 0
    try:
        for group in prefetched:
            workload._gf_folded += len(group)
            t0 = _time.perf_counter() if autok is not None else 0.0
            if workload.group_supported(group):
                yield from workload.fold_group(group)
            else:
                yield from workload.fold_group_fallback(group)
            if autok is not None:
                k_next = autok.tap_group(
                    len(group), group_edge_count(group),
                    _time.perf_counter() - t0,
                )
                # mirror the live K onto the workload: consumers that
                # read `superbatch` (checkpoint drivers rounding their
                # cadence, bench evidence) see the operating point,
                # while barrier alignment itself rides the exact
                # _gf_folded watermark
                if getattr(workload, "superbatch", None) is not None:
                    workload.superbatch = k_next
    finally:
        # the watermark is only meaningful INSIDE this run: a later
        # run of the same object down a per-window path must fall back
        # to the static modulo rule, not compare against a stale total
        workload._gf_folded = None


def group_edge_count(group) -> int:
    """Total edges of a packed group: exact from the host column views,
    the padded block capacities (an upper bound, consistent across
    groups) for device-stacked ones."""
    if group.cols is not None:
        return int(sum(len(c[0]) for c in group.cols))
    blocks = getattr(group, "_blocks", None)
    if blocks:
        return int(sum(int(b.capacity) for b in blocks))
    return 0


def verify_group_fold(
    make_workload: Callable[[int], Any],
    make_stream: Callable[[], Any],
    k: int,
    *,
    normalize: Callable[[Any], Any] = str,
    run: Optional[Callable[[Any, Any], Iterator[Any]]] = None,
) -> list:
    """Reusable protocol-conformance check: the grouped run must be
    emission-for-emission value-identical to the per-window run.

    ``make_workload(superbatch)`` builds a fresh workload;
    ``make_stream()`` a fresh stream over the same source;
    ``normalize(emission)`` maps an emission to a comparable value
    (default ``str`` — materializes lazy emissions); ``run(workload,
    stream)`` overrides how the workload is driven (default
    ``workload.run(stream)``). Raises ``AssertionError`` naming the
    first diverging window; returns the normalized per-window sequence
    so callers can chain further checks."""
    drive = run if run is not None else (lambda w, s: w.run(s))
    base = [normalize(e) for e in drive(make_workload(1), make_stream())]
    got = [normalize(e) for e in drive(make_workload(k), make_stream())]
    if len(got) != len(base):
        raise AssertionError(
            f"group fold (k={k}) yielded {len(got)} emissions, "
            f"per-window yielded {len(base)}"
        )
    for i, (a, b) in enumerate(zip(base, got)):
        if not _values_equal(a, b):
            raise AssertionError(
                f"group fold (k={k}) diverges at window {i}: "
                f"per-window {a!r} != grouped {b!r}"
            )
    return base


def _values_equal(a, b) -> bool:
    import numpy as np

    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        return bool(np.array_equal(np.asarray(a), np.asarray(b)))
    return a == b
