"""Typed engine configuration + CLI parsing (SURVEY.md §5).

The reference's "config system" is per-example positional-arg parsing with
hard-coded defaults (``ConnectedComponentsExample.java:78-102``) and engine
knobs as constructor params (``mergeWindowTime``, ``transientState``, tree
``degree``). SURVEY.md §5: one small typed config object + CLI, nothing
fancier.
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Optional

from ..core.window import CountWindow, EventTimeWindow, WindowPolicy


@dataclasses.dataclass
class EngineConfig:
    """Engine-level knobs, the analogs of the reference's ctor params."""

    #: edges per merge window (CountWindow) — the mergeWindowTime analog
    window_size: int = 1 << 16
    #: event-time window span instead of a count window (when set)
    window_time: Optional[float] = None
    #: reset the running summary after each emission
    #: (``SummaryAggregation.java:113-115``)
    transient_state: bool = False
    #: tree-reduce fan-in, API parity (``SummaryTreeReduce.java:75``)
    tree_degree: int = 2
    #: fixed EdgeBlock capacity override (else power-of-two bucketing)
    capacity: Optional[int] = None
    #: edge-axis shards for the device mesh (None = all devices)
    edge_shards: Optional[int] = None
    #: run the vertex mapping on the accelerator — see
    #: ``datasets.stream_file``. With ``id_bound`` set, the device table
    #: covers the declared dense id space; with ``id_bound=0`` this is the
    #: GENERAL arbitrary-id path (growth mode, exact host-side novelty
    #: tracking, zero device->host reads)
    device_encode: bool = False
    #: raw id-space bound for identity/device vertex mappings (0 = general:
    #: host dictionary, or device growth mode under ``device_encode``)
    id_bound: int = 0

    def window(self, timestamp_fn=None) -> WindowPolicy:
        if self.window_time is not None:
            return EventTimeWindow(self.window_time, timestamp_fn=timestamp_fn)
        return CountWindow(self.window_size)

    def open_stream(self, path: str):
        """``datasets.stream_file`` with this config's ingest knobs."""
        from .. import datasets

        kw = {}
        if self.device_encode:
            kw = dict(
                device_encode=True, min_vertex_capacity=self.id_bound,
                dense_ids=bool(self.id_bound),
            )
        elif self.id_bound:
            kw = dict(vertex_dict=datasets.IdentityDict(self.id_bound))
        return datasets.stream_file(path, window=self.window(), **kw)

    @staticmethod
    def add_args(parser: argparse.ArgumentParser) -> None:
        g = parser.add_argument_group("engine")
        g.add_argument("--window-size", type=int, default=1 << 16)
        g.add_argument("--window-time", type=float, default=None)
        g.add_argument("--transient-state", action="store_true")
        g.add_argument("--tree-degree", type=int, default=2)
        g.add_argument("--capacity", type=int, default=None)
        g.add_argument("--edge-shards", type=int, default=None)
        g.add_argument("--device-encode", action="store_true")
        g.add_argument("--id-bound", type=int, default=0)

    @classmethod
    def from_args(cls, ns: argparse.Namespace) -> "EngineConfig":
        return cls(
            window_size=ns.window_size,
            window_time=ns.window_time,
            transient_state=ns.transient_state,
            tree_degree=ns.tree_degree,
            capacity=ns.capacity,
            edge_shards=ns.edge_shards,
            device_encode=ns.device_encode,
            id_bound=ns.id_bound,
        )
