"""Per-window step timing and device tracing (SURVEY.md §5).

The reference has no profiling beyond ``getNetRuntime()`` printed by one
example (``CentralizedWeightedMatching.java:62-64``); its pom references
measurement jars whose classes don't exist. SURVEY.md §5 directs: plan for
``jax.profiler`` traces + per-window step timing from day one, and keep the
reference's design stance that metrics are ordinary output streams
(``README.md:26-32``).

- :func:`profiled` wraps any per-window emission iterator and yields
  ``(result, WindowStats)`` pairs — the metrics ARE a stream.
- :class:`StreamProfiler` aggregates those stats (edges/sec, p50/p95
  window latency). Since ISSUE 3 it is also a VIEW over the obs metric
  registry: with observability enabled (or a registry passed), every
  recorded window mirrors into ``profiler.window_seconds`` /
  ``profiler.window_edges`` so the same numbers surface through the
  Prometheus/JSONL exporters; percentiles use the repo-wide
  :func:`~gelly_streaming_tpu.obs.registry.nearest_rank` rule.
- :func:`device_trace` wraps ``jax.profiler.trace`` for TensorBoard-
  readable TPU traces.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Iterator, List, NamedTuple, Optional, Tuple

from ..obs import trace as _trace
from ..obs.registry import get_registry, nearest_rank


class WindowStats(NamedTuple):
    """One window's measurements."""

    index: int
    wall_seconds: float
    edges: Optional[int]  # None when the source doesn't expose block sizes


class StreamProfiler:
    """Aggregate window stats; exposes throughput and latency percentiles.

    ``registry`` (optional) pins where mirrored metrics go; by default
    they go to the global obs registry ONLY while observability is
    enabled, so a bare profiler stays a private list like it always was.
    ``name`` prefixes the mirrored instrument names (one profiler per
    pipeline stage stays distinguishable).
    """

    def __init__(self, registry=None, name: str = "profiler"):
        self.stats: List[WindowStats] = []
        self._registry = registry
        self._name = name

    def record(self, s: WindowStats) -> None:
        self.stats.append(s)
        reg = self._registry
        if reg is None and _trace.on():
            reg = get_registry()
        if reg is not None:
            reg.histogram(self._name + ".window_seconds").observe(
                s.wall_seconds
            )
            if s.edges:
                reg.counter(self._name + ".window_edges").inc(s.edges)

    # ------------------------------------------------------------------ #
    def total_edges(self) -> int:
        return sum(s.edges or 0 for s in self.stats)

    def total_seconds(self) -> float:
        return sum(s.wall_seconds for s in self.stats)

    def edges_per_sec(self) -> float:
        t = self.total_seconds()
        return self.total_edges() / t if t > 0 else 0.0

    def latency_percentile(self, q: float) -> float:
        """q in [0, 100]: percentile of per-window wall time (seconds).
        Nearest-rank, via the shared obs helper (previously duplicated
        here and in ``serving/stats._pct``)."""
        return nearest_rank(sorted(s.wall_seconds for s in self.stats), q)

    def summary(self) -> dict:
        return {
            "windows": len(self.stats),
            "edges": self.total_edges(),
            "edges_per_sec": self.edges_per_sec(),
            "p50_window_s": self.latency_percentile(50),
            "p95_window_s": self.latency_percentile(95),
        }


def profiled(
    iterator: Iterator[Any],
    profiler: Optional[StreamProfiler] = None,
    edges_per_window: Optional[Iterator[int]] = None,
) -> Iterator[Tuple[Any, WindowStats]]:
    """Yield ``(result, WindowStats)`` per window of any emission stream.

    Timing covers the work to produce each emission (next() call), i.e. the
    host windowing + device step + host emission — the end-to-end per-window
    latency BASELINE.md's p50 metric asks for.
    """
    prof = profiler if profiler is not None else StreamProfiler()
    idx = 0
    it = iter(iterator)
    sizes = iter(edges_per_window) if edges_per_window is not None else None
    while True:
        t0 = time.perf_counter()
        try:
            result = next(it)
        except StopIteration:
            return
        dt = time.perf_counter() - t0
        n = next(sizes, None) if sizes is not None else None
        stats = WindowStats(idx, dt, n)
        prof.record(stats)
        yield result, stats
        idx += 1


@contextlib.contextmanager
def device_trace(log_dir: str):
    """TensorBoard-readable device trace around a block of stream steps."""
    import jax

    with jax.profiler.trace(log_dir):
        yield


# --------------------------------------------------------------------- #
# Roofline accounting (round-2 verdict #4): every perf claim anchored as
# a fraction of the chip's peak — MFU for MXU-dense paths, fraction of
# HBM bandwidth for memory-bound scatter/gather kernels.
# --------------------------------------------------------------------- #

#: per-generation peaks: (bf16 FLOP/s, HBM bytes/s). Public figures.
_CHIP_PEAKS = {
    "v2": (45e12, 0.7e12),
    "v3": (123e12, 0.9e12),
    "v4": (275e12, 1.2e12),
    "v5e": (197e12, 0.82e12),
    "v5lite": (197e12, 0.82e12),
    "v5p": (459e12, 2.76e12),
    "v6e": (918e12, 1.64e12),
    "cpu": (1e12, 0.1e12),  # nominal; keeps ratios defined off-TPU
}


@functools.lru_cache(maxsize=1)
def _chip_spec_cached() -> dict:
    import jax

    kind = jax.devices()[0].device_kind.lower()
    squashed = kind.replace(" ", "").replace("-", "")  # "v5 lite" -> "v5lite"
    for key, (flops, bw) in sorted(
        _CHIP_PEAKS.items(), key=lambda kv: -len(kv[0])
    ):
        if key in squashed:
            return {"kind": kind, "peak_bf16_flops": flops, "hbm_bytes_s": bw}
    # unknown accelerator: assume a v4-class chip and say so
    return {"kind": kind + " (assumed v4-class)",
            "peak_bf16_flops": 275e12, "hbm_bytes_s": 1.2e12}


def chip_spec() -> dict:
    """Peak numbers for the attached device (fuzzy device_kind match;
    cached — every roofline entry reads it).

    Degrades to the nominal CPU peaks when ``jax.devices()`` itself
    fails (backend down / tunnel gone): a roofline ANNOTATION must never
    crash the measurement it annotates. The failure is recorded in the
    returned ``kind`` and NOT cached, so a recovered backend gets its
    real spec on the next call.
    """
    try:
        return _chip_spec_cached()
    except Exception as e:  # jax.devices() raising = no backend reachable
        flops, bw = _CHIP_PEAKS["cpu"]
        return {
            "kind": f"unavailable (jax.devices failed: {e}); "
                    "assuming nominal cpu peaks",
            "peak_bf16_flops": flops,
            "hbm_bytes_s": bw,
        }


def roofline_entry(
    seconds: float, *, flops: float = 0.0, bytes_moved: float = 0.0,
    model: str = "",
) -> dict:
    """One kernel's achieved rate vs the chip roofline.

    ``flops``/``bytes_moved`` are the caller's ANALYTIC model of the
    kernel's work (the model string documents what was counted); the
    returned percentages are achieved/peak for whichever resources were
    modeled.
    """
    spec = chip_spec()
    out = {"time_ms": seconds * 1e3, "model": model}
    if flops:
        out["gflops_s"] = flops / seconds / 1e9
        out["mfu_pct"] = 100.0 * flops / seconds / spec["peak_bf16_flops"]
    if bytes_moved:
        out["gbytes_s"] = bytes_moved / seconds / 1e9
        out["hbm_pct"] = 100.0 * bytes_moved / seconds / spec["hbm_bytes_s"]
    return out
