"""API-parity analogs of the reference's ``util/`` tuple types.

These are host-side emission/message records. On device their roles are
played by dense arrays (the signed double cover replaces per-record
``SignedVertex`` flows, sampler state vectors replace routed
``SampledEdge``/``TriangleEstimate`` messages); the types remain for users
porting reference code that pattern-matches on them.
"""

from __future__ import annotations

from typing import NamedTuple

from ..core.types import Edge


class SignedVertex(NamedTuple):
    """``util/SignedVertex.java:23-41``: (vertex, sign) with ``reverse()``."""

    vertex: int
    sign: bool

    def reverse(self) -> "SignedVertex":
        return SignedVertex(self.vertex, not self.sign)


class SampledEdge(NamedTuple):
    """``util/SampledEdge.java:26-56``: routed sample message
    (subtask, instance, edge, edgeCount, resample)."""

    subtask: int
    instance: int
    edge: Edge
    edge_count: int
    resample: bool


class TriangleEstimate(NamedTuple):
    """``util/TriangleEstimate.java:25-44``: partial estimator message
    (sourceSubtask, edgeCount, beta)."""

    source: int
    edge_count: int
    beta: int
