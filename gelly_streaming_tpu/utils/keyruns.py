"""Sorted-run key set: amortized-cheap host shadow for growing key sets.

The novelty-shadow pattern ([[novelty-tracked-device-dict]]) keeps an
exact host-side set of canonical int64 keys beside the stream. A single
sorted array + ``np.insert`` per window costs O(total) memcpy per window
— quadratic over the stream, ~13 s of pure memcpy at the 134M-edge
north-star scale. This LSM-style variant (the same scheme
``SimpleEdgeStream.distinct``'s fallback uses inline,
``core/stream.py:315``) keeps O(log N) sorted runs with geometric
merging: amortized O(N log N) total insertion, O(log N) binary-search
probes per lookup batch.
"""

from __future__ import annotations

import numpy as np


class SortedRunSet:
    """Set of int64 keys stored as O(log N) sorted runs."""

    __slots__ = ("_runs", "_n")

    def __init__(self, initial: np.ndarray | None = None):
        self._runs: list = []
        self._n = 0
        if initial is not None and len(initial):
            arr = np.unique(np.asarray(initial, np.int64))
            self._runs.append(arr)
            self._n = len(arr)

    def __len__(self) -> int:
        return self._n

    def contains(self, keys: np.ndarray) -> np.ndarray:
        """Membership mask for ``keys`` (any order, int64)."""
        dup = np.zeros(len(keys), bool)
        for run in self._runs:
            pos = np.searchsorted(run, keys)
            pos = np.minimum(pos, len(run) - 1)
            dup |= run[pos] == keys
        return dup

    def filter_new(self, keys: np.ndarray) -> np.ndarray:
        """``keys`` must be sorted-unique; returns the subset NOT in the
        set (the per-window novelty probe)."""
        if not self._runs or not len(keys):
            return keys
        return keys[~self.contains(keys)]

    def add(self, new_keys: np.ndarray) -> None:
        """Insert sorted-unique keys disjoint from the current content.
        Geometric merge: collapse the newest runs while the last is at
        least half its neighbor — every key is re-merged O(log N) times
        total."""
        if not len(new_keys):
            return
        self._runs.append(np.asarray(new_keys, np.int64))
        self._n += len(new_keys)
        while (
            len(self._runs) >= 2
            and len(self._runs[-1]) * 2 >= len(self._runs[-2])
        ):
            b = self._runs.pop()
            a = self._runs.pop()
            self._runs.append(_merge_disjoint(a, b))

    def to_array(self) -> np.ndarray:
        """All keys, sorted (checkpoint/debug surface)."""
        if not self._runs:
            return np.zeros(0, np.int64)
        out = self._runs[0]
        for run in self._runs[1:]:
            out = _merge_disjoint(out, run)
        return out


def _merge_disjoint(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Two-way merge of DISJOINT sorted int64 runs (searchsorted placement
    + boolean scatter — one O(n) pass, no re-sort)."""
    pos = np.searchsorted(a, b)
    idx_b = pos + np.arange(len(b))
    merged = np.empty(len(a) + len(b), np.int64)
    mask = np.zeros(len(merged), bool)
    mask[idx_b] = True
    merged[mask] = b
    merged[~mask] = a
    return merged
