from .types import SampledEdge, SignedVertex, TriangleEstimate
from .profiling import StreamProfiler, WindowStats, device_trace, profiled
from .config import EngineConfig
