"""Device mesh construction and sharding helpers.

The reference's parallelism is implicit in Flink: operator parallelism plus
Netty shuffles (SURVEY.md §2.5-2.6). Here parallelism is explicit and
declarative: a ``jax.sharding.Mesh`` over TPU chips with named axes, and
shardings annotated on edge blocks / vertex tables; XLA inserts the ICI
collectives.

Axis conventions used throughout the framework:

- ``"edges"`` — the data-parallel axis: edge blocks are split along their
  capacity dimension (the analog of the reference's edge-partition
  data-parallelism, ``SummaryBulkAggregation.java:76-80``).
- ``"model"`` — feature/model parallel axis for the GNN layers (tensor
  parallelism over the feature dimension); unused (size 1) for the pure
  analytics workloads.

On a single chip both axes have size 1 and everything degenerates gracefully.
Multi-chip testing runs on a virtual CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) — the moral
equivalent of the reference's in-process Flink mini-cluster
(SURVEY.md §4).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

EDGE_AXIS = "edges"
MODEL_AXIS = "model"


def make_mesh(
    n_edge_shards: Optional[int] = None,
    n_model_shards: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 2-D (edges, model) mesh over the available devices."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_edge_shards is None:
        n_edge_shards = len(devs) // n_model_shards
    n = n_edge_shards * n_model_shards
    if n > len(devs):
        raise ValueError(
            f"requested {n} devices ({n_edge_shards}x{n_model_shards}) "
            f"but only {len(devs)} available"
        )
    grid = np.asarray(devs[:n]).reshape(n_edge_shards, n_model_shards)
    return Mesh(grid, (EDGE_AXIS, MODEL_AXIS))


def edge_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for EdgeBlock arrays: split capacity across the edge axis."""
    return NamedSharding(mesh, P(EDGE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated sharding (vertex tables, summaries)."""
    return NamedSharding(mesh, P())


def shard_block_spec():
    """PartitionSpec pytree for an EdgeBlock (all leaf arrays edge-sharded)."""
    from ..core.edgeblock import EdgeBlock  # local import to avoid cycle

    return EdgeBlock(src=P(EDGE_AXIS), dst=P(EDGE_AXIS), val=P(EDGE_AXIS), mask=P(EDGE_AXIS), n_vertices=0)
