"""Collective-communication layer: the TPU-native Flink shuffle.

The reference has zero transport code of its own — all communication is
implicit in Flink dataflow edges over Netty TCP (SURVEY.md §2.6): hash
shuffles (``keyBy``), broadcast, gather-to-one (``timeWindowAll`` /
``setParallelism(1)``), and the tree-reduce topology built by re-keying
(``SummaryTreeReduce.java:95-123``).

This module is the explicit equivalent over ICI, built on ``shard_map`` +
XLA collectives. Mapping (reference -> here):

- flat global reduce (``timeWindowAll().reduce`` + parallelism-1 ``Merger``,
  ``SummaryBulkAggregation.java:81-83``)  ->  :func:`all_reduce` (psum/pmin/
  pmax over a mesh axis; every shard gets the result — strictly stronger
  than the reference's single-task funnel).
- tree reduce (``SummaryTreeReduce.enhance()``)  ->  :func:`tree_all_reduce`,
  a log2(p) ``ppermute`` butterfly provided for topology parity/testing; on
  real ICI the flat collective is already ring/tree-optimal, so the engine
  uses :func:`all_reduce` by default.
- broadcast (``edges.broadcast()``, ``BroadcastTriangleCount.java:42``) ->
  replication (no sharding) or :func:`all_gather`.
- hash shuffle (``keyBy``)  ->  deterministic host-side bucketing by compact
  vertex id (VertexDict) — data is *placed* correctly instead of shuffled.

All functions take an ``axis_name`` and must run inside ``shard_map`` (or any
SPMD context where the axis is bound).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh

try:  # jax>=0.6 moved shard_map to jax.shard_map
    from jax import shard_map as _shard_map_fn  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map_fn  # type: ignore


# the relaxed-check kwarg was renamed check_rep -> check_vma across JAX
# releases; resolve which one this install accepts ONCE at import
import inspect as _inspect

_SM_PARAMS = _inspect.signature(_shard_map_fn).parameters
_SM_CHECK_KW = (
    "check_vma" if "check_vma" in _SM_PARAMS
    else "check_rep" if "check_rep" in _SM_PARAMS
    else None
)


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma: bool = False):
    """Thin wrapper over jax.shard_map with relaxed varying-manual-axes checks."""
    kw = {} if _SM_CHECK_KW is None else {_SM_CHECK_KW: check_vma}
    return _shard_map_fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         **kw)


# --------------------------------------------------------------------------- #
# Flat collectives (P3 / P5 in SURVEY.md §2.5)
# --------------------------------------------------------------------------- #
def all_reduce(x: Any, axis_name: str, op: str = "sum") -> Any:
    """All-reduce a pytree across a mesh axis (sum/min/max)."""
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    raise ValueError(f"unknown all_reduce op {op!r}")


def all_gather(x: Any, axis_name: str, axis: int = 0, tiled: bool = False) -> Any:
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def axis_index(axis_name: str) -> jax.Array:
    return lax.axis_index(axis_name)


def axis_size(axis_name: str) -> int:
    return lax.axis_size(axis_name)


# --------------------------------------------------------------------------- #
# Tree reduction (P4): ppermute butterfly, parity with SummaryTreeReduce
# --------------------------------------------------------------------------- #
def stacked_reduce(stacked: Any, n: int, combine: Callable[[Any, Any], Any]) -> Any:
    """Log-depth fold of ``n`` stacked partials (leading axis) with an
    arbitrary pytree ``combine`` — the bulk engine's cross-shard merge
    (``SummaryBulkAggregation``'s timeWindowAll-gather analog). Handles
    odd counts by carrying the tail partial into the next level."""
    while n > 1:
        half = n // 2
        lo = jax.tree.map(lambda x: x[:half], stacked)
        hi = jax.tree.map(lambda x: x[half: 2 * half], stacked)
        merged = jax.vmap(combine)(lo, hi)
        if n % 2:
            stacked = jax.tree.map(
                lambda m, x: jnp.concatenate([m, x[2 * half: n]]),
                merged,
                stacked,
            )
            n = half + 1
        else:
            stacked = merged
            n = half
    return jax.tree.map(lambda x: x[0], stacked)


def validate_tree_degree(n_shards: int, degree: int) -> None:
    """The degree-d butterfly needs the axis size to be a power of the
    degree; callable eagerly (stream setup) so a misconfiguration fails
    before any window runs, whichever carry ends up executing."""
    if degree < 2:
        raise ValueError(f"tree_all_reduce degree must be >= 2, got {degree}")
    total = 1
    while total < n_shards:
        total *= degree
    if total != n_shards:
        raise ValueError(
            f"tree_all_reduce requires the axis size ({n_shards}) to be a "
            f"power of the tree degree ({degree}); use degree=2 for "
            "power-of-two meshes"
        )


def resolve_tree_degree(n_shards: int, degree: int) -> int:
    """Effective butterfly fan-in for this mesh: ``degree`` when the
    axis size is a power of it, else 2 (which fits every power-of-two
    mesh) with a warning.

    In the reference ``degree`` configures the partial-aggregation
    PARALLELISM (``setParallelism(degree)``) while ``enhance()``'s
    fan-in is fixed at 2 — a non-conforming degree there degrades with a
    warning rather than failing. The butterfly generalizes degree into a
    true fan-in, so a degree the mesh cannot honor degrades the same
    way: warn, run the degree-2 butterfly. ``degree < 2`` still raises
    (no meaningful fallback)."""
    if degree < 2:
        raise ValueError(f"tree_all_reduce degree must be >= 2, got {degree}")
    total = 1
    while total < n_shards:
        total *= degree
    if total == n_shards:
        return degree
    import warnings

    warnings.warn(
        f"tree degree {degree} does not fit the {n_shards}-shard edge "
        "axis (axis size must be a power of the degree); falling back "
        "to the degree-2 butterfly",
        stacklevel=2,
    )
    return 2


def tree_all_reduce(
    x: Any,
    axis_name: str,
    combine: Callable[[Any, Any], Any],
    n_shards: int,
    degree: int = 2,
) -> Any:
    """Butterfly all-reduce with an arbitrary combine fn and fan-in
    ``degree``.

    The reference's ``SummaryTreeReduce.enhance()`` repeatedly reduces
    parallelism by its tree degree and combines partials
    (``SummaryTreeReduce.java:95-123``). The ICI-native equivalent is a
    degree-d butterfly: at round r the shards split into groups of
    ``degree`` (stride ``degree**r``); every shard ppermute-receives the
    other ``degree - 1`` members' partials and folds them in — after
    ``log_degree(p)`` rounds *every* shard holds the global combine.
    ``degree=2`` is the classic recursive-doubling exchange; higher
    degrees trade fewer rounds (less latency-bound collective setup) for
    more sequential combines per round.

    ``combine`` may be any associative+commutative pytree merge (not just
    an elementwise monoid) — commutativity is required because each shard
    folds partials in its own arrival order (the degree-2 case already
    relied on this: shard i computes combine(x_i, x_j) while shard j
    computes combine(x_j, x_i)).

    ``n_shards`` must be a power of ``degree`` (the mesh axis size).
    """
    validate_tree_degree(n_shards, degree)
    group = 1
    while group < n_shards:
        span = group * degree
        # permute the ROUND-START partial each exchange: accumulating
        # into the permute source would ship partially-combined values
        # on the second and later exchanges of a round
        x0 = x
        for j in range(1, degree):
            # shard i = hi*span + pos*group + lo receives the partial of
            # the group member at position (pos - j) mod degree
            perm = []
            for i in range(n_shards):
                hi, rem = divmod(i, span)
                pos, lo = divmod(rem, group)
                dst = hi * span + ((pos + j) % degree) * group + lo
                perm.append((i, dst))
            partner = jax.tree.map(
                lambda leaf: lax.ppermute(leaf, axis_name, perm), x0
            )
            x = combine(x, partner)
        group = span
    return x


# --------------------------------------------------------------------------- #
# Sharded segment reduction: the engine's cross-shard combine primitive
# --------------------------------------------------------------------------- #
def sharded_segment_min(
    values: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    axis_name: str,
) -> jax.Array:
    """Per-shard scatter-min over a replicated vertex table, then pmin.

    The building block of the distributed aggregate path: each shard folds its
    slice of the edge block into a local V-sized table, and one ICI all-reduce
    replaces the reference's keyBy + timeWindowAll funnel.
    """
    local = jax.ops.segment_min(values, segment_ids, num_segments=num_segments)
    return lax.pmin(local, axis_name)


def sharded_segment_sum(
    values: jax.Array,
    segment_ids: jax.Array,
    num_segments: int,
    axis_name: str,
) -> jax.Array:
    local = jax.ops.segment_sum(values, segment_ids, num_segments=num_segments)
    return lax.psum(local, axis_name)
