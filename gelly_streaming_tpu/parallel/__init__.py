from .mesh import EDGE_AXIS, MODEL_AXIS, edge_sharding, make_mesh, replicated
from . import comm
from . import multihost
