"""Multi-host (multi-slice / DCN) execution support.

The reference scales out through Flink's cluster runtime: one JobManager,
N TaskManagers, Netty shuffles between hosts (SURVEY.md §2.6). The JAX
equivalent is multi-controller SPMD: every host runs this same program,
``jax.distributed.initialize`` wires them into one runtime, and a global
``Mesh`` spans all hosts' devices — collectives ride ICI within a slice
and DCN across slices, placed by XLA from the same ``shard_map`` programs
used single-host (nothing else in the framework changes).

Ingest contract (the keyBy analog across hosts): every host windows ITS
OWN shard of the edge stream with a deterministic VertexDict — compaction
is deterministic given identical id streams, so hosts must either (a)
share the raw->compact mapping by exchanging dictionaries per window, or
(b) pre-partition the raw id space (e.g. ``hash(v) % n_hosts``) and use
:func:`global_edge_block` to assemble the global sharded arrays from
per-host blocks. This module provides the wiring; the windowing/kernel
stack is host-count agnostic.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process into the multi-controller runtime.

    Thin wrapper over ``jax.distributed.initialize`` (args auto-detected
    on TPU pods, explicit elsewhere). Call once per process, before any
    device computation; afterwards ``jax.devices()`` spans all hosts and
    :func:`gelly_streaming_tpu.parallel.mesh.make_mesh` builds a global
    mesh.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def global_edge_block(mesh, local_arrays: Sequence[np.ndarray]):
    """Assemble globally-sharded device arrays from per-host numpy columns.

    Each host passes the columns of ITS edge shard (e.g. src, dst, val,
    mask of the local window); the result is a tuple of global
    ``jax.Array``s sharded over the mesh ``"edges"`` axis whose global
    shape concatenates all hosts' rows — the input contract of the
    sharded aggregation/snapshot paths. All hosts must pass equal-length
    columns (pad to the window capacity as usual).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import EDGE_AXIS

    sharding = NamedSharding(mesh, P(EDGE_AXIS))
    out = []
    for col in local_arrays:
        col = np.asarray(col)
        global_shape = (col.shape[0] * jax.process_count(), *col.shape[1:])
        out.append(
            jax.make_array_from_process_local_data(sharding, col, global_shape)
        )
    return tuple(out)


def global_block(mesh, local_block):
    """Assemble a globally-sharded EdgeBlock from each host's local block.

    Every host passes the block holding ITS shard of the window (equal
    capacities everywhere; vertex mappings must agree across hosts — use
    a pre-partitioned/dense id scheme, see the module docstring). The
    result is an EdgeBlock of global ``jax.Array``s sharded over the mesh
    edge axis, consumable by the engine's sharded window step directly.
    """
    import numpy as np

    from ..core.edgeblock import EdgeBlock

    s, d, v, m = (
        np.asarray(local_block.src),
        np.asarray(local_block.dst),
        np.asarray(local_block.val),
        np.asarray(local_block.mask),
    )
    gs, gd, gv, gm = global_edge_block(mesh, [s, d, v, m])
    return EdgeBlock(
        src=gs, dst=gd, val=gv, mask=gm,
        n_vertices=local_block.n_vertices,
    )


def globalize_stream(stream, mesh):
    """A stream whose windows are the global assembly of every host's
    local windows — the ingest contract for running the aggregation
    engine itself multi-process (each host windows its own shard; the
    engine's shard_map programs see one global block per window)."""
    from ..core.stream import SimpleEdgeStream

    return SimpleEdgeStream(
        _blocks=lambda: (global_block(mesh, b) for b in stream.blocks()),
        _vdict=stream.vertex_dict,
    )


def is_coordinator() -> bool:
    """True on the process that should own singleton side effects
    (emission files, checkpoint writes) — the JobManager analog."""
    return jax.process_index() == 0
