"""Multi-host (multi-slice / DCN) execution support.

The reference scales out through Flink's cluster runtime: one JobManager,
N TaskManagers, Netty shuffles between hosts (SURVEY.md §2.6). The JAX
equivalent is multi-controller SPMD: every host runs this same program,
``jax.distributed.initialize`` wires them into one runtime, and a global
``Mesh`` spans all hosts' devices — collectives ride ICI within a slice
and DCN across slices, placed by XLA from the same ``shard_map`` programs
used single-host (nothing else in the framework changes).

Ingest contract (the keyBy analog across hosts): every host windows ITS
OWN shard of the edge stream, and the per-host raw->compact mappings must
agree globally. Two implemented contracts:

(a) **dict exchange** (:func:`dict_exchange_encode`): per window, hosts
    allgather their windows' first-occurrence raw ids and every host
    feeds the union into its VertexDict in (process rank, arrival) order
    — compaction is deterministic given identical id streams, so all
    dictionaries stay byte-identical with no coordinator. For sparse /
    arbitrary raw id spaces.
(b) **pre-partition** (:func:`global_edge_block` /
    :func:`globalize_stream`): dense or pre-hashed id spaces need no
    exchange at all — every host uses the same deterministic mapping
    (e.g. ``IdentityDict``) and the global sharded arrays assemble
    directly from per-host blocks.

Both feed the same sharded aggregation stack; the windowing/kernel code
is host-count agnostic.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np

from ..fabric import CollectiveTransport, SharedDirTransport


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process into the multi-controller runtime.

    Thin wrapper over ``jax.distributed.initialize`` (args auto-detected
    on TPU pods, explicit elsewhere). Call once per process, before any
    device computation; afterwards ``jax.devices()`` spans all hosts and
    :func:`gelly_streaming_tpu.parallel.mesh.make_mesh` builds a global
    mesh.
    """
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def global_edge_block(mesh, local_arrays: Sequence[np.ndarray]):
    """Assemble globally-sharded device arrays from per-host numpy columns.

    Each host passes the columns of ITS edge shard (e.g. src, dst, val,
    mask of the local window); the result is a tuple of global
    ``jax.Array``s sharded over the mesh ``"edges"`` axis whose global
    shape concatenates all hosts' rows — the input contract of the
    sharded aggregation/snapshot paths. All hosts must pass equal-length
    columns (pad to the window capacity as usual).
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    from .mesh import EDGE_AXIS

    sharding = NamedSharding(mesh, P(EDGE_AXIS))
    out = []
    for col in local_arrays:
        col = np.asarray(col)
        global_shape = (col.shape[0] * jax.process_count(), *col.shape[1:])
        out.append(
            jax.make_array_from_process_local_data(sharding, col, global_shape)
        )
    return tuple(out)


def global_block(mesh, local_block):
    """Assemble a globally-sharded EdgeBlock from each host's local block.

    Every host passes the block holding ITS shard of the window (equal
    capacities everywhere; vertex mappings must agree across hosts — use
    a pre-partitioned/dense id scheme, see the module docstring). The
    result is an EdgeBlock of global ``jax.Array``s sharded over the mesh
    edge axis, consumable by the engine's sharded window step directly.
    """
    import numpy as np

    from ..core.edgeblock import EdgeBlock

    s, d, v, m = (
        np.asarray(local_block.src),
        np.asarray(local_block.dst),
        np.asarray(local_block.val),
        np.asarray(local_block.mask),
    )
    gs, gd, gv, gm = global_edge_block(mesh, [s, d, v, m])
    return EdgeBlock(
        src=gs, dst=gd, val=gv, mask=gm,
        n_vertices=local_block.n_vertices,
    )


def globalize_stream(stream, mesh):
    """A stream whose windows are the global assembly of every host's
    local windows — the ingest contract for running the aggregation
    engine itself multi-process (each host windows its own shard; the
    engine's shard_map programs see one global block per window)."""
    from ..core.stream import SimpleEdgeStream

    return SimpleEdgeStream(
        _blocks=lambda: (global_block(mesh, b) for b in stream.blocks()),
        _vdict=stream.vertex_dict,
    )


# The exchange transports moved into the cluster fabric (ISSUE 16):
# the collective allgather generalized into CollectiveTransport, the
# shared-directory exchange into SharedDirTransport — both now full
# Transport implementations (put/get/barrier/elect on top of the same
# allgather this module always used, byte-identical file layout). The
# historical names stay importable here as the ingest-facing aliases.
JaxAllgatherTransport = CollectiveTransport
FileExchangeTransport = SharedDirTransport


def dict_exchange_encode(
    mesh, vdict, src_raw: np.ndarray, dst_raw: np.ndarray,
    *, transport=None, window=None,
):
    """Encode one window's raw columns under a GLOBALLY-AGREED dictionary
    (ingest contract (a), module docstring).

    Each host proposes its window's raw ids in first-occurrence order;
    two allgathers (counts, then bucket-padded id arrays) give every host
    the same proposal matrix, and each host folds the union into its own
    ``vdict`` in (process rank, arrival order) — a deterministic sequence,
    so dictionaries that started identical remain identical without any
    coordinator. Returns the compact ``(src, dst)`` columns. Proposal
    arrays are padded to shared pow2 buckets so the allgather shapes (and
    their compiled programs) stay stable across windows. ``mesh`` is
    accepted for call-site symmetry with the pre-partition helpers; the
    exchange itself spans the global process set.

    ``transport`` selects how the allgather runs: any
    :class:`~gelly_streaming_tpu.fabric.Transport` — the collective
    backend by default (the live multi-controller runtime), a
    shared-dir or socket transport for the coordinated-recovery path
    (replay-deterministic). ``window`` is the window ordinal used to
    tag persistent-transport exchanges; required there, ignored by the
    collective transport.
    """
    from ..core.edgeblock import bucket_capacity

    tr = transport if transport is not None else CollectiveTransport()
    if window is None and getattr(tr, "persistent", True):
        # a persisted transport keys the exchange on the tag; with a
        # constant tag its idempotent-skip path would silently re-read
        # the FIRST window's proposals for every later window and the
        # ranks' dictionaries would diverge — the exact state the
        # exchange exists to prevent
        raise ValueError(
            "dict_exchange_encode: `window` is required when the "
            "transport persists exchanges by tag (it disambiguates "
            "windows); only the jax allgather transport may omit it"
        )
    tag = "x" if window is None else f"w{int(window):08d}"
    ids = np.concatenate(
        [src_raw.astype(np.int64), dst_raw.astype(np.int64)]
    )
    # first-occurrence order, matching single-host VertexDict semantics
    _, first = np.unique(ids, return_index=True)
    proposal = ids[np.sort(first)]
    n = np.int32(len(proposal))
    counts = np.concatenate([
        np.asarray(c).reshape(-1)
        for c in tr.allgather(tag + ".n", np.array([n], np.int32))
    ])
    cap = bucket_capacity(int(counts.max()) if counts.size else 1, minimum=8)
    # ship int64 raw ids as two int32 planes: the gather rides device
    # arrays, and default-jax (x64 disabled) silently truncates int64 —
    # 40-bit ids came back negative before this split
    padded = np.zeros((2, cap), np.int32)
    padded[0, : len(proposal)] = (proposal >> 32).astype(np.int32)
    padded[1, : len(proposal)] = (proposal & 0xFFFFFFFF).astype(np.uint32).view(np.int32)
    gathered = [
        np.asarray(g) for g in tr.allgather(tag + ".ids", padded)
    ]
    for p, plane in enumerate(gathered):
        hi = plane[0, : int(counts[p])].astype(np.int64)
        lo = plane[1, : int(counts[p])].view(np.uint32).astype(np.int64)
        vdict.encode((hi << 32) | lo)
    return vdict.encode(src_raw), vdict.encode(dst_raw)


def is_coordinator() -> bool:
    """True on the process that should own singleton side effects
    (emission files, checkpoint writes) — the JobManager analog."""
    return jax.process_index() == 0
