"""Event-time windowing + retraction: graphs that forget (ISSUE 18).

Every window the repo streamed before this package was an add-only
count window: edges entered, summaries grew, nothing ever left. The
reference's richer half — keyed ``timeWindow``/``slice`` over event
time (PAPER.md §1 L1/L2) — needs the opposite contract: records carry
their OWN clock (an i64 ``ts`` column on the wire, GSEW v2), progress
is a WATERMARK merged across shards by the min rule, and a SLIDING
window retracts the pane that ages out — results must stay correct on
the surviving edge multiset, not the union of everything ever seen.

The pieces, one module each:

- :mod:`.watermark` — per-shard watermark tracking and THE cross-shard
  min-merge rule (:func:`merge_watermarks`), closing PR 11's
  per-shard-count-windows-only residual.
- :mod:`.panes` — the pane/slice decomposition: a sliding window of
  ``(size, slide)`` with ``size % slide == 0`` is a union of
  ``size//slide`` tumbling PANES of length ``slide``; the assembler
  buffers arriving column chunks per pane, closes panes as the
  watermark passes them, and drops records later than the lateness
  bound as counted ``eventtime.late_dropped`` — never silently.
  Closed panes are plain raw-id column tuples, so they pack like count
  windows through
  :meth:`~gelly_streaming_tpu.core.window.Windower.pack_window_cols`
  and the superbatch/group-fold path consumes them unchanged.
- :mod:`.retract` — decremental summaries: exact decremental
  degree/heavy-hitters, CC via the forest REPAIR kernel
  (:func:`~gelly_streaming_tpu.summaries.forest.repair_forest_host` —
  rebuild only affected components from surviving pane edges), and
  bipartiteness with odd-cycle latch re-resolution on expiry. Each
  ships its from-scratch oracle; the test suite pins byte identity on
  the surviving multiset at every pane boundary.
- :mod:`.stream` — :class:`SlidingGraphAggregator`, the driver that
  sequences pane close -> retract expired pane -> fold new pane ->
  emit window, with atomic checksummed commits between panes so a kill
  between pane close and retraction commit recovers oracle-identical
  (the chaos contract).

Serving reports how far behind real time an answer is: the emitted
window results and published snapshots carry an ``event_ts`` watermark
stamp that rides :class:`~gelly_streaming_tpu.serving.query.Answer`
next to its snapshot version.
"""

from .panes import EventTimeSlidingWindow, Pane, PaneAssembler
from .retract import (
    DecBipartite,
    DecDegree,
    DecForest,
    oracle_bipartite,
    oracle_degrees,
    oracle_labels,
)
from .stream import SlidingGraphAggregator, WindowResult, drive_sliding
from .watermark import NO_WATERMARK, WatermarkTracker, merge_watermarks

__all__ = [
    "EventTimeSlidingWindow",
    "Pane",
    "PaneAssembler",
    "DecBipartite",
    "DecDegree",
    "DecForest",
    "oracle_bipartite",
    "oracle_degrees",
    "oracle_labels",
    "SlidingGraphAggregator",
    "WindowResult",
    "drive_sliding",
    "NO_WATERMARK",
    "WatermarkTracker",
    "merge_watermarks",
]
