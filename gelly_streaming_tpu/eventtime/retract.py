"""Decremental summaries: results that stay correct as panes expire.

The add-only single-pass model structurally excludes retraction — a
degree count can decrement, but a union-find cannot un-union. Each
summary here picks the cheapest HONEST decremental strategy, and each
ships its from-scratch oracle so the contract is testable as byte
identity on the surviving edge multiset (the acceptance criterion
``tests/test_eventtime.py`` pins at every pane boundary):

- **Degree / heavy hitters** (:class:`DecDegree`) — exactly
  decremental: per-vertex counts are a sum, so expiry subtracts the
  pane's contribution (one ``np.subtract.at``). Heavy hitters are the
  exact top-k of the maintained table with deterministic ties (degree
  desc, vertex id asc) — no sketch, no approximation to un-approximate.
- **Connected components** (:class:`DecForest`) — union-find supports
  union, not deletion, so expiry goes through the forest REPAIR kernel
  (:func:`~gelly_streaming_tpu.summaries.forest.repair_forest_host`):
  only the components the expired edges touched are reset and re-folded
  from the surviving panes' edges — bounded recompute from the
  group-fold contract's carried table, not a from-scratch rebuild.
- **Bipartiteness** (:class:`DecBipartite`) — the signed double cover
  (``summaries/candidates.py`` semantics) over ``2 * vcap`` cover ids.
  The odd-cycle verdict is a LATCH while adding (a conflict, once
  merged, stays), but expiry can dissolve the odd cycle — so on
  retraction the cover forest is repaired and the latch RE-RESOLVED
  from the repaired cover (conflict iff some live vertex's (+) and (-)
  cover nodes share a component), never carried stale across an expiry.

All three grow their vertex capacity amortized-doubling; labels of
existing vertices are preserved exactly across growth (new rows are
singletons, which is what a from-scratch fold over the same multiset
produces for unseen ids).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..summaries.forest import (
    fold_edges_host,
    fold_into_forest_host,
    repair_forest_host,
    resolve_flat_host,
)

_EMPTY = np.zeros(0, np.int64)


# --------------------------------------------------------------------- #
# From-scratch oracles (the byte-identity reference for every summary)
# --------------------------------------------------------------------- #
def oracle_labels(vcap: int, src, dst) -> np.ndarray:
    """CC labels of the given edge multiset, from scratch: one
    group-fold over an identity table — THE reference the repair kernel
    must match byte-for-byte."""
    return fold_edges_host(
        np.arange(vcap, dtype=np.int64),
        np.asarray(src, np.int64), np.asarray(dst, np.int64),
    )


def oracle_degrees(vcap: int, src, dst) -> np.ndarray:
    """Degrees of the given edge multiset, from scratch (both endpoints
    count; self-loops count twice — the multiset convention every
    decremental path must share)."""
    deg = np.zeros(vcap, np.int64)
    np.add.at(deg, np.asarray(src, np.int64), 1)
    np.add.at(deg, np.asarray(dst, np.int64), 1)
    return deg


def oracle_bipartite(vcap: int, src, dst) -> bool:
    """Bipartiteness of the given edge multiset, from scratch: CC over
    the signed double cover; bipartite iff no vertex's (+)/(-) cover
    nodes share a component."""
    src = np.asarray(src, np.int64)
    dst = np.asarray(dst, np.int64)
    cs, cd = _cover_cols(src, dst, vcap)
    lab = fold_edges_host(np.arange(2 * vcap, dtype=np.int64), cs, cd)
    return not bool(np.any(lab[:vcap] == lab[vcap:]))


def _cover_cols(src: np.ndarray, dst: np.ndarray,
                vcap: int) -> Tuple[np.ndarray, np.ndarray]:
    """One edge column pair expanded to the signed-cover pair
    ((u,+)~(v,-) and (u,-)~(v,+)) — the same expansion
    ``library/bipartiteness.py`` uses, over ``2 * vcap`` cover ids."""
    return (
        np.concatenate([src, src + vcap]),
        np.concatenate([dst + vcap, dst]),
    )


# --------------------------------------------------------------------- #
# Degree / heavy hitters
# --------------------------------------------------------------------- #
class DecDegree:
    """Exact decremental degree table + exact top-k heavy hitters."""

    def __init__(self, vcap: int = 0):
        self.deg = np.zeros(int(vcap), np.int64)

    @property
    def vcap(self) -> int:
        return len(self.deg)

    def grow(self, vcap: int) -> None:
        if vcap > len(self.deg):
            self.deg = np.concatenate(
                [self.deg, np.zeros(vcap - len(self.deg), np.int64)]
            )

    def add(self, src, dst) -> None:
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        np.add.at(self.deg, src, 1)
        np.add.at(self.deg, dst, 1)

    def retract(self, src, dst) -> None:
        """Subtract one expired pane's contribution — degrees are a
        sum, so this is EXACT (never clamped: a negative degree here
        is a caller bug the tests would catch, not data)."""
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        np.subtract.at(self.deg, src, 1)
        np.subtract.at(self.deg, dst, 1)

    def top_k(self, k: int) -> list:
        """Exact heavy hitters: ``[(vertex, degree), ...]`` sorted by
        degree desc then vertex id asc (deterministic ties), zero-degree
        vertices excluded."""
        nz = np.nonzero(self.deg)[0]
        if len(nz) == 0 or k < 1:
            return []
        # sort by (-degree, id): lexsort's LAST key is primary
        order = np.lexsort((nz, -self.deg[nz]))[:k]
        picked = nz[order]
        return [(int(v), int(self.deg[v])) for v in picked]

    def state_dict(self) -> dict:
        return {"deg": self.deg.copy()}

    def load_state_dict(self, state: dict) -> None:
        self.deg = np.asarray(state["deg"], np.int64).copy()


# --------------------------------------------------------------------- #
# Connected components
# --------------------------------------------------------------------- #
class DecForest:
    """CC over the live multiset: incremental union on pane close,
    bounded repair on pane expiry.

    The carried table is the canonical min-rooted host forest the
    group-fold contract already uses
    (:func:`~gelly_streaming_tpu.summaries.forest.fold_edges_host`
    output), so between retractions it is byte-identical to a
    from-scratch fold by construction; across a retraction the repair
    kernel re-establishes the identity over the SURVIVING multiset and
    reports the bounded-recompute stats (affected roots/members,
    re-folded edges) the bench's retraction-vs-rebuild cell commits."""

    def __init__(self, vcap: int = 0):
        self.lab = np.arange(int(vcap), dtype=np.int64)
        self.last_repair: Dict[str, int] = {}

    @property
    def vcap(self) -> int:
        return len(self.lab)

    def grow(self, vcap: int) -> None:
        if vcap > len(self.lab):
            self.lab = np.concatenate([
                self.lab,
                np.arange(len(self.lab), vcap, dtype=np.int64),
            ])

    def add(self, src, dst) -> None:
        self.lab = fold_into_forest_host(self.lab, src, dst)

    def retract(self, expired_src, expired_dst,
                surviving_src, surviving_dst) -> Dict[str, int]:
        self.lab, stats = repair_forest_host(
            self.lab, expired_src, expired_dst,
            surviving_src, surviving_dst,
        )
        self.last_repair = stats
        return stats

    def labels(self) -> np.ndarray:
        return resolve_flat_host(self.lab)

    def state_dict(self) -> dict:
        return {"lab": self.lab.copy()}

    def load_state_dict(self, state: dict) -> None:
        self.lab = np.asarray(state["lab"], np.int64).copy()


# --------------------------------------------------------------------- #
# Bipartiteness
# --------------------------------------------------------------------- #
class DecBipartite:
    """Bipartiteness over the live multiset via the signed double
    cover, with the odd-cycle latch RE-RESOLVED on every expiry.

    While only adding, the verdict is the usual latch — once some
    vertex's (+)/(-) cover nodes merge, more edges cannot unmerge them.
    Expiry breaks the latch's monotonicity, so :meth:`retract` repairs
    the cover forest (the same bounded kernel as CC, over ``2 * vcap``
    cover ids and cover-expanded columns) and recomputes the verdict
    from the repaired structure — the cover table is the truth, the
    latch is only a cache of it (the ``serving/query.py`` bipartite
    ethos)."""

    def __init__(self, vcap: int = 0):
        self.vcap = int(vcap)
        self.cover = np.arange(2 * self.vcap, dtype=np.int64)

    def grow(self, vcap: int) -> None:
        """Grow the COVER table preserving labels: cover ids are
        ``v`` / ``v + vcap``, so growth re-homes the (-) half to the
        new offset (labels that pointed into the old (-) half shift
        with it)."""
        vcap = int(vcap)
        if vcap <= self.vcap:
            return
        old = self.vcap
        lab = resolve_flat_host(self.cover)
        grown = np.arange(2 * vcap, dtype=np.int64)
        shift = np.where(lab >= old, lab + (vcap - old), lab)
        grown[:old] = shift[:old]
        grown[vcap:vcap + old] = shift[old:]
        self.vcap = vcap
        self.cover = resolve_flat_host(grown)

    def add(self, src, dst) -> None:
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        cs, cd = _cover_cols(src, dst, self.vcap)
        self.cover = fold_into_forest_host(self.cover, cs, cd)

    def retract(self, expired_src, expired_dst,
                surviving_src, surviving_dst) -> Dict[str, int]:
        es, ed = _cover_cols(
            np.asarray(expired_src, np.int64),
            np.asarray(expired_dst, np.int64), self.vcap,
        )
        ss, sd = _cover_cols(
            np.asarray(surviving_src, np.int64),
            np.asarray(surviving_dst, np.int64), self.vcap,
        )
        self.cover, stats = repair_forest_host(
            self.cover, es, ed, ss, sd,
        )
        return stats

    def is_bipartite(self) -> bool:
        """The verdict, resolved from the cover structure (never a
        carried boolean across an expiry)."""
        lab = resolve_flat_host(self.cover)
        return not bool(np.any(lab[:self.vcap] == lab[self.vcap:]))

    def conflict_witness(self) -> Optional[int]:
        """The smallest vertex whose (+)/(-) cover nodes share a
        component, None when bipartite."""
        lab = resolve_flat_host(self.cover)
        hit = np.nonzero(lab[:self.vcap] == lab[self.vcap:])[0]
        return int(hit[0]) if len(hit) else None

    def state_dict(self) -> dict:
        return {"vcap": self.vcap, "cover": self.cover.copy()}

    def load_state_dict(self, state: dict) -> None:
        self.vcap = int(state["vcap"])
        self.cover = np.asarray(state["cover"], np.int64).copy()
