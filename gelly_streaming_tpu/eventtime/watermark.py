"""Watermarks: event-time progress, tracked per shard, merged by min.

A watermark is a PROMISE about the past: "no record with ``ts`` below
this value will arrive on this stream again" (modulo the configured
lateness allowance, which the pane assembler enforces as a counted
drop, never a silent absorb). Each shard's watermark advances to the
maximum timestamp it has observed — the GSEW wire preserves per-shard
arrival order, so within one shard the max IS the promise. Across
shards nothing orders arrivals, so the merged watermark is the MINIMUM
over shards: one slow shard holds the whole stream's clock back, which
is exactly the behavior that makes pane closes safe (Flink's
``StatusWatermarkValve`` rule; PR 11 left this residual open when it
shipped per-shard count windows only).

A shard that has observed NO timestamped record yet reports
:data:`NO_WATERMARK` (i64 min), which the min-merge propagates: the
merged clock does not move until every shard has spoken. Sources that
END remove themselves from the merge (a closed shard can hold nothing
back — its promise is total).
"""

from __future__ import annotations

from typing import Iterable, List

import numpy as np

from ..obs.registry import get_registry

#: "no event-time progress yet": below every real i64 timestamp
NO_WATERMARK = int(np.iinfo(np.int64).min)


def merge_watermarks(marks: Iterable[int]) -> int:
    """THE cross-shard merge rule: the minimum over per-shard
    watermarks (an empty collection — every shard ended — merges to
    ``+inf``-like i64 max: nothing can be held back). One shard at
    :data:`NO_WATERMARK` pins the merge there: the stream's clock only
    moves once every shard has observed event time."""
    marks = list(marks)
    if not marks:
        return int(np.iinfo(np.int64).max)
    return min(int(m) for m in marks)


class WatermarkTracker:
    """Per-shard watermark registry + the merged min (the one clock the
    pane assembler trusts).

    ``observe(shard, ts)`` advances that shard's watermark to the max
    timestamp in the column (watermarks are monotone — a late record
    never moves one backwards); ``finish(shard)`` removes an ENDED
    shard from the merge. ``current()`` is the min-merge over live
    shards. Every merged advance is counted
    (``eventtime.watermark_advance``, the timeline's WATERMARK story
    line) and the merged value is published as the
    ``eventtime.watermark`` gauge — always-on operational evidence,
    like the resilience counters.
    """

    def __init__(self, nshards: int = 1):
        if nshards < 1:
            raise ValueError(f"nshards must be >= 1, got {nshards}")
        self._marks: List[int] = [NO_WATERMARK] * int(nshards)
        self._live = [True] * int(nshards)
        self._merged = NO_WATERMARK
        self._advance = None  # lazy counter (registry may be swapped)
        self._gauge = None

    @property
    def nshards(self) -> int:
        return len(self._marks)

    def shard_watermarks(self) -> List[int]:
        return list(self._marks)

    def observe(self, shard: int, ts) -> int:
        """Advance ``shard``'s watermark to the max of ``ts`` (a column
        or a scalar); returns the merged watermark after the advance."""
        ts = np.asarray(ts, np.int64)
        if ts.size:
            hi = int(ts.max())
            if hi > self._marks[shard]:
                self._marks[shard] = hi
        return self._remerge()

    def finish(self, shard: int) -> int:
        """An ENDED shard stops holding the clock back."""
        self._live[shard] = False
        return self._remerge()

    def current(self) -> int:
        return self._merged

    # ------------------------------------------------------------------ #
    def _remerge(self) -> int:
        merged = merge_watermarks(
            m for m, live in zip(self._marks, self._live) if live
        )
        if merged > self._merged:
            self._merged = merged
            if self._advance is None:
                self._advance = get_registry().counter(
                    "eventtime.watermark_advance"
                )
                self._gauge = get_registry().gauge("eventtime.watermark")
            self._advance.inc()
            self._gauge.set(float(merged))
        return self._merged

    # ------------------------------------------------------------------ #
    # Checkpoint surface (the driver commits between panes)
    # ------------------------------------------------------------------ #
    def state_dict(self) -> dict:
        return {
            "marks": list(self._marks),
            "live": list(self._live),
            "merged": int(self._merged),
        }

    def load_state_dict(self, state: dict) -> None:
        self._marks = [int(m) for m in state["marks"]]
        self._live = [bool(x) for x in state["live"]]
        self._merged = int(state["merged"])

    def __repr__(self) -> str:  # debugging aid, not a contract
        return (
            f"WatermarkTracker(merged={self._merged}, "
            f"marks={self._marks})"
        )
