"""Pane/slice decomposition: sliding windows as unions of tumbling panes.

A sliding window of ``(size, slide)`` with ``size % slide == 0`` is the
union of ``size // slide`` consecutive PANES of length ``slide`` — the
classic slice decomposition (the reference's ``slice()`` operator;
Flink assigns each record to ``size/slide`` windows, this repo stores
it ONCE in its pane and composes windows at emission). Panes matter
for two reasons:

1. **They pack like count windows.** A closed pane is a plain raw-id
   column tuple, exactly what
   :meth:`~gelly_streaming_tpu.core.window.Windower.pack_window_cols`
   packs into a
   :class:`~gelly_streaming_tpu.core.window.SuperbatchGroup` — so the
   superbatch/group-fold path (``drive_group_folded``, prefetch,
   checkpointing, auto-K) consumes event-time panes unchanged. No new
   device path exists for event time; the decomposition IS the
   composition point.
2. **They are the retraction unit.** When the window slides, exactly
   one pane expires; the pane's edge columns are retained until then,
   so the retraction kernel gets the expired multiset AND the
   surviving multiset as concatenations of views, never a recompute.

LATENESS: a record whose ``ts`` is below ``watermark -
allowed_lateness`` is DROPPED and counted ``eventtime.late_dropped``
(the timeline's LATE-DROP line) — never silently absorbed into a pane
that already closed, which would silently corrupt the retraction
arithmetic. Records inside the allowance land in their pane as long as
it is still open; panes only close once the watermark passes
``pane_end + allowed_lateness``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..obs.registry import get_registry
from .watermark import NO_WATERMARK


@dataclasses.dataclass(frozen=True)
class EventTimeSlidingWindow:
    """The sliding event-time policy: ``size`` and ``slide`` in event
    time units (``slide == size`` degenerates to tumbling). The pane
    length is ``slide``; ``size % slide == 0`` is required so every
    window is a whole number of panes (the decomposition invariant)."""

    size: int
    slide: Optional[int] = None

    def __post_init__(self):
        slide = self.size if self.slide is None else self.slide
        object.__setattr__(self, "slide", int(slide))
        object.__setattr__(self, "size", int(self.size))
        if self.size < 1:
            raise ValueError(f"size must be >= 1, got {self.size}")
        if self.slide < 1 or self.slide > self.size:
            raise ValueError(
                f"slide must be in [1, size], got {self.slide}"
            )
        if self.size % self.slide:
            raise ValueError(
                f"size ({self.size}) must be a multiple of slide "
                f"({self.slide}) — sliding windows decompose into "
                "whole panes"
            )

    @property
    def pane_size(self) -> int:
        return self.slide

    @property
    def panes_per_window(self) -> int:
        return self.size // self.slide

    def pane_of(self, ts) -> np.ndarray:
        """Pane index per timestamp (floor division — i64 exact)."""
        return np.floor_divide(np.asarray(ts, np.int64), self.slide)


@dataclasses.dataclass
class Pane:
    """One closed pane: the raw-id edge columns that arrived inside
    ``[start, end)``, retained until the pane expires out of its last
    window (the retraction unit)."""

    index: int
    start: int
    end: int
    src: np.ndarray
    dst: np.ndarray
    ts: np.ndarray

    def __len__(self) -> int:
        return len(self.src)

    def cols(self) -> Tuple[np.ndarray, np.ndarray, None]:
        """The ``(src, dst, val|None)`` triple ``pack_window_cols``
        packs — a closed pane IS a closed count window to the
        superbatch path."""
        return self.src, self.dst, None


class PaneAssembler:
    """Assign arriving edge columns to panes; close panes as the
    watermark passes them; drop (and count) records past the lateness
    allowance.

    ``add(src, dst, ts, watermark)`` buffers per-pane column chunks —
    whole-array numpy bucketing, no per-record Python.
    ``advance(watermark)`` returns every pane whose
    ``end + allowed_lateness <= watermark``, in index order, including
    EMPTY panes between closed ones (a silent slot still slides the
    window — emission cadence is event time, not data arrival).
    ``flush()`` closes everything left (end of stream: the watermark's
    promise becomes total)."""

    def __init__(self, policy: EventTimeSlidingWindow, *,
                 allowed_lateness: int = 0):
        if allowed_lateness < 0:
            raise ValueError(
                f"allowed_lateness must be >= 0, got {allowed_lateness}"
            )
        self.policy = policy
        self.allowed_lateness = int(allowed_lateness)
        self._open: Dict[int, list] = {}   # pane index -> column chunks
        self._next_pane: Optional[int] = None  # lowest un-closed slot
        # False until a slot ACTUALLY closes (or a restore pins the
        # cursor): before then ``_next_pane`` is only the earliest
        # pane SEEN, and a cross-shard record for an earlier pane is
        # legal — the merged clock has not closed anything yet
        self._sealed = False
        self._late = None  # lazy eventtime.late_dropped counter

    # ------------------------------------------------------------------ #
    def add(self, src, dst, ts, watermark: int = NO_WATERMARK) -> int:
        """Buffer one column chunk, dropping records later than the
        allowance relative to ``watermark`` (the CALLER's merged clock —
        the assembler does not own a tracker, so shard-merge policy
        stays in one place). Returns the number of late-dropped
        records."""
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        ts = np.asarray(ts, np.int64)
        if not (len(src) == len(dst) == len(ts)):
            raise ValueError(
                f"src/dst/ts column lengths disagree: "
                f"{len(src)}/{len(dst)}/{len(ts)}"
            )
        if len(src) == 0:
            return 0
        dropped = 0
        if watermark != NO_WATERMARK:
            horizon = watermark - self.allowed_lateness
            # a record is late when its PANE already closed: panes
            # close at end + lateness <= watermark, i.e. every ts with
            # pane_end <= horizon is late
            pane_end = (self.policy.pane_of(ts) + 1) * self.policy.slide
            late = pane_end <= horizon
            dropped = int(late.sum())
            if dropped:
                if self._late is None:
                    self._late = get_registry().counter(
                        "eventtime.late_dropped"
                    )
                self._late.inc(dropped)
                keep = ~late
                src, dst, ts = src[keep], dst[keep], ts[keep]
                if len(src) == 0:
                    return dropped
        panes = self.policy.pane_of(ts)
        if self._next_pane is not None and self._sealed:
            # a record whose pane ALREADY closed is late regardless of
            # the allowance arithmetic (its close consumed the slot) —
            # absorbing it would corrupt the retraction multiset
            closed = panes < self._next_pane
            n_closed = int(closed.sum())
            if n_closed:
                dropped += n_closed
                if self._late is None:
                    self._late = get_registry().counter(
                        "eventtime.late_dropped"
                    )
                self._late.inc(n_closed)
                keep = ~closed
                src, dst, ts = src[keep], dst[keep], ts[keep]
                panes = panes[keep]
                if len(src) == 0:
                    return dropped
        lo = int(panes.min())
        if self._next_pane is None or (not self._sealed
                                       and lo < self._next_pane):
            self._next_pane = lo
        order = np.argsort(panes, kind="stable")
        sp, ss, sd, st = panes[order], src[order], dst[order], ts[order]
        bounds = np.nonzero(np.diff(sp))[0] + 1
        starts = np.concatenate([[0], bounds])
        ends = np.concatenate([bounds, [len(sp)]])
        for a, b in zip(starts.tolist(), ends.tolist()):
            p = int(sp[a])
            self._open.setdefault(p, []).append(
                (ss[a:b], sd[a:b], st[a:b])
            )
        return dropped

    # ------------------------------------------------------------------ #
    def advance(self, watermark: int) -> List[Pane]:
        """Close every pane the watermark (minus the lateness
        allowance) has passed, in index order, empty slots included."""
        if watermark == NO_WATERMARK or self._next_pane is None:
            return []
        horizon = watermark - self.allowed_lateness
        out: List[Pane] = []
        while (self._next_pane + 1) * self.policy.slide <= horizon:
            out.append(self._close(self._next_pane))
            self._next_pane += 1
        return out

    def flush(self) -> List[Pane]:
        """Close everything left, in index order (end of stream)."""
        if self._next_pane is None:
            return []
        out: List[Pane] = []
        while self._open:
            out.append(self._close(self._next_pane))
            self._next_pane += 1
        return out

    def _close(self, p: int) -> Pane:
        self._sealed = True
        chunks = self._open.pop(p, None)
        slide = self.policy.slide
        if not chunks:
            z = np.zeros(0, np.int64)
            return Pane(p, p * slide, (p + 1) * slide, z, z, z)
        if len(chunks) == 1:
            s, d, t = chunks[0]
        else:
            s = np.concatenate([c[0] for c in chunks])
            d = np.concatenate([c[1] for c in chunks])
            t = np.concatenate([c[2] for c in chunks])
        return Pane(p, p * slide, (p + 1) * slide, s, d, t)

    # ------------------------------------------------------------------ #
    # Checkpoint surface
    # ------------------------------------------------------------------ #
    def state_dict(self) -> dict:
        return {
            "next_pane": self._next_pane,
            "sealed": self._sealed,
            "open": {
                int(p): [
                    (c[0].copy(), c[1].copy(), c[2].copy())
                    for c in chunks
                ]
                for p, chunks in self._open.items()
            },
        }

    def load_state_dict(self, state: dict) -> None:
        self._next_pane = (
            None if state["next_pane"] is None else int(state["next_pane"])
        )
        self._sealed = bool(state.get("sealed", self._next_pane is not None))
        self._open = {
            int(p): [
                (np.asarray(c[0], np.int64), np.asarray(c[1], np.int64),
                 np.asarray(c[2], np.int64))
                for c in chunks
            ]
            for p, chunks in state["open"].items()
        }
